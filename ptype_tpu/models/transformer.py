"""Decoder-only transformer, TPU-first.

The reference framework ships no model (SURVEY.md §2: "no ML code"); the
optimus example's worker compute was ``Prime.Check``'s simulated 250 ms
scan (example/optimus/prime.go:15-25). This module supplies the real
compute the north star demands — "optimus trains a 125M-param transformer"
(BASELINE.json) — designed for the MXU and XLA, not translated from
anything:

- **Scan over layers.** All blocks' parameters are stacked on a leading
  layer dim and the body is ``lax.scan``-ed: one compiled layer body
  regardless of depth (compile time O(1) in layers, XLA-friendly static
  control flow).
- **bf16 compute, f32 params.** Matmuls run in bfloat16 on the MXU;
  parameters and the softmax/logit paths stay f32 for stability.
- **RMSNorm + RoPE + SwiGLU + GQA** — one architecture covers the
  125M optimus preset and the Llama-3-8B FSDP baseline config.
- **Sharding by annotation.** :func:`param_specs` returns a PartitionSpec
  pytree (fsdp/model axes); the train layer jits with those shardings and
  GSPMD inserts the collectives (ICI-mapped; scaling-book recipe).
- **Remat.** ``cfg.remat`` wraps the block body in ``jax.checkpoint`` to
  trade FLOPs for HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ptype_tpu.parallel.topology import DATA_AXIS


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    #: KV heads for grouped-query attention; None → MHA (== n_heads).
    n_kv_heads: int | None = None
    #: SwiGLU hidden size (LLaMA sizing ≈ 8/3 · d_model, MXU-aligned).
    d_ff: int = 2048
    max_seq: int = 1024
    rope_theta: float = 10000.0
    #: Compute dtype for MXU matmuls; params stay in param_dtype.
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    #: Tie the LM head to the token embedding (GPT-2-style).
    tie_embeddings: bool = True
    #: Rematerialize each block in backward (jax.checkpoint).
    remat: bool = False
    #: jax.checkpoint policy when ``remat``: "none" saves nothing
    #: (recompute everything), "dots" saves matmul outputs but
    #: recomputes the cheap elementwise chains (norms, RoPE, SwiGLU
    #: products) — the usual HBM-vs-FLOPs middle ground.
    remat_policy: str = "none"
    #: ``lax.scan`` unroll factor for the layer stack. Measured on v5e
    #: at 125M: unroll>1 is ~25% SLOWER (0.33 vs 0.45 MFU — the
    #: unrolled body loses the loop-level overlap scheduling), so the
    #: default stays 1; the knob exists because the tradeoff flips with
    #: model size and backend generation.
    scan_unroll: int = 1
    #: Causal (decoder) vs. bidirectional (encoder/BERT) attention.
    causal: bool = True
    #: Attention lowering, resolved by :func:`resolve_attn_fn`:
    #: "auto" (flash on TPU, xla elsewhere), "xla" (compiler-fused dense),
    #: "flash" (Pallas kernel, ops/flash_attention.py), "ring" / "ulysses"
    #: (sequence-parallel over the "seq" mesh axis — these need a mesh, so
    #: the Trainer resolves them; see parallel/ring_attention.py).
    attn_impl: str = "auto"
    #: Mixture-of-experts: number of experts per MLP (0 = dense). The
    #: expert dim shards over the "expert" mesh axis (EP — the
    #: all_to_all family, SURVEY.md §2 parallelism table).
    n_experts: int = 0
    #: Experts routed per token (top-k, GShard-style).
    expert_top_k: int = 2
    #: Expert capacity = ceil(top_k · tokens/expert · this factor);
    #: overflow tokens fall back to the residual stream (dropped).
    capacity_factor: float = 1.25
    #: Coefficient of the router load-balancing aux loss.
    moe_aux_coef: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


#: Named presets for the BASELINE.json configs. "tiny" is the test-size
#: model every CPU-mesh test uses.
PRESETS: dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq=128,
    ),
    # ≈110M params. 6 heads × 128 head_dim (not GPT-2's 12 × 64): same
    # d_model/params/FLOPs, but 128-wide heads fill the MXU contraction
    # and the 128-lane tile — Dh=64 tensors pad 2× in HBM and ran the
    # flash kernel 1.5× slower (measured on v5e).
    "optimus-125m": TransformerConfig(n_heads=6),
    "optimus-350m": TransformerConfig(
        d_model=1024, n_layers=24, n_heads=8, d_ff=2816,
    ),
    # Encoder config for the async param-server baseline ("BERT-base async
    # param-server mode", BASELINE.json configs) — bidirectional attention,
    # MLM-style masked loss via loss_mask.
    "bert-base": TransformerConfig(
        vocab_size=30592, d_model=768, n_layers=12, n_heads=12, d_ff=3072,
        max_seq=512, causal=False, tie_embeddings=True,
    ),
    "llama-3-8b": TransformerConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq=8192, rope_theta=500000.0,
        tie_embeddings=False, remat=True,
    ),
    # Mixture-of-experts variant of the optimus config — 8 experts,
    # top-2 routing; the EP baseline (expert dim over the "expert" axis).
    "optimus-moe": TransformerConfig(
        d_ff=1024, n_experts=8, expert_top_k=2,
    ),
    "tiny-moe": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=64,
        max_seq=128, n_experts=4, expert_top_k=2,
    ),
}


def preset(name: str, **overrides) -> TransformerConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return replace(PRESETS[name], **overrides)


# ------------------------------------------------------------------ params


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Initialize the stacked-parameter pytree.

    Block params carry a leading ``n_layers`` dim — the scan axis. Weight
    init: truncated-normal-free simple scaled normals (0.02 embed / GPT
    residual scaling on the out-projections).
    """
    L, D, H, K = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.kv_heads
    Dh, F, V = cfg.head_dim, cfg.d_ff, cfg.vocab_size
    pd = cfg.param_dtype
    keys = jax.random.split(rng, 9)

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, pd) * scale).astype(pd)

    resid_scale = 0.02 / jnp.sqrt(2.0 * L)
    E = cfg.n_experts
    if E:
        mlp = {
            "mlp_norm": jnp.ones((L, D), pd),
            "router": norm(keys[8], (L, D, E), 0.02),
            "w_gate": norm(keys[5], (L, E, D, F), 0.02),
            "w_up": norm(keys[6], (L, E, D, F), 0.02),
            "w_down": norm(keys[7], (L, E, F, D), resid_scale),
        }
    else:
        mlp = {
            "mlp_norm": jnp.ones((L, D), pd),
            "w_gate": norm(keys[5], (L, D, F), 0.02),
            "w_up": norm(keys[6], (L, D, F), 0.02),
            "w_down": norm(keys[7], (L, F, D), resid_scale),
        }
    params = {
        "embed": norm(keys[0], (V, D), 0.02),
        "blocks": {
            "attn_norm": jnp.ones((L, D), pd),
            "wq": norm(keys[1], (L, D, H, Dh), 0.02),
            "wk": norm(keys[2], (L, D, K, Dh), 0.02),
            "wv": norm(keys[3], (L, D, K, Dh), 0.02),
            "wo": norm(keys[4], (L, H, Dh, D), resid_scale),
            **mlp,
        },
        "final_norm": jnp.ones((D,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(jax.random.split(keys[0])[0], (D, V), 0.02)
    return params


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: TransformerConfig, seq_len: int,
                    n_params: int | None = None) -> float:
    """Fwd+bwd training FLOPs per token (PaLM appendix B convention):
    ``6·N_matmul + 12·L·D·S`` — the MFU denominator."""
    if n_params is None:
        # ACTIVE matmul params only (norms excluded — negligible; for
        # MoE, the top-k routed experts count, not the full bank).
        L, D = cfg.n_layers, cfg.d_model
        H, K, Dh, F = cfg.n_heads, cfg.kv_heads, cfg.head_dim, cfg.d_ff
        if cfg.n_experts:
            mlp = cfg.expert_top_k * 3 * D * F + D * cfg.n_experts
        else:
            mlp = 3 * D * F
        per_layer = D * Dh * (H + 2 * K) + H * Dh * D + mlp
        n_params = cfg.vocab_size * D + L * per_layer
        if not cfg.tie_embeddings:
            n_params += D * cfg.vocab_size
    return 6.0 * n_params + 12.0 * cfg.n_layers * cfg.d_model * seq_len


# ----------------------------------------------------------------- forward


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * scale.astype(x.dtype)


def rope_tables(cfg: TransformerConfig, seq_len: int | None = None,
                positions: jax.Array | None = None):
    """(sin, cos) tables, shape (S, head_dim/2), f32. Pass either a
    ``seq_len`` (positions 0..S-1, the training path) or explicit
    ``positions`` (the decode path, models/generate.py) — one formula
    for both, so RoPE changes can never diverge between them."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    if positions is None:
        positions = jnp.arange(seq_len)
    # Broadcast (not outer, which flattens): positions may be (S,) —
    # shared, the training path — or (B, S) for per-row ragged offsets.
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """Rotate pairs (x1, x2) of the head dim. x: (B, S, H, Dh).

    ``sin``/``cos`` are (S, half) — shared positions, the training
    path — or (B, S, half) for PER-ROW positions (left-padded ragged
    prompts, where row i's column s sits at position s - pad_i)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if sin.ndim == 2:
        sin = sin[None, :, None, :]
        cos = cos[None, :, None, :]
    else:
        sin = sin[:, :, None, :]
        cos = cos[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _attention(q, k, v, cfg: TransformerConfig, kv_mask=None):
    """Causal attention; q:(B,S,H,Dh) k,v:(B,S,K,Dh). Softmax in f32.

    GQA-native: query heads are grouped as (K, G) and contracted against
    the K kv heads directly — no ``jnp.repeat`` materializing H-head K/V
    (the memory GQA exists to avoid; VERDICT r2 weak #4).

    ``kv_mask`` (B, S) bool, optional: keys where False are masked out
    for every query — the left-pad validity mask of ragged-prompt
    prefill (models/generate.py). Training never passes it."""
    B, S, H, Dh = q.shape
    K = k.shape[2]
    qg = q.reshape(B, S, K, H // K, Dh)
    scores = jnp.einsum("bqngd,bsnd->bngqs", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    if cfg.causal:
        causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
        scores = jnp.where(causal[None, None, None], scores,
                           jnp.float32(-1e30))
    if kv_mask is not None:
        scores = jnp.where(kv_mask[:, None, None, None, :], scores,
                           jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bngqs,bsnd->bqngd", probs, v)
    return o.reshape(B, S, H, Dh)


def default_attn_impl() -> str:
    """THE 'auto' policy, in one place (resolve_attn_fn, the Ulysses
    inner default, and prefill's gate all consult it — hand-copied
    backend checks drift): flash kernel on TPU, XLA dense elsewhere."""
    return "flash" if jax.default_backend() == "tpu" else "xla"


def resolve_attn_fn(cfg: TransformerConfig, mesh=None):
    """Resolve ``cfg.attn_impl`` to a concrete ``attn_fn(q, k, v, cfg)``.

    "auto" picks the Pallas flash kernel on TPU backends (the dense path
    materializes B·H·S² f32 scores — the thing that kills the ≥30% MFU
    target) and the XLA-fused dense path elsewhere. "ring"/"ulysses"
    need a mesh with a "seq" axis; the Trainer passes its mesh, and a
    bare ``forward`` call raises a clear error instead of silently
    running dense.
    """
    impl = cfg.attn_impl
    if impl == "auto":
        impl = default_attn_impl()
    if impl == "xla":
        return _attention
    if impl == "flash":
        from ptype_tpu.ops.flash_attention import make_flash_attn_fn

        return make_flash_attn_fn()
    if impl in ("ring", "ulysses"):
        if mesh is None:
            raise ValueError(
                f"attn_impl={impl!r} needs a mesh with a 'seq' axis — "
                "use the Trainer (which passes its mesh) or pass attn_fn "
                "explicitly (parallel/ring_attention.py)"
            )
        from ptype_tpu.parallel.ring_attention import (
            make_ring_attention, make_ulysses_attention)

        make = (make_ring_attention if impl == "ring"
                else make_ulysses_attention)
        return make(mesh)
    raise ValueError(f"unknown attn_impl {impl!r}; "
                     "want auto|xla|flash|ring|ulysses")


def _moe_mlp(h, layer, cfg: TransformerConfig, capacity: int | None = None):
    """GShard-style top-k MoE MLP. h: (B, S, D) → (y, aux_loss).

    Einsum dispatch with static expert capacity: tokens scatter into an
    (E, C, D) buffer, the expert SwiGLUs run as one batched einsum over
    the stacked expert weights (expert dim shardable over the "expert"
    mesh axis — GSPMD lowers the dispatch to all_to_all), and outputs
    gather back weighted by the router. Overflow past capacity falls
    back to the residual stream. ``capacity`` overrides the
    capacity_factor formula — decode passes the exact per-step token
    count so single-token steps never drop (generate.py).
    """
    B, S, D = h.shape
    E, topk = cfg.n_experts, cfg.expert_top_k
    dt = cfg.dtype
    T = B * S
    x = h.reshape(T, D)

    logits = jnp.einsum(
        "td,de->te", x.astype(jnp.float32),
        layer["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    gate_w, gate_e = jax.lax.top_k(probs, topk)  # (T, k)
    gate_w = gate_w / jnp.maximum(
        jnp.sum(gate_w, axis=-1, keepdims=True), 1e-9)

    # Load-balancing aux (Switch eq. 4): E · Σ_e frac_tokens · frac_prob.
    me = jnp.mean(probs, axis=0)
    dispatched = jnp.sum(jax.nn.one_hot(gate_e, E, dtype=jnp.float32),
                        axis=1)  # (T, E)
    ce = jnp.mean(dispatched, axis=0) / topk
    aux = E * jnp.sum(me * ce)

    import math as _math

    C = (capacity if capacity is not None
         else max(_math.ceil(topk * T / E * cfg.capacity_factor), 1))
    flat_e = gate_e.reshape(-1)  # (T·k,)
    # Position within each expert, token-priority order.
    counts = jnp.cumsum(jax.nn.one_hot(flat_e, E, dtype=jnp.int32), axis=0)
    pos = counts[jnp.arange(T * topk), flat_e] - 1
    keep = pos < C
    tok = jnp.arange(T * topk) // topk

    # Dispatch via the INVERSE index map: scatter each kept assignment's
    # token id (a single i32) into its (expert, slot) cell — (e, slot)
    # pairs are unique for kept entries and overflow rides slot=C,
    # dropped by mode="drop" — then GATHER token rows into the (E, C, D)
    # buffer. Scattering the D-wide activation rows instead
    # (``.at[e, slot].add(x[tok])``, the previous lowering) ran 22×
    # slower on v5e (102 ms vs 4.7 ms fwd+bwd at T=16k, D=768: TPU
    # scatter serializes; gather vectorizes).
    slot_oob = jnp.where(keep, pos, C)
    inv = jnp.zeros((E, C), jnp.int32).at[flat_e, slot_oob].set(
        tok + 1, mode="drop", unique_indices=True)  # 0 = empty slot
    X = jnp.where((inv > 0)[..., None],
                  x[jnp.maximum(inv - 1, 0)].astype(dt), 0)
    slot = jnp.clip(pos, 0, C - 1)

    g = jnp.einsum("ecd,edf->ecf", X, layer["w_gate"].astype(dt))
    u = jnp.einsum("ecd,edf->ecf", X, layer["w_up"].astype(dt))
    Y = jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u,
                   layer["w_down"].astype(dt))

    y_tok = Y[flat_e, slot] * keep[:, None].astype(dt)
    y_tok = y_tok * gate_w.reshape(-1)[:, None].astype(dt)
    y = jnp.sum(y_tok.reshape(T, topk, D), axis=1)
    return y.reshape(B, S, D), aux


def qkv_proj(x, layer, cfg: TransformerConfig, sin, cos):
    """Pre-norm + Q/K/V projections + RoPE. x: (B, S, D) → three
    (B, S, H|K, Dh). Shared by training forward and the KV-cache
    prefill/decode paths (models/generate.py) — the block math lives
    here once."""
    dt = cfg.dtype
    h = rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
    return apply_rope(q, sin, cos), apply_rope(k, sin, cos), v


def attn_residual(x, o, layer, cfg: TransformerConfig):
    """Output projection + residual add. o: (B, S, H, Dh)."""
    return x + jnp.einsum("bshk,hkd->bsd", o,
                          layer["wo"].astype(cfg.dtype))


def mlp_residual(x, layer, cfg: TransformerConfig,
                 moe_capacity: int | None = None):
    """Pre-norm MLP (dense SwiGLU or MoE) + residual. → (x, aux)."""
    dt = cfg.dtype
    h = rms_norm(x, layer["mlp_norm"])
    if cfg.n_experts:
        y, aux = _moe_mlp(h, layer, cfg, capacity=moe_capacity)
        return x + y, aux
    gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
    x = x + jnp.einsum(
        "bsf,fd->bsd", jax.nn.silu(gate) * up, layer["w_down"].astype(dt)
    )
    return x, jnp.float32(0.0)


def _block(x, layer, sin, cos, cfg: TransformerConfig, attn_fn):
    """One transformer block; x: (B, S, D) in compute dtype.
    Returns (x, moe_aux) — aux is 0.0 for dense MLPs."""
    q, k, v = qkv_proj(x, layer, cfg, sin, cos)
    o = attn_fn(q, k, v, cfg)
    x = attn_residual(x, o, layer, cfg)
    return mlp_residual(x, layer, cfg)


def hidden_with_aux(params: dict, tokens: jax.Array,
                    cfg: TransformerConfig, attn_fn=None):
    """Backbone up to (and including) the final norm: (x (B,S,D) in
    compute dtype, aux). The LM head is applied by the caller — either
    densely (:func:`forward_with_aux`) or fused with the loss
    (:func:`loss_terms`) so the (B,S,V) f32 logits never materialize."""
    attn_fn = attn_fn or resolve_attn_fn(cfg)
    B, S = tokens.shape
    dt = cfg.dtype
    x = params["embed"][tokens].astype(dt)
    sin, cos = rope_tables(cfg, S)

    def body(x, layer):
        x, aux = _block(x, layer, sin, cos, cfg, attn_fn)
        return x, aux

    if cfg.remat:
        policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                  if cfg.remat_policy == "dots" else None)
        body = jax.checkpoint(body, policy=policy)
    x, auxs = lax.scan(body, x, params["blocks"], unroll=cfg.scan_unroll)
    return rms_norm(x, params["final_norm"]), jnp.sum(auxs)


def _head_weight(params: dict, cfg: TransformerConfig) -> jax.Array:
    return (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"])


def head_logits(x: jax.Array, head: jax.Array,
                cfg: TransformerConfig) -> jax.Array:
    """LM head matmul: bf16 operands, f32 MXU accumulation.

    Casting both operands to f32 (the previous lowering) ran the
    largest matmul in the model at half MXU rate (VERDICT r2 weak #7);
    ``preferred_element_type`` keeps the f32 accumulator — and the f32
    logits the softmax needs — with bf16 inputs."""
    return jnp.einsum("...d,dv->...v", x.astype(cfg.dtype),
                      head.astype(cfg.dtype),
                      preferred_element_type=jnp.float32)


def forward_with_aux(params: dict, tokens: jax.Array,
                     cfg: TransformerConfig, attn_fn=None):
    """(logits (B,S,V) f32, aux) — aux is the summed MoE router
    load-balancing loss (0.0 for dense configs)."""
    x, aux = hidden_with_aux(params, tokens, cfg, attn_fn)
    return head_logits(x, _head_weight(params, cfg), cfg), aux


def forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            attn_fn=None) -> jax.Array:
    """Logits (B, S, V) in f32. ``attn_fn`` overrides the attention
    implementation (ring attention injects itself here)."""
    return forward_with_aux(params, tokens, cfg, attn_fn)[0]


def nll_terms_from_logits(logits: jax.Array, batch: dict):
    """(nll_sum, denom) — the unnormalized pieces of the (masked) mean
    cross-entropy. Gradient accumulation sums these across microbatches
    and divides ONCE, so the loss (and its grads) are invariant to the
    accumulation factor even when valid-token counts differ per
    microbatch."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, batch["targets"][..., None], axis=-1
    )[..., 0]
    nll = logz - gold
    mask = batch.get("loss_mask")
    if mask is None:
        return jnp.sum(nll), jnp.float32(nll.size)
    mask = mask.astype(nll.dtype)
    return jnp.sum(nll * mask), jnp.maximum(jnp.sum(mask), 1.0)


def nll_from_logits(logits: jax.Array, batch: dict) -> jax.Array:
    """(Masked) mean cross-entropy from precomputed logits — shared by
    the dense forward, the pipelined forward, and eval paths."""
    nll_sum, denom = nll_terms_from_logits(logits, batch)
    return nll_sum / denom


#: Rows of (tokens × vocab) logits materialized at once by the fused
#: loss head. 8192 × 32k vocab f32 ≈ 1 GB of transient per chunk — big
#: enough to keep the MXU fed, small enough that the full (B·S, V)
#: tensor (4.3 GB at batch 32 / seq 1024) never exists.
LOSS_CHUNK_ROWS = 8192


def _chunked_nll(x, head, targets, mask, cfg: TransformerConfig):
    """(nll_sum, denom) with the head matmul fused into the loss.

    The dense path materializes (B, S, V) f32 logits — at the bench's
    32-per-chip batch that is 4.3 GB and was the HBM wall that forced
    the ladder down to batch 16. Here rows stream through a
    ``lax.scan`` in :data:`LOSS_CHUNK_ROWS` chunks; each chunk's body is
    rematerialized (``jax.checkpoint``) so backward recomputes the
    chunk logits instead of saving them — saved residuals shrink from
    O(B·S·V) to O(B·S·D).
    """
    B, S, D = x.shape
    n = B * S
    x = x.reshape(n, D)
    targets = targets.reshape(n)
    mask = None if mask is None else mask.reshape(n).astype(jnp.float32)

    # Largest divisor of n that fits the chunk budget — NOT just "n if
    # it doesn't divide evenly": global batch 12 × seq 1024 (n=12288)
    # must chunk at 6144, not fall back to one 1.6 GB dense chunk.
    chunk = min(n, LOSS_CHUNK_ROWS)
    while n % chunk:
        chunk -= 1
    if chunk < 512:  # pathological n (odd/prime): dense beats 1-row scan
        chunk = n
    xc = x.reshape(n // chunk, chunk, D)
    tc = targets.reshape(n // chunk, chunk)
    mc = (jnp.ones((n // chunk, chunk), jnp.float32) if mask is None
          else mask.reshape(n // chunk, chunk))

    @jax.checkpoint
    def body(carry, xs):
        nll_sum, denom = carry
        xr, tr, mr = xs
        logits = head_logits(xr, head, cfg)  # (chunk, V) f32
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tr[:, None], axis=-1)[:, 0]
        nll = (logz - gold) * mr
        return (nll_sum + jnp.sum(nll), denom + jnp.sum(mr)), None

    (nll_sum, denom), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (xc, tc, mc))
    return nll_sum, jnp.maximum(denom, 1.0)


def loss_terms(params: dict, batch: dict, cfg: TransformerConfig,
               attn_fn=None):
    """(nll_sum, denom, aux) — loss pieces for gradient accumulation
    (train/trainer.py sums across microbatches, normalizes once). The
    LM head runs fused with the cross-entropy (:func:`_chunked_nll`):
    full logits are never materialized."""
    x, aux = hidden_with_aux(params, batch["tokens"], cfg, attn_fn)
    nll_sum, denom = _chunked_nll(
        x, _head_weight(params, cfg), batch["targets"],
        batch.get("loss_mask"), cfg)
    return nll_sum, denom, aux


def loss_fn(params: dict, batch: dict, cfg: TransformerConfig,
            attn_fn=None) -> jax.Array:
    """Mean next-token cross-entropy (+ MoE router aux when configured).
    ``batch``: tokens (B,S) int32, targets (B,S) int32, optional
    loss_mask (B,S)."""
    nll_sum, denom, aux = loss_terms(params, batch, cfg, attn_fn)
    loss = nll_sum / denom
    if cfg.n_experts:
        loss = loss + cfg.moe_aux_coef * aux
    return loss


# ---------------------------------------------------------------- sharding


def _maybe(axis: str | None, size: int, axis_sizes: dict[str, int]):
    """Use the axis in a spec only if present and it divides ``size`` —
    strategies degrade to replication when an axis is absent
    (mesh.py axis conventions)."""
    if axis is None or axis not in axis_sizes:
        return None
    return axis if size % axis_sizes[axis] == 0 else None


def param_specs(cfg: TransformerConfig,
                axis_sizes: dict[str, int]) -> dict:
    """PartitionSpec pytree matching :func:`init_params`.

    Conventions (scaling-book layout): ``model`` (TP) shards head and ff
    dims — megatron-style column/row pairing so each block needs exactly
    one psum on each residual write; ``fsdp`` shards the d_model dim of
    every matmul weight (ZeRO-3-style, allgathered by GSPMD per layer).
    Block specs carry a leading None for the scan/layer dim.
    """
    D, F, V = cfg.d_model, cfg.d_ff, cfg.vocab_size
    H, K, E = cfg.n_heads, cfg.kv_heads, cfg.n_experts
    fsdp = partial(_maybe, "fsdp", axis_sizes=axis_sizes)
    tp = partial(_maybe, "model", axis_sizes=axis_sizes)
    ep = partial(_maybe, "expert", axis_sizes=axis_sizes)
    if E:
        mlp_specs = {
            "mlp_norm": P(None, None),
            "router": P(None, fsdp(D), None),
            "w_gate": P(None, ep(E), fsdp(D), tp(F)),
            "w_up": P(None, ep(E), fsdp(D), tp(F)),
            "w_down": P(None, ep(E), tp(F), fsdp(D)),
        }
    else:
        mlp_specs = {
            "mlp_norm": P(None, None),
            "w_gate": P(None, fsdp(D), tp(F)),
            "w_up": P(None, fsdp(D), tp(F)),
            "w_down": P(None, tp(F), fsdp(D)),
        }
    specs = {
        "embed": P(tp(V), fsdp(D)),
        "blocks": {
            "attn_norm": P(None, None),
            "wq": P(None, fsdp(D), tp(H), None),
            "wk": P(None, fsdp(D), tp(K), None),
            "wv": P(None, fsdp(D), tp(K), None),
            "wo": P(None, tp(H), None, fsdp(D)),
            **mlp_specs,
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(fsdp(D), tp(V))
    return specs


def batch_spec(axis_sizes: dict[str, int], seq_axis: bool = False) -> P:
    """Token batch sharding: batch dim over every data-like axis present
    (data + fsdp both act as data for activations); optionally the seq
    dim over ``seq`` (ring attention)."""
    batch_axes = tuple(a for a in (DATA_AXIS, "fsdp")
                       if a in axis_sizes)
    first = batch_axes if batch_axes else None
    second = "seq" if (seq_axis and "seq" in axis_sizes) else None
    return P(first, second)
