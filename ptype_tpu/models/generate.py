"""Autoregressive generation — static-shape KV-cache decode.

The reference served request/reply actors (calculator.go); the model
framework's equivalent of "serve a request" is generate-from-prompt.
TPU-first decisions:

- **Static shapes everywhere**: the KV cache is allocated at
  ``max_seq`` up front; the decode loop is a ``lax.scan`` over step
  index with ``dynamic_update_slice`` writes — one compiled program
  regardless of prompt/output length, no retracing.
- **Prefill + decode split**: prefill runs the full-sequence forward
  (MXU-efficient batched matmuls) while collecting per-layer K/V;
  decode steps attend against the cache with a position mask.
- Sampling: greedy or temperature with top-k / top-p (nucleus,
  temperature-first semantics), HF-style repetition penalty, and
  stop-token early stopping (output-masked outside the compiled
  program); RNG is explicit (fold_in per step).
- Ragged serving: ``generate(prompt_lens=...)`` decodes a LEFT-padded
  mixed-length batch in one compiled program — lengths are traced,
  pad keys masked, RoPE offsets per row; greedy rows match their solo
  decode exactly.

Works for any dense ``TransformerConfig`` (MoE generation uses
zero-drop expert capacity — dropping is a training regularizer). GQA
caches only ``kv_heads`` heads.
"""

from __future__ import annotations

from dataclasses import dataclass

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ptype_tpu.models import transformer as tfm


@dataclass(frozen=True)
class KVCache:
    """Stacked per-layer KV: (L, B, Smax, Kh, Dh)."""

    k: jax.Array
    v: jax.Array

    def tree_flatten(self):
        return (self.k, self.v), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten
)


def init_cache(cfg: tfm.TransformerConfig, batch: int,
               max_seq: int | None = None) -> KVCache:
    S = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, S, cfg.kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _cached_attention(q, k_cache, v_cache, pos_limit, cfg,
                      valid_from=None):
    """q: (B, 1, H, Dh); caches: (B, Smax, Kh, Dh); attend to
    positions < pos_limit. GQA-native: query heads are grouped onto
    their kv head inside the einsum — no ``jnp.repeat``
    materializing H-head caches every decode step (the G=1 MHA case
    is the same einsum).

    ``valid_from`` (B,), optional: per-row first valid cache slot —
    left-padded ragged prompts leave pad rows in slots
    [0, valid_from); they stay masked for the row's whole decode.

    ``pos_limit`` may be a scalar (uniform batch) or (B,) — per-row
    limits are the continuous-batching case, where every slot is at
    its own depth."""
    B, _, H, Dh = q.shape
    Kh = k_cache.shape[2]
    G = H // Kh
    qg = q.reshape(B, 1, Kh, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                        k_cache).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    cols = jnp.arange(k_cache.shape[1])  # (Smax,)
    pos_limit = jnp.asarray(pos_limit)
    if pos_limit.ndim == 1:
        mask = cols[None, :] < pos_limit[:, None]  # (B, Smax)
    else:
        mask = (cols < pos_limit)[None, :]
    if valid_from is not None:
        mask = mask & (cols[None, :] >= valid_from[:, None])
    scores = jnp.where(mask[:, None, None, None, :], scores,
                       jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bkgqs,bskd->bqkgd", probs, v_cache)
    return o.reshape(B, 1, H, Dh)


def _head_logits(params, x_last, cfg):
    # One LM-head lowering for train and decode: bf16 operands with f32
    # MXU accumulation (transformer.head_logits), so precision policy
    # can never drift between the two paths.
    return tfm.head_logits(x_last, tfm._head_weight(params, cfg), cfg)


def prefill(params: dict, tokens: jax.Array, cfg: tfm.TransformerConfig,
            cache: KVCache,
            prompt_lens: jax.Array | None = None,
            last_index: jax.Array | None = None
            ) -> tuple[jax.Array, KVCache]:
    """Full-sequence forward, filling cache[:, :, :S]. Returns
    (last-position logits (B, V), cache). Block math is the shared
    transformer pieces (qkv_proj/attn_residual/mlp_residual), so
    training and generation can never diverge.

    ``prompt_lens`` (B,), optional: tokens are LEFT-padded — row i's
    real prompt occupies columns [S - L_i, S). RoPE positions shift
    per row so every prompt starts at position 0, pad keys are masked
    out of attention, and the last column is every row's final real
    token (which is why left-padding is the serving layout).

    ``last_index`` (B,), optional: return logits at these columns
    instead of the last — the RIGHT-padded layout continuous batching
    prefills slots with (each slot's prompt occupies [0, L_i), so its
    final real token sits at column L_i - 1, and decode writes grow
    from L_i, overwriting the never-attended pad garbage)."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    if prompt_lens is None:
        sin, cos = tfm.rope_tables(cfg, S)
        kv_mask = None
    else:
        pad = S - prompt_lens  # (B,)
        positions = jnp.maximum(
            jnp.arange(S)[None, :] - pad[:, None], 0)
        sin, cos = tfm.rope_tables(cfg, positions=positions)
        kv_mask = jnp.arange(S)[None, :] >= pad[:, None]  # (B, S)

    # MoE: generation prefill always uses ZERO-DROP expert capacity
    # (per-expert bound = T, since each token routes to top_k DISTINCT
    # experts — the same reasoning behind decode_step's capacity=B).
    # Factor-capacity dropping is a TRAINING regularizer; at inference
    # it would (a) silently degrade prompts whose routing concentrates
    # and (b) break batched-equals-solo parity — batch composition
    # would change which tokens drop (left-pad columns, coming first,
    # would even outrank real tokens in token-priority order).
    cap = B * S if cfg.n_experts else None

    # Uniform causal prefill is ordinary full-sequence attention: use
    # the flash kernel when the resolved impl says so (auto → flash on
    # TPU; explicit "flash" also forces the interpret-mode kernel on
    # CPU for tests) — dense prefill pays B·H·S² f32 scores exactly
    # where long-prompt serving hurts. Ragged (kv_mask) prompts keep
    # the masked dense path (the kernel has no kv-mask support), and
    # so do UNALIGNED lengths: S must be lane-aligned (128) or Mosaic
    # rejects the block at compile time (the round-2 hardware failure
    # class — serving buckets are pow2, so real callers qualify), and
    # divide the clamped block size.
    impl = cfg.attn_impl
    if impl == "auto":
        impl = tfm.default_attn_impl()
    use_flash = (kv_mask is None and cfg.causal and impl == "flash"
                 and S % 128 == 0 and S % min(1024, S) == 0)
    if use_flash:
        from ptype_tpu.ops.flash_attention import flash_attention

        def attn(q, k, v):
            return flash_attention(q, k, v, causal=True)
    else:
        def attn(q, k, v):
            return tfm._attention(q, k, v, cfg, kv_mask=kv_mask)

    def body(x, inputs):
        layer, kc, vc = inputs
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        o = attn(q, k, v)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=cap)
        kc = lax.dynamic_update_slice(kc, k, (0, 0, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, 0, 0, 0))
        return x, (kc, vc)

    x, (kcs, vcs) = lax.scan(body, x,
                             (params["blocks"], cache.k, cache.v))
    x = tfm.rms_norm(x, params["final_norm"])
    x_last = (x[:, -1] if last_index is None
              else x[jnp.arange(B), last_index])
    return _head_logits(params, x_last, cfg), KVCache(kcs, vcs)


def decode_step(params: dict, token: jax.Array, pos: jax.Array,
                cfg: tfm.TransformerConfig, cache: KVCache,
                rope_pos: jax.Array | None = None,
                valid_from: jax.Array | None = None
                ) -> tuple[jax.Array, KVCache]:
    """One decode step. token: (B,) int32 at CACHE slot ``pos``
    (scalar). Returns (logits (B, V), updated cache). MoE capacity is
    pinned to the step's token count (B) so no routed token can drop
    at decode.

    Ragged (left-padded) prompts: ``rope_pos`` (B,) gives each row's
    TOKEN position (cache slot minus its pad) and ``valid_from`` (B,)
    its first real cache slot — slot and position coincide only in the
    uniform-length case."""
    B = token.shape[0]
    x = params["embed"][token][:, None, :].astype(cfg.dtype)  # (B, 1, D)
    if rope_pos is None:
        sin, cos = tfm.rope_tables(cfg, positions=jnp.asarray(pos)[None])
    else:
        sin, cos = tfm.rope_tables(cfg, positions=rope_pos[:, None])

    def body(x, inputs):
        layer, kc, vc = inputs  # kc/vc: (B, Smax, Kh, Dh)
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        kc = lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        o = _cached_attention(q, kc, vc, pos + 1, cfg,
                              valid_from=valid_from)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=B)
        return x, (kc, vc)

    x, (kcs, vcs) = lax.scan(body, x,
                             (params["blocks"], cache.k, cache.v))
    x = tfm.rms_norm(x, params["final_norm"])
    return _head_logits(params, x[:, 0], cfg), KVCache(kcs, vcs)


def _paged_attention_gather(q, kc, vc, tables, pos_limit, cfg):
    """Attention through a block table — the XLA gather path of the
    paged serving engine. q: (B, Q, H, Dh); kc/vc: (n_blocks,
    block_tokens, Kh, Dh) bank layers; tables: (B, nb) int32 block ids
    in POSITION order, so the gathered layout is exactly the
    contiguous cache (garbage in never-written / trash-block columns
    is masked, and masked-out columns contribute exact zeros to the
    softmax sums — greedy rows match the contiguous path bit-for-bit).

    ``pos_limit``: (B,) per-row limits (decode, Q=1) or (B, Q)
    per-query limits (chunked prefill: query c attends positions
    ``<= start + c``). Same grouped-GQA einsums as
    :func:`_cached_attention`."""
    B, Q, H, Dh = q.shape
    nb = tables.shape[1]
    bt = kc.shape[1]
    Kh = kc.shape[2]
    ks = kc[tables].reshape(B, nb * bt, Kh, Dh)
    vs = vc[tables].reshape(B, nb * bt, Kh, Dh)
    G = H // Kh
    qg = q.reshape(B, Q, Kh, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                        ks).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    cols = jnp.arange(nb * bt)
    pos_limit = jnp.asarray(pos_limit)
    if pos_limit.ndim == 1:
        mask = cols[None, None, :] < pos_limit[:, None, None]
    else:  # (B, Q) per-query
        mask = cols[None, None, :] < pos_limit[:, :, None]
    scores = jnp.where(mask[:, None, None, :, :], scores,
                       jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bkgqs,bskd->bqkgd", probs, vs)
    return o.reshape(B, Q, H, Dh)


def decode_step_paged(params: dict, token: jax.Array, pos: jax.Array,
                      cfg: tfm.TransformerConfig, kb: jax.Array,
                      vb: jax.Array, tables: jax.Array,
                      wr_blocks: jax.Array, wr_off: jax.Array,
                      attn_impl: str = "gather",
                      interpret: bool | None = None
                      ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step through per-sequence BLOCK TABLES — the paged
    engine step (serve_engine.PagedGeneratorActor). ``kb``/``vb``:
    ``(L, n_blocks, block_tokens, Kh, Dh)`` banks shared by every
    sequence; ``tables`` (B, nb) maps each row's positions onto bank
    blocks. Each row writes its new K/V at ``(wr_blocks[b],
    wr_off[b])`` — the engine routes INACTIVE rows to the trash block
    so a masked lane can never scatter into a real (possibly shared)
    block — and attends through its table: position order == table
    order, so greedy rows match the solo :func:`generate` decode
    token-for-token (the engine's parity bar).

    ``attn_impl="kernel"`` uses the Pallas paged-attention kernel
    (ops/paged_attention, gated behind its ``check_tpu_lowering``);
    the default is the XLA gather path. Returns
    ``(logits (B, V), kb, vb)``."""
    B = token.shape[0]
    x = params["embed"][token][:, None, :].astype(cfg.dtype)
    sin, cos = tfm.rope_tables(cfg, positions=pos[:, None])

    def body(x, inputs):
        layer, kc, vc = inputs  # (n_blocks, block_tokens, Kh, Dh)
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        kc = kc.at[wr_blocks, wr_off].set(k[:, 0])
        vc = vc.at[wr_blocks, wr_off].set(v[:, 0])
        if attn_impl == "kernel":
            from ptype_tpu.ops.paged_attention import paged_attention

            o = paged_attention(q, kc, vc, tables, pos,
                                interpret=interpret)
        else:
            o = _paged_attention_gather(q, kc, vc, tables, pos + 1,
                                        cfg)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=B)
        return x, (kc, vc)

    x, (kb, vb) = lax.scan(body, x, (params["blocks"], kb, vb))
    x = tfm.rms_norm(x, params["final_norm"])
    return _head_logits(params, x[:, 0], cfg), kb, vb


def prefill_paged_chunk(params: dict, tokens: jax.Array,
                        start: jax.Array, length: jax.Array,
                        cfg: tfm.TransformerConfig, kb: jax.Array,
                        vb: jax.Array, table: jax.Array
                        ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One CHUNK of paged prefill for a single sequence — the bounded
    unit chunked admission interleaves with decode steps. ``tokens``
    (1, C): prompt positions ``[start, start + length)`` right-padded
    to the chunk bucket C; ``table`` (nb,) the sequence's block table.
    K/V for real tokens scatter into their blocks (pad columns go to
    the trash block); attention runs per-query-causal against the
    gathered table, i.e. query ``c`` sees every previously-written
    position plus the chunk through itself — mathematically the same
    full causal prefill, split at chunk boundaries. Returns
    ``(logits (1, V) at the chunk's LAST REAL token, kb, vb)`` — only
    the final chunk's logits feed the first sampled token."""
    B, C = tokens.shape
    bt = kb.shape[2]
    nb = table.shape[0]
    x = params["embed"][tokens].astype(cfg.dtype)
    pos_vec = start + jnp.arange(C)  # (C,) positions of chunk columns
    sin, cos = tfm.rope_tables(cfg, positions=pos_vec[None])
    valid = jnp.arange(C) < length
    wr_b = jnp.where(valid, table[jnp.clip(pos_vec // bt, 0, nb - 1)],
                     0)
    wr_o = pos_vec % bt
    # Per-query limits: pad queries attend nothing (their garbage
    # outputs are never read — x_last indexes the last REAL token).
    limits = jnp.where(valid, pos_vec + 1, 0)
    # MoE: zero-drop capacity over the padded chunk (same reasoning as
    # prefill's B*S bound — dropping is a training regularizer).
    cap = C if cfg.n_experts else None

    def body(x, inputs):
        layer, kc, vc = inputs
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        kc = kc.at[wr_b, wr_o].set(k[0])
        vc = vc.at[wr_b, wr_o].set(v[0])
        o = _paged_attention_gather(q, kc, vc, table[None],
                                    limits[None], cfg)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=cap)
        return x, (kc, vc)

    x, (kb, vb) = lax.scan(body, x, (params["blocks"], kb, vb))
    x = tfm.rms_norm(x, params["final_norm"])
    x_last = x[jnp.arange(B), jnp.asarray(length)[None] - 1]
    return _head_logits(params, x_last, cfg), kb, vb


# ------------------------------------------------- speculative decoding

#: RNG domain separators for the speculative path: the draft's
#: proposal draws and the acceptance test's uniforms/residual draws
#: fold these into the row key FIRST, so the three streams (engine
#: sampling, draft sampling, acceptance) can never collide at a shared
#: fold index. Arbitrary constants; changing them changes sampled
#: outputs (never greedy ones).
_DRAFT_FOLD = 0x5bec
_ACCEPT_FOLD = 0xacce


def truncated_draft_params(params: dict, cfg: tfm.TransformerConfig,
                           n_layers: int = 1
                           ) -> tuple[dict, tfm.TransformerConfig]:
    """The shared-prefix-truncated draft: reuse the target's embedding
    / final norm / LM head and its FIRST ``n_layers`` transformer
    blocks as a cheap same-family draft model. Zero extra parameter
    memory (the returned tree aliases the target's arrays — blocks are
    stacked on the scan axis, so truncation is one leading slice).
    Returns ``(draft_params, draft_cfg)`` for
    ``SpecConfig(draft_params=..., draft_cfg=...)``."""
    if not 1 <= n_layers <= cfg.n_layers:
        raise ValueError(
            f"truncated draft needs 1 <= n_layers <= {cfg.n_layers}, "
            f"got {n_layers}")
    from dataclasses import replace

    blocks = jax.tree_util.tree_map(lambda a: a[:n_layers],
                                    params["blocks"])
    return dict(params, blocks=blocks), replace(cfg, n_layers=n_layers)


def verify_step_paged(params: dict, tokens: jax.Array,
                      pos0: jax.Array, cfg: tfm.TransformerConfig,
                      kb: jax.Array, vb: jax.Array, tables: jax.Array,
                      wr_b: jax.Array, wr_o: jax.Array
                      ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Target-model verification of one speculation window in ONE
    batched forward — the speculative-decoding counterpart of
    :func:`decode_step_paged`. ``tokens`` (B, W): each row's last
    committed token followed by its draft proposals, at positions
    ``pos0 + [0..W)``; every position's K/V scatters through the block
    tables (``wr_b``/``wr_o`` (B, W) — the engine routes inactive
    lanes and positions past a row's reserved span to the trash
    block), and query ``j`` attends causally through position
    ``pos0 + j`` via the same ragged per-slot gather path decode uses.
    Returns ``(logits (B, W, V) f32, kb, vb)``: ``logits[:, j]`` is
    the target distribution for the token AT position ``pos0 + j + 1``
    given the prefix through ``tokens[:, j]`` — exactly the logits W
    sequential :func:`decode_step_paged` calls would produce, which is
    what makes greedy speculative acceptance bit-identical to the
    non-speculative engine. Rejected positions need no KV cleanup:
    their writes land inside the row's already-reserved blocks and the
    position-limit mask hides them until a later token overwrites them
    (rollback is a position rewind, never a reallocation)."""
    B, W = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)  # (B, W, D)
    pos = pos0[:, None] + jnp.arange(W)[None, :]   # (B, W)
    sin, cos = tfm.rope_tables(cfg, positions=pos)
    limits = pos + 1  # (B, W): per-query causal limits
    # MoE: zero-drop capacity over the whole window (same reasoning
    # as decode_step's B bound — dropping is a training regularizer).
    cap = B * W if cfg.n_experts else None

    def body(x, inputs):
        layer, kc, vc = inputs
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        kc = kc.at[wr_b, wr_o].set(k)
        vc = vc.at[wr_b, wr_o].set(v)
        o = _paged_attention_gather(q, kc, vc, tables, limits, cfg)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=cap)
        return x, (kc, vc)

    x, (kb, vb) = lax.scan(body, x, (params["blocks"], kb, vb))
    x = tfm.rms_norm(x, params["final_norm"])
    return _head_logits(params, x, cfg), kb, vb


def draft_propose_paged(params: dict, tok: jax.Array,
                        pos0: jax.Array, cfg: tfm.TransformerConfig,
                        kb: jax.Array, vb: jax.Array,
                        tables: jax.Array, wr_b: jax.Array,
                        wr_o: jax.Array, keys: jax.Array,
                        steps0: jax.Array, temps: jax.Array,
                        top_ks: jax.Array, top_ps: jax.Array,
                        n_steps: int, sampled: bool = True
                        ) -> tuple[jax.Array, jax.Array, jax.Array,
                                   jax.Array]:
    """``n_steps`` draft decode steps through the draft model's own
    block tables inside ONE program (a ``lax.scan`` — one dispatch per
    window, not per proposal). Step ``j`` feeds the previous token at
    position ``pos0 + j``, writes its K/V (``wr_b``/``wr_o``
    (B, n_steps), trash-routed like the verify step), and draws the
    next token from the draft distribution: greedy rows take the
    argmax; sampled rows draw from the same filtered/temperature-
    scaled logits the acceptance test will score, with a
    draft-domain-separated key folded at ``steps0 + j`` per row
    (:func:`sample_token_rows` — the one RNG home). The engine runs
    ``n_steps = k + 1``: the last step's K/V write covers the
    all-accepted case (the bonus token's context) and its proposal is
    discarded. Returns ``(proposed (B, n_steps) int32, draft_logits
    (B, n_steps, V) f32 raw, kb, vb)`` — ``proposed[:, j]`` is the
    draft's token for position ``pos0 + j + 1`` and
    ``draft_logits[:, j]`` the logits it was drawn from (acceptance
    recomputes the filtered distribution from these, so q is scored
    exactly as sampled)."""
    B = tok.shape[0]
    dkeys = jax.vmap(
        lambda kk: jax.random.fold_in(kk, _DRAFT_FOLD))(keys)

    def step(carry, inputs):
        tok, kb, vb = carry
        j, wb, wo = inputs
        pos = pos0 + j  # (B,)
        x = params["embed"][tok][:, None, :].astype(cfg.dtype)
        sin, cos = tfm.rope_tables(cfg, positions=pos[:, None])

        def body(x, inp):
            layer, kc, vc = inp
            q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
            kc = kc.at[wb, wo].set(k[:, 0])
            vc = vc.at[wb, wo].set(v[:, 0])
            o = _paged_attention_gather(q, kc, vc, tables, pos + 1,
                                        cfg)
            x = tfm.attn_residual(x, o, layer, cfg)
            x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=B)
            return x, (kc, vc)

        x, (kb, vb) = lax.scan(body, x, (params["blocks"], kb, vb))
        x = tfm.rms_norm(x, params["final_norm"])
        lg = _head_logits(params, x[:, 0], cfg)  # (B, V) f32
        if sampled:
            nxt = sample_token_rows(lg, dkeys, steps0 + j, temps,
                                    top_ks, top_ps)
        else:
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return (nxt, kb, vb), (nxt, lg)

    (_, kb, vb), (toks, lgs) = lax.scan(
        step, (tok, kb, vb),
        (jnp.arange(n_steps), jnp.swapaxes(wr_b, 0, 1),
         jnp.swapaxes(wr_o, 0, 1)))
    return (jnp.swapaxes(toks, 0, 1), jnp.swapaxes(lgs, 0, 1), kb, vb)


def spec_accept_rows(draft_toks: jax.Array, draft_logits: jax.Array,
                     target_logits: jax.Array, keys: jax.Array,
                     steps0: jax.Array, temps: jax.Array,
                     top_ks: jax.Array, top_ps: jax.Array,
                     sampled: bool = True
                     ) -> tuple[jax.Array, jax.Array]:
    """Per-row acceptance sampling over one speculation window — the
    exact-distribution contract (:func:`sample_token_rows`'s
    draw-for-draw machinery extended to a residual-distribution
    acceptance). ``draft_toks`` (B, k), ``draft_logits`` (B, k, V)
    raw f32, ``target_logits`` (B, k+1, V) raw f32.

    Greedy rows (``temps == 0``): accept the longest draft prefix
    matching the target argmax chain, then emit the target argmax at
    the first mismatch — bit-identical to sequential greedy decode,
    whatever the draft proposed. Sampled rows: token ``j`` accepts
    with probability ``min(1, p_j(d_j) / q_j(d_j))`` where ``p`` / ``q``
    are the filtered, temperature-scaled target / draft distributions
    (the SAME filtering the draws came from); the first rejection
    draws the corrected token from the normalized residual
    ``max(p_j − q_j, 0)``, and a fully-accepted window draws the bonus
    token from ``p_k`` — the classic speculative-sampling identity, so
    the emitted stream is distributed EXACTLY as sequential
    ``jax.random.categorical`` sampling from the target
    (contract-tested statistically; the residual draw rides an
    acceptance-domain-separated key at ``steps0``/``steps0 + 1``).

    Returns ``(out_toks (B, k+1), n_acc (B,))``: row ``b`` emits
    ``out_toks[b, :n_acc[b] + 1]`` — its accepted draft prefix plus
    one corrected/bonus token."""
    k = draft_toks.shape[1]

    if not sampled:
        # All-greedy window: the argmax chain only — no softmax, no
        # RNG, no filter machinery on the serving hot path.
        def one_greedy(d_toks, t_lg):
            gt = jnp.argmax(t_lg, axis=-1).astype(jnp.int32)
            match = (d_toks == gt[:k]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(match))
            out = jnp.concatenate(
                [d_toks, jnp.zeros((1,), jnp.int32)])
            return out.at[n_acc].set(gt[n_acc]), n_acc

        return jax.vmap(one_greedy)(draft_toks, target_logits)

    akeys = jax.vmap(
        lambda kk: jax.random.fold_in(kk, _ACCEPT_FOLD))(keys)

    def one(d_toks, d_lg, t_lg, key, step0, t, tk, tp):
        gt = jnp.argmax(t_lg, axis=-1).astype(jnp.int32)  # (k+1,)
        match_g = d_toks == gt[:k]

        def dist(lg):  # raw (V,) logits → filtered sampling probs
            x = lg.astype(jnp.float32) / jnp.where(t > 0, t, 1.0)
            return jax.nn.softmax(_filter_logits_traced(x, tk, tp))

        p = jax.vmap(dist)(t_lg)  # (k+1, V)
        q = jax.vmap(dist)(d_lg)  # (k, V)
        idx = jnp.arange(k)
        ratio = p[idx, d_toks] / jnp.maximum(q[idx, d_toks], 1e-30)
        u = jax.random.uniform(jax.random.fold_in(key, step0), (k,))
        ok = jnp.where(t > 0.0, u < jnp.minimum(ratio, 1.0), match_g)
        n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
        # Residual at the rejection point; q padded with a zero row so
        # a fully-accepted window (n_acc == k) draws the bonus token
        # from the bare target distribution p_k.
        q_pad = jnp.concatenate([q, jnp.zeros((1, q.shape[-1]),
                                              q.dtype)])
        res = jnp.maximum(p[n_acc] - q_pad[n_acc], 0.0)
        rs = jnp.sum(res)
        # A numerically-empty residual (p == q to float precision but
        # the ratio test still rejected) falls back to p itself.
        res = jnp.where(rs > 0, res / jnp.maximum(rs, 1e-30),
                        p[n_acc])
        c_s = jax.random.categorical(
            jax.random.fold_in(key, step0 + 1),
            jnp.log(jnp.maximum(res, 1e-38))).astype(jnp.int32)
        c = jnp.where(t > 0.0, c_s, gt[n_acc])
        out = jnp.concatenate([d_toks, jnp.zeros((1,), jnp.int32)])
        return out.at[n_acc].set(c), n_acc

    return jax.vmap(one)(draft_toks, draft_logits, target_logits,
                         akeys, steps0, temps, top_ks, top_ps)


@functools.lru_cache(maxsize=64)
def _compiled_generate(cfg: tfm.TransformerConfig, B: int, S: int,
                       max_new_tokens: int, temperature: float,
                       top_k: int, top_p: float, rep_penalty: float):
    """One jitted prefill+decode program per (cfg, shapes, sampling
    params) — repeated calls (the serving hot path) reuse the
    compilation. ``run(params, prompt, lens, rng)``: ``lens`` is None
    for uniform-length prompts (a static, empty pytree under jit) or
    a traced (B,) lengths array for LEFT-padded ragged batches — ONE
    implementation for both, so sampling fixes can't drift between
    them."""
    penalize = rep_penalty != 1.0

    def run(params, prompt, lens, rng):
        # Size the cache to THIS request's reach (128-lane aligned),
        # not cfg.max_seq: decode reads the whole static cache every
        # step, so a 128+128-token call against a 1024-slot cache was
        # paying 4× the attention HBM traffic for masked-out zeros.
        reach = min(cfg.max_seq, -(-(S + max_new_tokens) // 128) * 128)
        cache = init_cache(cfg, B, max_seq=reach)
        logits, cache = prefill(params, prompt, cfg, cache,
                                prompt_lens=lens)
        # (B,) first valid cache slot per row (0 when uniform).
        pad = None if lens is None else S - lens
        # Token-presence mask for repetition penalty: prompt tokens
        # count as seen (HF semantics), emitted tokens join per step.
        seen = None
        if penalize:
            if lens is None:
                idx = prompt
            else:
                # Pad columns must not count as "seen": redirect them
                # to an out-of-bounds index dropped by the scatter.
                valid = jnp.arange(S)[None, :] >= pad[:, None]
                idx = jnp.where(valid, prompt, cfg.vocab_size)
            seen = (jnp.zeros((B, cfg.vocab_size), jnp.bool_)
                    .at[jnp.arange(B)[:, None], idx]
                    .set(True, mode="drop"))

        def sample(logits, key, seen):
            if penalize:
                # HF repetition penalty: seen tokens' positive logits
                # divide by the penalty, negative multiply — both push
                # probability down for penalty > 1.
                pen = jnp.where(logits > 0, logits / rep_penalty,
                                logits * rep_penalty)
                logits = jnp.where(seen, pen, logits)
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # Temperature FIRST: the nucleus must be measured on the
            # distribution actually sampled (HF/llama.cpp semantics) —
            # top-k is scale-invariant but top-p is not.
            logits = logits / jnp.float32(temperature)
            logits = _filter_logits(logits, top_k, top_p)
            return jax.random.categorical(key, logits,
                                          axis=-1).astype(jnp.int32)

        def mark(seen, token):
            if not penalize:
                return None
            return seen.at[jnp.arange(B), token].set(True)

        first = sample(logits, jax.random.fold_in(rng, 0), seen)
        seen = mark(seen, first)

        def step(carry, i):
            token, cache, seen = carry
            # Cache slot S+i is uniform; each ragged row's TOKEN
            # position is its own length + i (the left-pad offset).
            logits, cache = decode_step(
                params, token, S + i, cfg, cache,
                rope_pos=None if lens is None else lens + i,
                valid_from=pad)
            nxt = sample(logits, jax.random.fold_in(rng, i + 1), seen)
            return (nxt, cache, mark(seen, nxt)), token

        (_, _, _), toks = lax.scan(
            step, (first, cache, seen), jnp.arange(max_new_tokens))
        return toks.T  # (B, max_new_tokens): ys are the emitted tokens

    return jax.jit(run)


def pad_prompts(prompts, pad_token: int = 0):
    """LEFT-pad a list of 1-D token arrays to one (B, S) batch.
    Returns (padded int32 (B, S), lens int32 (B,)) for
    ``generate(..., prompt_lens=lens)``."""
    lens = np.asarray([len(p) for p in prompts], np.int32)
    S = int(lens.max())
    out = np.full((len(prompts), S), pad_token, np.int32)
    for i, p in enumerate(prompts):
        out[i, S - len(p):] = np.asarray(p, np.int32)
    return jnp.asarray(out), jnp.asarray(lens)


def _filter_logits(logits: jax.Array, top_k: int,
                   top_p: float) -> jax.Array:
    """Nucleus/top-k filtering: mask logits outside the top-k set and
    outside the smallest prefix whose probability mass reaches top_p.
    ``top_k <= 0`` / ``top_p >= 1`` disable the respective filter.
    logits: (B, V) f32."""
    if top_k > 0:
        k = min(top_k, logits.shape[-1])  # top_k > V means "keep all"
        # lax.top_k (selection) beats a full-vocab sort in the decode
        # hot loop; the smallest of the k kept values is the threshold.
        kth = lax.top_k(logits, k)[0][:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep every token whose PRECEDING mass is < top_p (the first
        # token always survives; the one that crosses the threshold is
        # included, matching the standard nucleus definition).
        keep_sorted = (cum - probs) < top_p
        # Threshold back in logit space: the smallest kept logit.
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _filter_logits_traced(logits: jax.Array, top_k: jax.Array,
                          top_p: jax.Array) -> jax.Array:
    """:func:`_filter_logits` with TRACED per-slot ``top_k``/``top_p``
    — the continuous engine samples every live slot in ONE compiled
    program, so the filters can't be compile-time constants. Same
    masking values (k-th-largest threshold via sort instead of
    ``lax.top_k``; identical nucleus cutoff math), with the
    enable/disable branches as ``jnp.where`` gates so a disabled
    filter is bit-for-bit a no-op, exactly like the skipped Python
    branch in the solo path. logits: (V,) f32."""
    V = logits.shape[-1]
    desc = jnp.sort(logits)[::-1]
    kth = desc[jnp.clip(top_k, 1, V) - 1]  # k-th largest == top_k's
    logits = jnp.where((top_k > 0) & (logits < kth), -jnp.inf, logits)
    desc2 = jnp.sort(logits)[::-1]
    probs = jax.nn.softmax(desc2)
    cum = jnp.cumsum(probs)
    keep = (cum - probs) < top_p
    cutoff = jnp.min(jnp.where(keep, desc2, jnp.inf))
    return jnp.where((top_p < 1.0) & (logits < cutoff), -jnp.inf,
                     logits)


def sample_token_rows(logits: jax.Array, keys: jax.Array,
                      steps: jax.Array, temps: jax.Array,
                      top_ks: jax.Array, top_ps: jax.Array
                      ) -> jax.Array:
    """Per-ROW sampling for the continuous engine step: row i draws
    with ITS OWN key folded at ITS OWN emitted-token index, so a
    co-batched sampled request sees exactly the RNG stream its solo
    (B=1) call would — ``jax.random.categorical(key, (1, V)) ==
    argmax(logits + gumbel(key, (1, V)))`` (asserted in tests), over
    the identically filtered/temperature-scaled logits. Rows with
    ``temperature == 0`` take the plain argmax (the greedy path).

    logits: (B, V) f32; keys: (B, 2) uint32 per-request PRNG keys;
    steps: (B,) emitted-token index (0 = first token, matching the
    solo path's ``fold_in(rng, 0)`` prefill draw)."""
    V = logits.shape[-1]

    def one(lg, key, step, t, k, p):
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        x = lg.astype(jnp.float32) / jnp.where(t > 0, t, 1.0)
        x = _filter_logits_traced(x, k, p)
        # (1, V) gumbel then [0]: the exact draw categorical makes on
        # a (1, V) logits batch — the solo path's shape.
        g = jax.random.gumbel(jax.random.fold_in(key, step), (1, V))[0]
        samp = jnp.argmax(x + g, axis=-1).astype(jnp.int32)
        return jnp.where(t > 0.0, samp, greedy)

    return jax.vmap(one)(logits, keys, steps, temps, top_ks, top_ps)


def generate(params: dict, cfg: tfm.TransformerConfig,
             prompt: jax.Array, max_new_tokens: int,
             temperature: float = 0.0,
             rng: jax.Array | None = None,
             top_k: int = 0, top_p: float = 1.0,
             stop_token: int = -1, pad_token: int = 0,
             repetition_penalty: float = 1.0,
             prompt_lens: jax.Array | None = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, S).

    One compiled program (cached per cfg/shape/sampling params):
    prefill then a ``lax.scan`` decode loop. ``temperature == 0`` →
    greedy; else softmax sampling, optionally filtered to the top-k
    logits and/or the top-p (nucleus) probability mass.
    ``stop_token >= 0``: output positions after a row's first stop
    token are filled with ``pad_token`` (static-shape early stopping —
    the loop length never varies, only the output mask).
    ``repetition_penalty > 1`` discounts logits of every token already
    seen (prompt + emitted, HF semantics) — applies to greedy too.
    ``prompt_lens`` (B,): the prompt batch is LEFT-padded ragged
    (``pad_prompts``); lengths are traced, so one compiled program
    serves any mix of lengths at this padded shape. Pad keys are
    masked and RoPE offsets are per-row, so a GREEDY row decodes
    exactly as it would solo; sampled rows draw from the batch-shaped
    RNG stream, which differs from a solo call (same caveat as
    uniform batching — the serving batcher coalesces greedy only).
    """
    B, S = prompt.shape
    total = S + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"generate: prompt {S} + new {max_new_tokens} exceeds "
            f"max_seq {cfg.max_seq}"
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"generate: top_p must be in (0, 1], got {top_p}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if temperature == 0.0:
        # Greedy ignores the filters — normalize them out of the
        # compile-cache key so differing sampling params can't force
        # redundant recompiles of an identical program.
        top_k, top_p = 0, 1.0
    if repetition_penalty <= 0.0:
        raise ValueError(
            f"generate: repetition_penalty must be > 0, "
            f"got {repetition_penalty}")
    lens = None
    if prompt_lens is not None:
        lens = jnp.asarray(prompt_lens, jnp.int32)
        if lens.shape != (B,):
            raise ValueError(
                f"generate: prompt_lens shape {lens.shape} != ({B},)")
        ln = np.asarray(lens)
        if (ln <= 0).any() or (ln > S).any():
            raise ValueError(
                f"generate: prompt_lens must be in [1, {S}], got "
                f"range [{ln.min()}, {ln.max()}]")
    run = _compiled_generate(cfg, B, S, int(max_new_tokens),
                             float(temperature), int(top_k),
                             float(top_p), float(repetition_penalty))
    out = run(params, prompt, lens, rng)
    if stop_token >= 0:
        # Post-processing OUTSIDE the jitted program: everything after
        # a row's first stop token becomes pad. Keeping stop/pad out of
        # the compile key means two tokenizers' EOS ids share one
        # compiled decode program; the O(B·max_new) mask is trivial.
        hit = out == stop_token
        after_stop = (jnp.cumsum(hit.astype(jnp.int32), axis=1)
                      - hit.astype(jnp.int32)) > 0
        out = jnp.where(after_stop, jnp.int32(pad_token), out)
    return out
