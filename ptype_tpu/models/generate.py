"""Autoregressive generation — static-shape KV-cache decode.

The reference served request/reply actors (calculator.go); the model
framework's equivalent of "serve a request" is generate-from-prompt.
TPU-first decisions:

- **Static shapes everywhere**: the KV cache is allocated at
  ``max_seq`` up front; the decode loop is a ``lax.scan`` over step
  index with ``dynamic_update_slice`` writes — one compiled program
  regardless of prompt/output length, no retracing.
- **Prefill + decode split**: prefill runs the full-sequence forward
  (MXU-efficient batched matmuls) while collecting per-layer K/V;
  decode steps attend against the cache with a position mask.
- Sampling: greedy or temperature; RNG is explicit (fold_in per step).

Works for any dense ``TransformerConfig`` (MoE decode falls back to the
same path — experts run per token). GQA caches only ``kv_heads`` heads.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from ptype_tpu.models import transformer as tfm


@dataclass(frozen=True)
class KVCache:
    """Stacked per-layer KV: (L, B, Smax, Kh, Dh)."""

    k: jax.Array
    v: jax.Array

    def tree_flatten(self):
        return (self.k, self.v), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten
)


def init_cache(cfg: tfm.TransformerConfig, batch: int,
               max_seq: int | None = None) -> KVCache:
    S = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, S, cfg.kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _cached_attention(q, k_cache, v_cache, pos_limit, cfg):
    """q: (B, 1, H, Dh); caches: (B, Smax, Kh, Dh); attend to
    positions < pos_limit. GQA-native: query heads are grouped onto
    their kv head inside the einsum — no ``jnp.repeat``
    materializing H-head caches every decode step (the G=1 MHA case
    is the same einsum)."""
    B, _, H, Dh = q.shape
    Kh = k_cache.shape[2]
    G = H // Kh
    qg = q.reshape(B, 1, Kh, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                        k_cache).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    mask = jnp.arange(k_cache.shape[1]) < pos_limit  # (Smax,)
    scores = jnp.where(mask[None, None, None, None, :], scores,
                       jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bkgqs,bskd->bqkgd", probs, v_cache)
    return o.reshape(B, 1, H, Dh)


def _head_logits(params, x_last, cfg):
    # One LM-head lowering for train and decode: bf16 operands with f32
    # MXU accumulation (transformer.head_logits), so precision policy
    # can never drift between the two paths.
    return tfm.head_logits(x_last, tfm._head_weight(params, cfg), cfg)


def prefill(params: dict, tokens: jax.Array, cfg: tfm.TransformerConfig,
            cache: KVCache) -> tuple[jax.Array, KVCache]:
    """Full-sequence forward, filling cache[:, :, :S]. Returns
    (last-position logits (B, V), cache). Block math is the shared
    transformer pieces (qkv_proj/attn_residual/mlp_residual), so
    training and generation can never diverge."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    sin, cos = tfm.rope_tables(cfg, S)

    def body(x, inputs):
        layer, kc, vc = inputs
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        o = tfm._attention(q, k, v, cfg)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg)
        kc = lax.dynamic_update_slice(kc, k, (0, 0, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, 0, 0, 0))
        return x, (kc, vc)

    x, (kcs, vcs) = lax.scan(body, x,
                             (params["blocks"], cache.k, cache.v))
    x = tfm.rms_norm(x, params["final_norm"])
    return _head_logits(params, x[:, -1], cfg), KVCache(kcs, vcs)


def decode_step(params: dict, token: jax.Array, pos: jax.Array,
                cfg: tfm.TransformerConfig,
                cache: KVCache) -> tuple[jax.Array, KVCache]:
    """One decode step. token: (B,) int32 at position ``pos`` (scalar).
    Returns (logits (B, V), updated cache). MoE capacity is pinned to
    the step's token count (B) so no routed token can drop at decode."""
    B = token.shape[0]
    x = params["embed"][token][:, None, :].astype(cfg.dtype)  # (B, 1, D)
    sin, cos = tfm.rope_tables(cfg, positions=jnp.asarray(pos)[None])

    def body(x, inputs):
        layer, kc, vc = inputs  # kc/vc: (B, Smax, Kh, Dh)
        q, k, v = tfm.qkv_proj(x, layer, cfg, sin, cos)
        kc = lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        o = _cached_attention(q, kc, vc, pos + 1, cfg)
        x = tfm.attn_residual(x, o, layer, cfg)
        x, _aux = tfm.mlp_residual(x, layer, cfg, moe_capacity=B)
        return x, (kc, vc)

    x, (kcs, vcs) = lax.scan(body, x,
                             (params["blocks"], cache.k, cache.v))
    x = tfm.rms_norm(x, params["final_norm"])
    return _head_logits(params, x[:, 0], cfg), KVCache(kcs, vcs)


import functools


@functools.lru_cache(maxsize=64)
def _compiled_generate(cfg: tfm.TransformerConfig, B: int, S: int,
                       max_new_tokens: int, temperature: float,
                       top_k: int, top_p: float, rep_penalty: float):
    """One jitted prefill+decode program per (cfg, shapes, sampling
    params) — repeated calls (the serving hot path) reuse the
    compilation."""
    penalize = rep_penalty != 1.0

    def run(params, prompt, rng):
        # Size the cache to THIS request's reach (128-lane aligned),
        # not cfg.max_seq: decode reads the whole static cache every
        # step, so a 128+128-token call against a 1024-slot cache was
        # paying 4× the attention HBM traffic for masked-out zeros.
        reach = min(cfg.max_seq, -(-(S + max_new_tokens) // 128) * 128)
        cache = init_cache(cfg, B, max_seq=reach)
        logits, cache = prefill(params, prompt, cfg, cache)
        # Token-presence mask for repetition penalty: prompt tokens
        # count as seen (HF semantics), emitted tokens join per step.
        seen = (jnp.zeros((B, cfg.vocab_size), jnp.bool_)
                .at[jnp.arange(B)[:, None], prompt].set(True)
                if penalize else None)

        def sample(logits, key, seen):
            if penalize:
                # HF repetition penalty: seen tokens' positive logits
                # divide by the penalty, negative multiply — both push
                # probability down for penalty > 1.
                pen = jnp.where(logits > 0, logits / rep_penalty,
                                logits * rep_penalty)
                logits = jnp.where(seen, pen, logits)
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # Temperature FIRST: the nucleus must be measured on the
            # distribution actually sampled (HF/llama.cpp semantics) —
            # top-k is scale-invariant but top-p is not.
            logits = logits / jnp.float32(temperature)
            logits = _filter_logits(logits, top_k, top_p)
            return jax.random.categorical(key, logits,
                                          axis=-1).astype(jnp.int32)

        def mark(seen, token):
            if not penalize:
                return None
            return seen.at[jnp.arange(B), token].set(True)

        first = sample(logits, jax.random.fold_in(rng, 0), seen)
        seen = mark(seen, first)

        def step(carry, i):
            token, cache, seen = carry
            logits, cache = decode_step(params, token, S + i, cfg, cache)
            nxt = sample(logits, jax.random.fold_in(rng, i + 1), seen)
            return (nxt, cache, mark(seen, nxt)), token

        (_, _, _), toks = lax.scan(
            step, (first, cache, seen), jnp.arange(max_new_tokens))
        return toks.T  # (B, max_new_tokens): ys are the emitted tokens

    return jax.jit(run)


def _filter_logits(logits: jax.Array, top_k: int,
                   top_p: float) -> jax.Array:
    """Nucleus/top-k filtering: mask logits outside the top-k set and
    outside the smallest prefix whose probability mass reaches top_p.
    ``top_k <= 0`` / ``top_p >= 1`` disable the respective filter.
    logits: (B, V) f32."""
    if top_k > 0:
        k = min(top_k, logits.shape[-1])  # top_k > V means "keep all"
        # lax.top_k (selection) beats a full-vocab sort in the decode
        # hot loop; the smallest of the k kept values is the threshold.
        kth = lax.top_k(logits, k)[0][:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep every token whose PRECEDING mass is < top_p (the first
        # token always survives; the one that crosses the threshold is
        # included, matching the standard nucleus definition).
        keep_sorted = (cum - probs) < top_p
        # Threshold back in logit space: the smallest kept logit.
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(params: dict, cfg: tfm.TransformerConfig,
             prompt: jax.Array, max_new_tokens: int,
             temperature: float = 0.0,
             rng: jax.Array | None = None,
             top_k: int = 0, top_p: float = 1.0,
             stop_token: int = -1, pad_token: int = 0,
             repetition_penalty: float = 1.0) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, S).

    One compiled program (cached per cfg/shape/sampling params):
    prefill then a ``lax.scan`` decode loop. ``temperature == 0`` →
    greedy; else softmax sampling, optionally filtered to the top-k
    logits and/or the top-p (nucleus) probability mass.
    ``stop_token >= 0``: output positions after a row's first stop
    token are filled with ``pad_token`` (static-shape early stopping —
    the loop length never varies, only the output mask).
    ``repetition_penalty > 1`` discounts logits of every token already
    seen (prompt + emitted, HF semantics) — applies to greedy too.
    """
    B, S = prompt.shape
    total = S + max_new_tokens
    if total > cfg.max_seq:
        raise ValueError(
            f"generate: prompt {S} + new {max_new_tokens} exceeds "
            f"max_seq {cfg.max_seq}"
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"generate: top_p must be in (0, 1], got {top_p}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if temperature == 0.0:
        # Greedy ignores the filters — normalize them out of the
        # compile-cache key so differing sampling params can't force
        # redundant recompiles of an identical program.
        top_k, top_p = 0, 1.0
    if repetition_penalty <= 0.0:
        raise ValueError(
            f"generate: repetition_penalty must be > 0, "
            f"got {repetition_penalty}")
    run = _compiled_generate(cfg, B, S, int(max_new_tokens),
                             float(temperature), int(top_k),
                             float(top_p), float(repetition_penalty))
    out = run(params, prompt, rng)
    if stop_token >= 0:
        # Post-processing OUTSIDE the jitted program: everything after
        # a row's first stop token becomes pad. Keeping stop/pad out of
        # the compile key means two tokenizers' EOS ids share one
        # compiled decode program; the O(B·max_new) mask is trivial.
        hit = out == stop_token
        after_stop = (jnp.cumsum(hit.astype(jnp.int32), axis=1)
                      - hit.astype(jnp.int32)) > 0
        out = jnp.where(after_stop, jnp.int32(pad_token), out)
    return out
