"""Model families.

The reference contains no ML code (SURVEY.md §2) — its "model" was the
`Prime.Check` worker handler (example/optimus/prime.go:15-25). The north
star (BASELINE.json `configs`) demands real model families trained through
the cluster's Store/actor surface; they live here, built TPU-first:
scan-over-layers stacked parameters, bf16 MXU compute, PartitionSpec trees
for GSPMD sharding.
"""

from ptype_tpu.models.transformer import (
    TransformerConfig,
    PRESETS,
    init_params,
    forward,
    loss_fn,
    param_specs,
    count_params,
    flops_per_token,
)

__all__ = [
    "TransformerConfig",
    "PRESETS",
    "init_params",
    "forward",
    "loss_fn",
    "param_specs",
    "count_params",
    "flops_per_token",
]
