"""ResNet-50, TPU-first — the actor-per-layer pipeline model family.

BASELINE.json configs: "ResNet-50 actor-per-layer pipeline (registry
PID→stage)". The reference has no vision model (no ML code at all); this
is a clean functional implementation designed for the MXU:

- **NHWC layout** (TPU-native conv layout; XLA tiles the C dim onto the
  MXU lanes), bf16 compute / f32 params like the transformer.
- **Functional BN**: batch-norm statistics are explicit state — ``train=
  True`` normalizes with batch stats and returns updated running stats;
  ``train=False`` uses the stored running stats. No hidden mutation, so
  every stage stays a pure function jit/pipeline/actor can move around.
- **Stage split for the actor pipeline**: :func:`stage_split` cuts the
  network into stem / c2 / c3 / c4 / c5 / head — the unit the registry
  maps onto actors (train/actor_pipeline.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

_LAYOUT = ("NHWC", "HWIO", "NHWC")


@dataclass(frozen=True)
class ResNetConfig:
    n_classes: int = 1000
    #: Blocks per stage; (3,4,6,3) = ResNet-50.
    depths: tuple = (3, 4, 6, 3)
    #: Bottleneck output channels per stage.
    widths: tuple = (256, 512, 1024, 2048)
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5


PRESETS = {
    "resnet-50": ResNetConfig(),
    "resnet-26": ResNetConfig(depths=(2, 2, 2, 2)),
    "tiny": ResNetConfig(n_classes=10, depths=(1, 1), widths=(32, 64)),
}


def preset(name: str, **overrides) -> ResNetConfig:
    from dataclasses import replace

    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return replace(PRESETS[name], **overrides)


# ------------------------------------------------------------------ params


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = (2.0 / fan_in) ** 0.5
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * std


def _bn_init(c, dtype):
    return {
        "scale": jnp.ones((c,), dtype),
        "bias": jnp.zeros((c,), dtype),
        "mean": jnp.zeros((c,), dtype),
        "var": jnp.ones((c,), dtype),
    }


def _bottleneck_init(key, cin, cout, dtype):
    mid = cout // 4
    k = jax.random.split(key, 4)
    p = {
        "conv1": _conv_init(k[0], 1, 1, cin, mid, dtype),
        "bn1": _bn_init(mid, dtype),
        "conv2": _conv_init(k[1], 3, 3, mid, mid, dtype),
        "bn2": _bn_init(mid, dtype),
        "conv3": _conv_init(k[2], 1, 1, mid, cout, dtype),
        "bn3": _bn_init(cout, dtype),
    }
    if cin != cout:
        p["proj"] = _conv_init(k[3], 1, 1, cin, cout, dtype)
        p["bn_proj"] = _bn_init(cout, dtype)
    return p


def init_params(rng: jax.Array, cfg: ResNetConfig) -> dict:
    pd = cfg.param_dtype
    keys = jax.random.split(rng, 3 + len(cfg.depths))
    params: dict = {
        "stem": {
            "conv": _conv_init(keys[0], 7, 7, 3, 64, pd),
            "bn": _bn_init(64, pd),
        },
        "head": {
            "w": jax.random.normal(
                keys[1], (cfg.widths[-1], cfg.n_classes), pd) * 0.01,
            "b": jnp.zeros((cfg.n_classes,), pd),
        },
    }
    cin = 64
    for si, (depth, cout) in enumerate(zip(cfg.depths, cfg.widths)):
        bkeys = jax.random.split(keys[3 + si], depth)
        blocks = []
        for bi in range(depth):
            blocks.append(_bottleneck_init(
                bkeys[bi], cin if bi == 0 else cout, cout, pd))
        params[f"stage{si + 1}"] = blocks
        cin = cout
    return params


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


# ----------------------------------------------------------------- forward


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype),
        window_strides=(stride, stride), padding="SAME",
        dimension_numbers=_LAYOUT,
    )


def _bn(x, p, cfg: ResNetConfig, train: bool):
    """Returns (y, new_stats). Stats math in f32."""
    x32 = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(x32, axis=(0, 1, 2))
        var = jnp.var(x32, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new = {"mean": m * p["mean"] + (1 - m) * mean,
               "var": m * p["var"] + (1 - m) * var}
    else:
        mean, var = p["mean"].astype(jnp.float32), p["var"].astype(jnp.float32)
        new = {"mean": p["mean"], "var": p["var"]}
    y = (x32 - mean) * lax.rsqrt(var + cfg.bn_eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new


def _bottleneck(x, p, cfg, stride, train, stats_out):
    dt = cfg.dtype
    y, s1 = _bn(_conv(x, p["conv1"], 1, dt), p["bn1"], cfg, train)
    y = jax.nn.relu(y)
    y, s2 = _bn(_conv(y, p["conv2"], stride, dt), p["bn2"], cfg, train)
    y = jax.nn.relu(y)
    y, s3 = _bn(_conv(y, p["conv3"], 1, dt), p["bn3"], cfg, train)
    stats_out.update({"bn1": s1, "bn2": s2, "bn3": s3})
    if "proj" in p:
        sc, sp = _bn(_conv(x, p["proj"], stride, dt), p["bn_proj"], cfg,
                     train)
        stats_out["bn_proj"] = sp
    else:
        sc = x if stride == 1 else x[:, ::stride, ::stride, :]
    return jax.nn.relu(y + sc)


def stem_apply(p, x, cfg, train=False):
    stats: dict = {}
    y, s = _bn(_conv(x, p["conv"], 2, cfg.dtype), p["bn"], cfg, train)
    stats["bn"] = s
    y = jax.nn.relu(y)
    y = lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    return y, stats


def stage_apply(blocks, x, cfg, stage_idx, train=False):
    """One residual stage (list of bottlenecks); stride 2 on the first
    block of every stage but the first."""
    stats = []
    for bi, p in enumerate(blocks):
        s: dict = {}
        stride = 2 if (bi == 0 and stage_idx > 0) else 1
        x = _bottleneck(x, p, cfg, stride, train, s)
        stats.append(s)
    return x, stats


def head_apply(p, x, cfg):
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global avg pool
    return x @ p["w"].astype(jnp.float32) + p["b"].astype(jnp.float32)


def forward(params: dict, x: jax.Array, cfg: ResNetConfig,
            train: bool = False):
    """Logits (B, n_classes); x: (B, H, W, 3). Returns (logits, stats)
    where ``stats`` mirrors the BN running-stat leaves (train=True) —
    merge with :func:`update_stats`."""
    stats: dict = {}
    y, stats["stem"] = stem_apply(params["stem"], x, cfg, train)
    for si in range(len(cfg.depths)):
        y, stats[f"stage{si + 1}"] = stage_apply(
            params[f"stage{si + 1}"], y, cfg, si, train
        )
    return head_apply(params["head"], y, cfg), stats


def update_stats(params: dict, stats: dict) -> dict:
    """Merge BN stat updates back into the param tree (pure)."""

    def merge(p, s):
        if isinstance(p, dict):
            out = {}
            for k, v in p.items():
                if k in ("mean", "var") and k in s:
                    out[k] = s[k].astype(v.dtype)
                elif isinstance(s, dict) and k in s:
                    out[k] = merge(v, s[k])
                else:
                    out[k] = v
            return out
        if isinstance(p, list):
            return [merge(pi, si) for pi, si in zip(p, s)]
        return p

    merged = dict(params)
    for key in stats:
        merged[key] = merge(params[key], stats[key])
    return merged


def loss_fn(params, batch, cfg, train=True):
    """Softmax cross-entropy; batch: {"images": (B,H,W,3), "labels": (B,)}.
    Returns (loss, stats)."""
    logits, stats = forward(params, batch["images"], cfg, train)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, batch["labels"][:, None], axis=-1
    )[:, 0]
    return jnp.mean(logz - gold), stats


# ------------------------------------------------------------ stage split


def stage_split(params: dict, cfg: ResNetConfig, train: bool = False):
    """[(name, apply_fn, params)] — the actor-per-layer pipeline units.

    Each ``apply_fn(params, x) -> y`` is pure; the registry maps each
    entry to an actor (PID→stage, north star). ``train=True`` normalizes
    with batch statistics (the correct training behavior — gradients
    flow through the batch moments); running-stat updates are dropped in
    this mode, so recompute them post-training (one ``forward(...,
    train=True)`` + :func:`update_stats` sweep) before switching to
    inference."""
    parts: list = [
        ("stem", lambda p, x: stem_apply(p, x, cfg, train)[0],
         params["stem"]),
    ]
    for si in range(len(cfg.depths)):
        name = f"stage{si + 1}"
        parts.append((
            name,
            (lambda si_: lambda p, x: stage_apply(p, x, cfg, si_, train)[0])(si),
            params[name],
        ))
    parts.append(("head", lambda p, x: head_apply(p, x, cfg),
                  params["head"]))
    return parts
