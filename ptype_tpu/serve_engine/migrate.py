"""KV-block migration: the quantized wire between serving classes.

Disaggregated serving (ISSUE 16) splits the fleet into prefill-class
and decode-class replicas: a prefill replica fills a prompt's KV
blocks, then migrates the block set to the decode replica that owns
the request for its whole decode lifetime. This module is the wire
between them — and the ONE module in ``serve_engine/`` where KV wire
serialization may live (lint PT021 bars ``quantize_leaf`` /
``dequantize_leaf`` on block banks anywhere else, the same
single-home discipline PT008/PT011 apply to collectives and RNG).

Wire format, by analogy with the training plane: the int8+EF codec
that quantizes gradient collectives (``parallel/collectives.py``,
PR 6 — the EQuARX move, arXiv 2506.17615) quantizes the KV transfer
leg too. Per migrated block:

- ``kv_wire="q8"`` (default): block-scaled int8 with per-block
  error-feedback residuals. The residual stays on the PREFILL side,
  keyed by the block's chain hash — a shared prefix block re-exported
  to a second decode replica carries the previous transfer's
  quantization error folded in, so repeated transfers of the same
  content do not accumulate bias (exactly the EF contract the
  quantized allreduce keeps across steps).
- ``kv_wire="exact"``: raw-dtype passthrough — the bit-exactness
  escape hatch parity tests pin greedy token equality with (int8 is
  lossy; "migrated decode == solo decode" is only a theorem in exact
  mode).

Only blocks the target does not already hold ride the wire: the
transfer manifest is :func:`~ptype_tpu.serve_engine.blocks.
block_hashes`'s chain-hash family (hash i commits to the whole prefix
through block i), so the decode side's content-verified residency
check is exact, and dedup hits are counted, never re-sent.

The pack/unpack programs carry the dispatch-discipline contracts the
rest of the data plane lives by: pack DONATES the residual buffers
(consumed into the pre-quantization sum, replaced by the new error),
unpack DONATES the target banks (scatter-in-place) — both registered
with ``progaudit`` as ``serve.kv_pack`` / ``serve.kv_unpack``
(donation consumed, no callbacks, no f64), and the engine runs them
inside a ``jitwatch.hot_region("serve.migrate")``.
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from ptype_tpu.parallel.collectives import (_Q8_KEY, DEFAULT_QUANT_BLOCK,
                                            dequantize_leaf, quantize_leaf)

#: The two wire encodings ``kv_wire`` accepts.
WIRE_MODES = ("q8", "exact")


def _wire_leaf(arr: np.ndarray) -> dict:
    """Codec-safe exact-mode leaf: the socket codec buffers standard
    dtypes only, so a non-native bank dtype (bf16) ships as its raw
    bits + the dtype name; bit-exactness is a view, not a cast."""
    try:
        memoryview(arr)
        return {"raw": arr}
    except (ValueError, TypeError):
        return {"raw": arr.view(np.uint8), "dtype": arr.dtype.name}


def _unwire_leaf(leaf: dict) -> np.ndarray:
    raw = np.ascontiguousarray(leaf["raw"])
    if "dtype" in leaf:
        raw = raw.view(np.dtype(leaf["dtype"]))
    return raw


def make_pack_prog(q_block: int | None = DEFAULT_QUANT_BLOCK):
    """One jitted program quantizing a single block's K/V pair for the
    wire: ``(k_blk, v_blk, res_k, res_v) -> (qk, sk, new_res_k, qv,
    sv, new_res_v)``. The residuals are DONATED — consumed into the
    pre-quantization sum and replaced by the new per-block error (the
    ``serve.kv_pack`` progaudit contract)."""

    def pack(kblk, vblk, rk, rv):
        wk, nrk = quantize_leaf(kblk, q_block, rk)
        wv, nrv = quantize_leaf(vblk, q_block, rv)
        return wk["q"], wk["s"], nrk, wv["q"], wv["s"], nrv

    return jax.jit(pack, donate_argnums=(2, 3))


def make_unpack_prog(block_shape, bank_dtype):
    """One jitted program scattering a quantized block pair into the
    target banks at ``bid``: ``(kb, vb, qk, sk, qv, sv, bid) -> (kb,
    vb)``. The banks are DONATED — the import is a scatter-in-place,
    never a bank copy (the ``serve.kv_unpack`` progaudit contract)."""
    shape = [int(d) for d in block_shape]
    dstr = np.dtype(bank_dtype).name

    def unpack(kb, vb, qk, sk, qv, sv, bid):
        kblk = dequantize_leaf(
            {_Q8_KEY: 1, "q": qk, "s": sk, "shape": shape, "dtype": dstr})
        vblk = dequantize_leaf(
            {_Q8_KEY: 1, "q": qv, "s": sv, "shape": shape, "dtype": dstr})
        kb = kb.at[:, bid].set(kblk.astype(kb.dtype))
        vb = vb.at[:, bid].set(vblk.astype(vb.dtype))
        return kb, vb

    return jax.jit(unpack, donate_argnums=(0, 1))


def make_unpack_exact_prog():
    """Exact-mode import scatter (no dequantize): ``(kb, vb, k_blk,
    v_blk, bid) -> (kb, vb)``, banks donated."""

    def unpack(kb, vb, kblk, vblk, bid):
        kb = kb.at[:, bid].set(kblk.astype(kb.dtype))
        vb = vb.at[:, bid].set(vblk.astype(vb.dtype))
        return kb, vb

    return jax.jit(unpack, donate_argnums=(0, 1))


class KVMigrator:
    """Per-engine wire state: the jitted pack/unpack programs plus the
    prefill-side error-feedback residual store.

    Residuals are keyed by the block's CHAIN hash (content-stable —
    the same key the pool's dedup index and the gateway's prefix
    directory use), bounded by an LRU of ``max_residuals`` block
    pairs; the unsealed partial tail block of a prompt has no hash
    and carries no residual (it is exported at most once per
    request). Thread contract: calls come from the engine's RPC
    handler threads under the engine's dispatch lock — the same lock
    that orders bank-donating programs."""

    def __init__(self, block_shape, bank_dtype, *,
                 q_block: int | None = DEFAULT_QUANT_BLOCK,
                 max_residuals: int = 64):
        self.block_shape = tuple(int(d) for d in block_shape)
        self.bank_dtype = np.dtype(bank_dtype)
        self.q_block = q_block
        self.max_residuals = int(max_residuals)
        self._pack = make_pack_prog(q_block)
        self._unpack = make_unpack_prog(self.block_shape, bank_dtype)
        self._unpack_exact = make_unpack_exact_prog()
        #: hash -> (res_k, res_v), LRU oldest-first.
        self._res: collections.OrderedDict[int, tuple] = \
            collections.OrderedDict()

    # ------------------------------------------------------------- pack

    def pack_block(self, kb, vb, bid: int, h: int | None,
                   mode: str) -> tuple[dict, int]:
        """Encode block ``bid`` of banks ``(kb, vb)`` for the wire.
        Returns ``(payload, nbytes)`` — the payload is codec-
        marshalable (numpy leaves only)."""
        if mode not in WIRE_MODES:
            raise ValueError(f"kv_wire must be one of {WIRE_MODES}, "
                             f"got {mode!r}")
        if mode == "exact":
            # device_get, not np.asarray: the engine packs inside an
            # armed hot_region, where only EXPLICIT transfers are
            # legal — the wire hop IS the contract here.
            k = np.ascontiguousarray(jax.device_get(kb[:, bid]))
            v = np.ascontiguousarray(jax.device_get(vb[:, bid]))
            payload = {"k": _wire_leaf(k), "v": _wire_leaf(v)}
            return payload, k.nbytes + v.nbytes
        rk = rv = None
        if h is not None:
            rk, rv = self._res.pop(h, (None, None))
        if rk is None:
            rk = jnp.zeros(self.block_shape, self.bank_dtype)
            rv = jnp.zeros(self.block_shape, self.bank_dtype)
        qk, sk, nrk, qv, sv, nrv = self._pack(kb[:, bid], vb[:, bid],
                                              rk, rv)
        if h is not None:
            self._res[h] = (nrk, nrv)
            while len(self._res) > self.max_residuals:
                self._res.popitem(last=False)
        qk, sk = jax.device_get(qk), jax.device_get(sk)
        qv, sv = jax.device_get(qv), jax.device_get(sv)
        payload = {"k": {"q": qk, "s": sk}, "v": {"q": qv, "s": sv}}
        return payload, (qk.nbytes + sk.nbytes + qv.nbytes + sv.nbytes)

    # ----------------------------------------------------------- unpack

    def unpack_block(self, kb, vb, payload: dict, bid: int, mode: str):
        """Scatter one wire payload into banks at ``bid``; returns the
        new ``(kb, vb)`` (the old ones are donated)."""
        if mode == "exact":
            return self._unpack_exact(
                kb, vb, jnp.asarray(_unwire_leaf(payload["k"])),
                jnp.asarray(_unwire_leaf(payload["v"])),
                jnp.int32(bid))
        pk, pv = payload["k"], payload["v"]
        return self._unpack(
            kb, vb, jnp.asarray(pk["q"]), jnp.asarray(pk["s"]),
            jnp.asarray(pv["q"]), jnp.asarray(pv["s"]), jnp.int32(bid))

    # -------------------------------------------------------- residuals

    def residual_count(self) -> int:
        return len(self._res)
