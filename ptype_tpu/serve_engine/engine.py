"""The paged continuous-batching engine.

:class:`PagedGeneratorActor` rebases serve.py's continuous engine onto
the :class:`~ptype_tpu.serve_engine.blocks.BlockPool`:

- **Paged decode**: one engine step decodes every live slot through
  per-sequence block tables (``models/generate.decode_step_paged``) —
  resident KV memory tracks actual token counts (pool blocks), not
  ``n_slots × reach`` contiguous banks. Greedy rows still match their
  solo decode token-for-token (gathered table order == position
  order).
- **Chunked prefill**: admission writes the prompt in bounded
  ``prefill_chunk``-token chunks INTERLEAVED with decode steps — a 4k
  prompt can no longer freeze co-batched decodes for its whole
  prefill; the per-decode-step stall is bounded by one chunk and
  recorded (``serve.prefill`` regions feed the goodput ledger's
  ``prefill`` leg; ``Info()['prefill_stall_ms']`` and the
  ``serve.prefill_stall_ms`` gauge carry the host-side maximum).
- **Prefix reuse**: prompt blocks are content-addressed by the
  fnv32a hash chain (blocks.block_hashes — the SAME hash family the
  gateway's affinity routing keys on), so an affinity-routed request
  skips prefill for every already-resident full block. Hits/misses/
  evictions surface in ``Info()`` and as ``serve.*`` gauges the
  health sampler picks up.
- **Sampling on the continuous path**: per-slot RNG keys fold into
  the engine step (``generate.sample_token_rows``) — single-row
  sampled requests (temperature/top-k/top-p) ride the engine with
  exact solo-path RNG parity instead of convoying the lock-serialized
  solo path. Multi-row sampled requests and repetition-penalty
  requests keep the solo fallback (batch-shaped RNG / seen-set state).

Admission control: the waiting room is bounded (``max_queue``) and
every request reserves its worst-case block count up front — an
arrival the pool or queue can't hold sheds with a typed
:class:`~ptype_tpu.errors.ShedError` (+ backlog-proportional
``retry_after_s``) instead of wedging the engine; the ``serve.admit``
chaos seam forces sheds/delays and pairs with success-path beacons.

Observability (ISSUE 10): every latency stamp in this engine rides a
seam on its :class:`~ptype_tpu.health.serving.ServingLedger` (lint
PT010 bars raw timers in ``serve_engine/``) — per-request lifecycle
records with TTFT/TPOT/e2e histograms, per-iteration batch
composition, ``kv.*`` pressure series, and a synthesized
``serve.admit`` / ``serve.prefill.chunk[i]`` / ``serve.decode`` span
tree under the caller's traceparent so one stitched Perfetto trace
answers "where did this request's latency go" across processes.
"""

from __future__ import annotations

import itertools
import threading

from ptype_tpu import lockcheck
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ptype_tpu import chaos, jitwatch, logs, trace
from ptype_tpu import metrics as metrics_mod
from ptype_tpu.errors import ShedError
from ptype_tpu.health.serving import ServingLedger
from ptype_tpu.models import generate as gen
from ptype_tpu.models import transformer as tfm
from ptype_tpu.serve import (LIFECYCLE_CODES, GeneratorActor, _norm_prompt,
                             _pow2)
from ptype_tpu.serve_engine.blocks import BlockPool, block_hashes
from ptype_tpu.serve_engine.migrate import WIRE_MODES, KVMigrator

log = logs.get_logger("serve_engine")

#: Replica classes for disaggregated serving (ISSUE 16): a "prefill"
#: replica fills KV blocks and exports them; a "decode" replica
#: imports migrated block sets and owns the decode lifetime;
#: "unified" does both (the pre-disaggregation behavior, and the
#: fallback class the router uses when a class pool is empty). The
#: class is ADVISORY — every engine serves every endpoint — routing
#: and the reconciler's per-class scaling are where it binds.
SERVE_CLASSES = ("unified", "prefill", "decode")
#: Numeric codes for the ``serve.class`` gauge (obs serve renders
#: the names back; same pattern as ``serve.lifecycle``).
SERVE_CLASS_CODES = {"unified": 0, "prefill": 1, "decode": 2}


@dataclass
class SpecConfig:
    """Speculative decoding on the paged engine (ISSUE 12).

    A small same-family draft transformer proposes ``k`` tokens per
    live slot (its own paged KV tables in a second :class:`BlockPool`;
    :func:`~ptype_tpu.models.generate.truncated_draft_params` builds
    the zero-extra-memory layer-truncated variant), the target model
    scores all ``k + 1`` positions in ONE batched forward through the
    ragged per-slot gather path, and acceptance sampling commits the
    accepted prefix plus one corrected token — greedy output
    bit-identical to the non-speculative engine, sampled output
    distributed exactly as the target (the residual-acceptance
    contract in ``generate.spec_accept_rows``).

    ``adaptive``: back speculation off when the measured accept rate
    makes it a loss — the accept-rate EWMA under ``accept_floor``
    sheds one proposal depth per window; at depth 1 and under
    ``accept_floor / 2`` speculation disables outright and re-probes
    with one k=1 window every ``probe_every`` plain decode iterations
    (a draft gone stale against new traffic re-earns its depth instead
    of taxing every token forever). Above ``accept_floor + 0.15`` the
    depth climbs back toward ``k``.
    """

    #: Draft model params pytree (same family: embed/blocks/head).
    draft_params: dict
    #: Draft model config; vocab must match the target's.
    draft_cfg: tfm.TransformerConfig
    #: Proposal depth per window (the max tokens drafted per slot).
    k: int = 4
    #: Back off / re-probe on the measured accept rate.
    adaptive: bool = True
    #: Accept-rate EWMA floor under which depth backs off.
    accept_floor: float = 0.35
    #: Plain iterations between re-probes once speculation disabled.
    probe_every: int = 32
    #: Accept-rate EWMA smoothing.
    ewma_alpha: float = 0.2


class _PagedRow:
    """One prompt ROW moving through the engine: queued → admitting
    (chunked prefill) → active slot → done."""

    __slots__ = ("prompt", "max_new", "stop_token", "temperature",
                 "top_k", "top_p", "key", "emitted", "done", "err",
                 "table", "hashes", "reused", "prefill_pos",
                 "reserve_left", "rec", "cancelled", "draft_table",
                 "draft_reserve_left", "export_id", "migrated")

    def __init__(self, prompt, max_new, stop_token, temperature,
                 top_k, top_p, key):
        self.prompt = prompt          # 1-D int32 np array
        self.max_new = max_new
        self.stop_token = stop_token
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.key = key                # (2,) uint32 np array
        self.emitted: list[int] = []
        self.done = threading.Event()
        self.err = None
        self.table: list[int] = []    # block ids, position order
        self.hashes: list[int] = []
        self.reused = 0
        self.prefill_pos = -1         # -1: reuse walk not yet run
        self.reserve_left = 0
        #: Lifecycle record (health/serving.RequestRecord) — every
        #: stamp the engine needs comes through its ledger seams
        #: (lint PT010: no raw timers in serve_engine/).
        self.rec = None
        self.cancelled = False
        #: Draft-model block table + reservation (speculative
        #: decoding only; mirrors table/reserve_left on the target
        #: pool — the draft's KV state rides its own BlockPool).
        self.draft_table: list[int] = []
        self.draft_reserve_left = 0
        #: Disaggregated serving (ISSUE 16): a non-None export_id
        #: marks a prefill-class row — at prompt completion its block
        #: refs park under the id for ExportBlocks instead of taking
        #: a slot; ``migrated`` marks a decode-class row whose prompt
        #: KV arrived over the wire (admission skips reservation and
        #: prefill — both already happened).
        self.export_id: int | None = None
        self.migrated = False


class PagedGeneratorActor(GeneratorActor):
    """Continuous batching over the paged KV block pool.

    Knobs (docs/OPERATIONS.md "Serving at scale"): ``n_slots`` live
    sequences; ``block_tokens`` KV block granularity (sublane-aligned,
    also the prefix-sharing granularity); ``n_blocks`` pool size
    (default ``n_slots × reach/block_tokens + 1`` — the contiguous
    engine's worst case; shrink it to oversubscribe on real token
    counts); ``prefill_chunk`` admission token budget per engine
    iteration (the decode-stall bound; ``None`` = whole-prompt, the
    legacy behavior); ``max_queue`` waiting-room bound before typed
    sheds; ``admit_timeout_s`` bound on how long a head-of-line
    request may wait for a pool reservation before it sheds typed
    (pool exhaustion becomes a routing signal instead of a gateway
    deadline burn; 0 = wait forever); ``attn`` "gather" (XLA,
    default) or "kernel" (Pallas paged attention, TPU backends gated
    by its ``check_tpu_lowering``); ``spec`` a :class:`SpecConfig`
    arming speculative decoding — draft-propose, one batched
    target-verify, exact-distribution acceptance (greedy output stays
    bit-identical to the non-speculative engine; per-slot accept
    lengths make iterations ragged, which the retirement path already
    tolerates).
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None, n_slots: int = 8,
                 max_len: int | None = None, block_tokens: int = 16,
                 n_blocks: int | None = None,
                 prefill_chunk: int | None = 64,
                 max_queue: int = 64, admit_timeout_s: float = 10.0,
                 attn: str = "gather",
                 spec: SpecConfig | None = None,
                 metrics_registry: metrics_mod.MetricsRegistry | None
                 = None, serve_class: str = "unified"):
        super().__init__(cfg, params, rng)
        #: Registry the engine's gauges/histograms land in (default:
        #: the process-global one; drills and simulated multi-replica
        #: fleets pass a per-node registry so each replica's series
        #: stay distinct in the cluster snapshot).
        self._reg = (metrics_registry if metrics_registry is not None
                     else metrics_mod.metrics)
        #: The serving observability ledger (ISSUE 10): request
        #: lifecycle records, TTFT/TPOT/e2e histograms, engine-
        #: iteration composition, KV-pressure series — every latency
        #: stamp in this engine rides its seams.
        self.ledger = ServingLedger(registry=self._reg)
        self.n_slots = int(n_slots)
        bt = int(block_tokens)
        reach = min(int(max_len) if max_len else cfg.max_seq,
                    cfg.max_seq)
        self.reach = -(-reach // bt) * bt  # block-aligned
        self.block_tokens = bt
        self.nb = self.reach // bt
        n_blocks = (int(n_blocks) if n_blocks
                    else self.n_slots * self.nb + 1)
        self.pool = BlockPool(cfg, n_blocks, bt)
        self.prefill_chunk = (int(prefill_chunk) if prefill_chunk
                              else self.reach)
        self.max_queue = int(max_queue)
        self.admit_timeout_s = float(admit_timeout_s)
        if attn not in ("gather", "kernel"):
            raise ValueError(f"attn must be 'gather'|'kernel', "
                             f"got {attn!r}")
        if serve_class not in SERVE_CLASSES:
            raise ValueError(f"serve_class must be one of "
                             f"{SERVE_CLASSES}, got {serve_class!r}")
        #: Disaggregated-serving class (ISSUE 16) — advisory: routing
        #: and per-class scaling key on it; every endpoint still
        #: answers (the gateway's fallback path relies on that).
        self.serve_class = serve_class
        #: KV wire state: pack/unpack programs + the prefill-side EF
        #: residual store (docs/OPERATIONS.md "Disaggregated
        #: serving"). One per engine — residuals are keyed by chain
        #: hash, so they follow block CONTENT, not requests.
        self._migrator = KVMigrator(
            (cfg.n_layers, bt, cfg.kv_heads, cfg.head_dim), cfg.dtype)
        #: export_id -> finished prefill row whose block refs are
        #: parked for migration (released by ReleaseExport).
        self._exports: dict[int, _PagedRow] = {}
        #: ticket -> decode-side migration state (reserved blocks,
        #: resident refs, the ledger record with the migration leg).
        self._tickets: dict[int, dict] = {}
        self._mig_ids = itertools.count(1)
        self._migrations = 0
        self._migrate_bytes = 0
        self._migrate_dedup_hits = 0
        if attn == "kernel" and jax.default_backend() != "cpu":
            from ptype_tpu.ops.paged_attention import check_tpu_lowering

            bad = check_tpu_lowering(
                self.n_slots, cfg.n_heads, cfg.kv_heads, cfg.head_dim,
                n_blocks, bt, self.nb)
            if bad:
                raise ValueError(
                    "paged-attention kernel cannot lower for this "
                    "config: " + "; ".join(bad))
        self.attn = attn

        # Speculative decoding (ISSUE 12): the draft model's own paged
        # KV tables ride a second BlockPool (same block geometry, its
        # own reservation discipline — admission reserves BOTH pools'
        # worst case so a mid-window boundary crossing can never find
        # either empty).
        self._spec = spec
        self._dpool: BlockPool | None = None
        if spec is not None:
            if spec.draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"spec draft vocab {spec.draft_cfg.vocab_size} != "
                    f"target vocab {cfg.vocab_size}")
            if int(spec.k) < 1:
                raise ValueError(f"spec.k must be >= 1, got {spec.k}")
            self._dpool = BlockPool(spec.draft_cfg, n_blocks, bt)
        #: Current proposal depth (adaptive-k backs this off; 0 =
        #: speculation disabled pending a re-probe).
        self._k_cur = int(spec.k) if spec is not None else 0
        self._spec_ewma = 0.0
        self._spec_windows = 0
        self._spec_probe_left = 0
        self._window_progs: dict = {}
        self._draft_chunk_progs: dict = {}
        #: Device mirror of the slow-moving spec slot state; None =
        #: re-upload (admission, retire, boundary allocation).
        self._sdev: dict | None = None

        ns = self.n_slots
        self._tables = np.zeros((ns, self.nb), np.int32)
        self._nalloc = np.zeros(ns, np.int32)
        self._tok = np.zeros(ns, np.int32)
        self._pos = np.zeros(ns, np.int32)
        self._active = np.zeros(ns, bool)
        self._keys = np.zeros((ns, 2), np.uint32)
        self._temps = np.zeros(ns, np.float32)
        self._topk = np.zeros(ns, np.int32)
        self._topp = np.ones(ns, np.float32)
        self._eidx = np.zeros(ns, np.int32)
        # Draft-side slot mirrors + the per-slot speculative RNG
        # counter (advances by k+2 per window: k+1 draft draws plus
        # the acceptance pair ride domain-separated folds of it).
        self._dtables = np.zeros((ns, self.nb), np.int32)
        self._dnalloc = np.zeros(ns, np.int32)
        self._sctr = np.zeros(ns, np.int32)
        #: First position whose draft KV is NOT yet written — plain
        #: decode steps (chaos reject, adaptive k=0, remaining-1
        #: windows) advance the target without touching the draft
        #: pool, and the next window catches the draft up from here
        #: (a cold draft cache would silently bias every later accept
        #: rate, including the re-probe that decides recovery).
        self._dpos = np.zeros(ns, np.int32)
        self._slot_state: dict[int, _PagedRow] = {}
        self._queue: list[_PagedRow] = []
        self._admitting: _PagedRow | None = None
        self._cond = lockcheck.condition("serve_engine.queue")
        self._closed = False
        self._steps = 0
        self._max_live = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefill_chunks = 0
        self._prefill_tokens = 0
        self._max_stall_ms = 0.0
        self._last_stall_ms = 0.0

        def engine_step(sampled, params, kb, vb, tok, pos, tables,
                        active, keys, eidx, temps, topk, topp):
            B = tok.shape[0]
            bt_ = self.block_tokens
            # Write routing in-graph: inactive lanes scatter to the
            # trash block. Keeping this (and the pos/eidx increments)
            # on device lets the engine loop skip re-uploading its
            # slot state on steps where nothing was admitted/retired —
            # the steady-state decode step transfers nothing in.
            wr_b = jnp.where(active,
                             tables[jnp.arange(B), pos // bt_], 0)
            wr_o = pos % bt_
            logits, kb, vb = gen.decode_step_paged(
                params, tok, pos, self.cfg, kb, vb, tables, wr_b,
                wr_o, attn_impl=self.attn)
            if sampled:
                nxt = gen.sample_token_rows(logits, keys, eidx, temps,
                                            topk, topp)
            else:
                # All-greedy step: skip the per-row sort/gumbel
                # machinery entirely (the serving hot path; two cached
                # programs, picked per step by live-slot inspection).
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, 0)
            return (kb, vb, nxt, jnp.where(active, pos + 1, pos),
                    jnp.where(active, eidx + 1, eidx))

        # Donate the banks: the engine must not copy the pool per step.
        self._engine_step = jax.jit(engine_step, donate_argnums=(2, 3),
                                    static_argnums=(0,))
        #: Device mirrors of the slot state; None = host copy is
        #: authoritative and must be re-uploaded (set dirty by
        #: admission, retire, and block-boundary allocation).
        self._dev: dict | None = None

        def sample_first(logits, key, temp, topk, topp):
            return gen.sample_token_rows(
                logits, key[None], jnp.zeros((1,), jnp.int32),
                temp[None], topk[None], topp[None])[0]

        self._sample_first = jax.jit(sample_first)
        self._chunk_progs: dict[int, object] = {}
        self._thread = threading.Thread(
            target=self._engine, name="paged-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ public

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        prompt = _norm_prompt(prompt)
        if (float(repetition_penalty) != 1.0
                or (float(temperature) != 0.0 and prompt.shape[0] > 1)):
            # Repetition penalty needs per-request seen-set state, and
            # a MULTI-row sampled request draws from the solo path's
            # batch-shaped RNG stream — both keep the solo fallback.
            # Single-row sampled requests ride the engine with exact
            # solo RNG parity (sample_token_rows).
            return super().Generate(prompt, max_new_tokens, temperature,
                                    seed, top_k, top_p, stop_token,
                                    pad_token, repetition_penalty)
        if not 0.0 < float(top_p) <= 1.0:
            raise ValueError(
                f"generate: top_p must be in (0, 1], got {top_p}")
        max_new = int(max_new_tokens)
        if max_new <= 0:
            return jnp.zeros((prompt.shape[0], 0), jnp.int32)
        if prompt.shape[1] + max_new > self.reach:
            raise ValueError(
                f"prompt {prompt.shape[1]} + max_new {max_new} exceeds "
                f"engine reach {self.reach}")
        bt = self.block_tokens
        blocks_per_row = -(-(prompt.shape[1] + max_new) // bt)
        if blocks_per_row > self.pool.capacity:
            raise ValueError(
                f"request needs {blocks_per_row} blocks; pool holds "
                f"{self.pool.capacity}")
        self._enter_request()
        try:
            # The drain seam (ISSUE 13): a draining replica refuses
            # NEW work typed — the frontdoor re-routes to a sibling —
            # while the engine runs already-admitted rows to
            # completion. Checked INSIDE _enter_request (see its
            # docstring): a request must be counted in in_flight
            # before it passes the gate, or drained() could flip true
            # with this request still executing.
            if self._draining:
                self.ledger.shed_untracked()
                raise ShedError("replica draining (scale-down in "
                                "progress); route elsewhere",
                                retry_after_s=0.05)
            # The admission seam: chaos can force a shed/delay here;
            # real sheds (queue full) ride the same typed contract.
            f = chaos.hit("serve.admit", f"rows={prompt.shape[0]}")
            if f is not None:
                if f.action == "delay":
                    f.sleep()
                elif f.action == "shed":
                    self.ledger.shed_untracked()
                    raise ShedError("chaos: serve.admit shed",
                                    retry_after_s=self._retry_after())
            key = (np.asarray(jax.random.PRNGKey(int(seed)))
                   if float(temperature) != 0.0
                   else np.zeros(2, np.uint32))
            rows = [_PagedRow(np.asarray(prompt[i]), max_new,
                              int(stop_token), float(temperature),
                              int(top_k), float(top_p), key)
                    for i in range(prompt.shape[0])]
            # One traceparent per call: the actor handler span (when
            # the request arrived over a traced RPC) — the
            # synthesized admit/prefill/decode span tree parents
            # under it, which is what stitches gateway.request → ...
            # → serve.decode.
            tp = trace.traceparent()
            for r in rows:
                r.rec = self.ledger.enqueued(len(r.prompt), max_new,
                                             tp=tp)
            with self._lock:
                self._calls += 1
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                if (self.max_queue
                        and len(self._queue) + len(rows) > self.max_queue):
                    for r in rows:
                        self.ledger.retired(r.rec, "shed")
                    raise ShedError(
                        f"serving backlog full "
                        f"({len(self._queue)} queued, cap "
                        f"{self.max_queue})",
                        retry_after_s=self._retry_after())
                self._queue.extend(rows)
                # Exported from the CALLER thread on purpose: the
                # serve-stall rule gates on a non-empty queue, and a
                # wedged engine thread (its primary target) would
                # never export the depth that pages it.
                self._reg.gauge("serve.queue_depth").set(
                    len(self._queue))
                self._cond.notify()
            chaos.note_ok("serve.admit")
            out = np.full((len(rows), max_new), int(pad_token),
                          np.int32)
            for i, r in enumerate(rows):
                r.done.wait()
                if r.err is not None:
                    # One row failed (e.g. admit-timeout shed): the
                    # caller gets the error for the WHOLE request, so
                    # withdraw the sibling rows — otherwise they keep
                    # decoding output nobody reads, holding the very
                    # blocks an exhausted pool's shed exists to free.
                    self._cancel_rows(rows)
                    raise r.err
                out[i, :len(r.emitted)] = r.emitted
            return jnp.asarray(out)
        finally:
            self._exit_request()

    def _cancel_rows(self, rows) -> None:
        """Withdraw a request's not-yet-finished rows: queued ones
        leave the queue now; the admitting/active ones are flagged and
        the engine retires them at its next boundary."""
        with self._cond:
            live = set()
            for r in rows:
                if not r.done.is_set():
                    r.cancelled = True
                    live.add(id(r))
            if live:
                kept = []
                for q in self._queue:
                    if id(q) in live:
                        q.err = RuntimeError("request cancelled")
                        self.ledger.retired(q.rec, "cancelled")
                        q.done.set()
                    else:
                        kept.append(q)
                self._queue = kept

    def _retry_after(self) -> float:
        with self._cond:
            backlog = len(self._queue) + len(self._slot_state) + 1
        per = self.ledger.svc_ewma_s() or 0.1
        return round(max(0.05, backlog * per), 3)

    # -------------------------------------------- migration (ISSUE 16)

    def Prefill(self, prompt, max_new_tokens: int = 16,
                temperature: float = 0.0, seed: int = 0,
                top_k: int = 0, top_p: float = 1.0,
                stop_token: int = -1) -> dict:
        """Disaggregated prefill: run the prompt through chunked
        prefill (prefix reuse and all), emit the FIRST token, and park
        the prompt's KV blocks under an export id instead of taking a
        decode slot. The gateway pairs this with MigratePlan/
        ImportBlocks/MigrateDecode on a decode-class replica;
        ``max_new_tokens`` is advisory here (the decode side reserves
        for it) — this replica only ever computes token one."""
        prompt = _norm_prompt(prompt)
        if prompt.shape[0] != 1:
            raise ValueError("Prefill is single-row (the gateway "
                             "migrates one request at a time)")
        L = int(prompt.shape[1])
        if L + 1 > self.reach:
            raise ValueError(f"prompt {L} exceeds engine reach "
                             f"{self.reach}")
        self._enter_request()
        try:
            if self._draining:
                self.ledger.shed_untracked()
                raise ShedError("replica draining (scale-down in "
                                "progress); route elsewhere",
                                retry_after_s=0.05)
            f = chaos.hit("serve.admit", "prefill")
            if f is not None:
                if f.action == "delay":
                    f.sleep()
                elif f.action == "shed":
                    self.ledger.shed_untracked()
                    raise ShedError("chaos: serve.admit shed",
                                    retry_after_s=self._retry_after())
            key = (np.asarray(jax.random.PRNGKey(int(seed)))
                   if float(temperature) != 0.0
                   else np.zeros(2, np.uint32))
            row = _PagedRow(np.asarray(prompt[0]), 1, int(stop_token),
                            float(temperature), int(top_k),
                            float(top_p), key)
            row.export_id = next(self._mig_ids)
            row.rec = self.ledger.enqueued(L, 1,
                                           tp=trace.traceparent())
            with self._lock:
                self._calls += 1
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                if (self.max_queue
                        and len(self._queue) + 1 > self.max_queue):
                    self.ledger.retired(row.rec, "shed")
                    raise ShedError(
                        f"serving backlog full ({len(self._queue)} "
                        f"queued, cap {self.max_queue})",
                        retry_after_s=self._retry_after())
                self._queue.append(row)
                self._reg.gauge("serve.queue_depth").set(
                    len(self._queue))
                self._cond.notify()
            chaos.note_ok("serve.admit")
            row.done.wait()
            if row.err is not None:
                raise row.err
            return {"export_id": int(row.export_id),
                    "first_token": int(row.emitted[0]),
                    "n_tokens": L,
                    "block_tokens": self.block_tokens,
                    "reused": int(row.reused),
                    "hashes": [int(h) for h in row.hashes]}
        finally:
            self._exit_request()

    def ExportBlocks(self, export_id: int, need_idx=None,
                     kv_wire: str = "q8") -> dict:
        """Pack an export's blocks for the wire: the full blocks in
        ``need_idx`` (None = all of them) plus the unsealed partial
        tail — only what the decode side doesn't already hold rides
        the transfer (the manifest dedup MigratePlan computed)."""
        if kv_wire not in WIRE_MODES:
            raise ValueError(f"kv_wire must be one of {WIRE_MODES}, "
                             f"got {kv_wire!r}")
        with self._cond:
            row = self._exports.get(int(export_id))
        if row is None:
            raise RuntimeError(f"unknown export {export_id}")
        toks = row.prompt
        L = len(toks)
        bt = self.block_tokens
        nfull = L // bt
        want = sorted(set(int(i) for i in need_idx)
                      if need_idx is not None else range(nfull))
        if any(i < 0 or i >= nfull for i in want):
            raise ValueError(f"need_idx out of range for {nfull} "
                             f"full blocks: {want}")
        if L % bt:
            want.append(nfull)  # the partial tail always ships
        blocks: list[dict] = []
        nbytes = 0
        # Under the dispatch lock: pack reads the banks the engine
        # thread's prefill/decode programs DONATE — lock-ordered
        # dispatch keeps every read on a live buffer. The hot region
        # holds the pack path to explicit-transfers-only (the wire
        # hop is the one sanctioned sync).
        with self._lock:
            with jitwatch.hot_region("serve.migrate"):
                for i in want:
                    h = row.hashes[i] if i < nfull else None
                    payload, nb = self._migrator.pack_block(
                        self.pool.k, self.pool.v, row.table[i], h,
                        kv_wire)
                    entry = {"idx": int(i),
                             "hash": int(h) if h is not None else None}
                    entry.update(payload)
                    blocks.append(entry)
                    nbytes += nb
        return {"mode": kv_wire, "block_tokens": bt, "n_tokens": L,
                "nbytes": int(nbytes), "blocks": blocks}

    def ReleaseExport(self, export_id: int) -> bool:
        """Drop an export's parked block refs (after migration, or on
        abort). Sealed full blocks park in the LRU — the next request
        sharing the prefix still reuses them here."""
        with self._cond:
            row = self._exports.pop(int(export_id), None)
        if row is None:
            return False
        for bid in row.table:
            self.pool.deref(bid)
        row.table = []
        self._export_gauges()
        return True

    def MigratePlan(self, prompt, max_new_tokens: int = 16,
                    temperature: float = 0.0, seed: int = 0,
                    top_k: int = 0, top_p: float = 1.0,
                    stop_token: int = -1) -> dict:
        """Decode-side admission for a migrating request: reserve the
        worst-case block count BEFORE any bytes move (a transfer that
        could land nowhere is wasted wire), then walk the chain-hash
        manifest and take refs on every block already resident — the
        dedup leg: those are never re-sent. Returns the ticket plus
        ``need`` (full-block indices to ship); a pool that can't
        cover the worst case sheds typed, same contract as
        admission."""
        prompt = _norm_prompt(prompt)
        if prompt.shape[0] != 1:
            raise ValueError("MigratePlan is single-row")
        toks = np.asarray(prompt[0])
        L = int(toks.shape[0])
        max_new = int(max_new_tokens)
        if max_new <= 0:
            raise ValueError("max_new_tokens must be >= 1")
        if L + max_new > self.reach:
            raise ValueError(
                f"prompt {L} + max_new {max_new} exceeds engine "
                f"reach {self.reach}")
        bt = self.block_tokens
        need_total = -(-(L + max_new) // bt)
        if need_total > self.pool.capacity:
            raise ValueError(
                f"request needs {need_total} blocks; pool holds "
                f"{self.pool.capacity}")
        self._enter_request()
        try:
            if self._draining:
                self.ledger.shed_untracked()
                raise ShedError("replica draining (scale-down in "
                                "progress); route elsewhere",
                                retry_after_s=0.05)
            reserved = self.pool.try_reserve(need_total)
            if reserved and self._dpool is not None \
                    and not self._dpool.try_reserve(need_total):
                self.pool.unreserve(need_total)
                reserved = False
            if not reserved:
                self.ledger.shed_untracked()
                raise ShedError(
                    f"kv pool cannot cover migration: need "
                    f"{need_total} blocks, free "
                    f"{self.pool.free_blocks()}",
                    retry_after_s=self._retry_after())
            hashes = block_hashes(toks, bt)
            nfull = L // bt
            table: dict[int, int] = {}
            for i in range(nfull):
                bid = self.pool.lookup(hashes[i],
                                       toks[i * bt:(i + 1) * bt])
                if bid is not None:
                    self.pool.ref(bid)  # consumes one reserved unit
                    table[i] = bid
            resident = len(table)
            self._prefix_hits += resident
            self._prefix_misses += nfull - resident
            self._migrate_dedup_hits += resident
            self._reg.counter("serve.migrate_dedup_hits").add(resident)
            key = (np.asarray(jax.random.PRNGKey(int(seed)))
                   if float(temperature) != 0.0
                   else np.zeros(2, np.uint32))
            rec = self.ledger.enqueued(L, max_new,
                                       tp=trace.traceparent())
            rec.reused_blocks = resident
            self.ledger.migrate_begin(rec)
            need = [i for i in range(nfull) if i not in table]
            tail = L % bt
            ticket = next(self._mig_ids)
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                self._tickets[ticket] = {
                    "toks": toks, "hashes": hashes, "table": table,
                    "need": set(need), "tail": tail,
                    "max_new": max_new, "stop_token": int(stop_token),
                    "temperature": float(temperature),
                    "top_k": int(top_k), "top_p": float(top_p),
                    "key": key, "rec": rec, "resident": resident,
                    "reserve_left": need_total - resident,
                    "draft_reserve_left": (need_total
                                           if self._dpool is not None
                                           else 0),
                    "imported": not need and not tail,
                }
            self._export_gauges()
            return {"ticket": int(ticket), "need": need,
                    "resident": resident, "tail": int(tail),
                    "block_tokens": bt}
        finally:
            self._exit_request()

    def ImportBlocks(self, ticket: int, wire: dict) -> dict:
        """Land a migration wire into the pool: allocate from the
        ticket's reservation, scatter each block through the unpack
        program (bank-donating, inside the dispatch lock — imports
        INTERLEAVE with in-flight decode iterations instead of
        stalling them), then seal the full blocks so the whole fleet
        cache warms. A wire missing planned blocks raises — the
        gateway's fallback leg (local prefill on the decode replica)
        owns recovery."""
        with self._cond:
            t = self._tickets.get(int(ticket))
        if t is None:
            raise RuntimeError(f"unknown migration ticket {ticket}")
        mode = wire.get("mode")
        if mode not in WIRE_MODES:
            raise RuntimeError(f"bad kv_wire mode on wire: {mode!r}")
        bt = self.block_tokens
        if int(wire.get("block_tokens", -1)) != bt:
            raise RuntimeError(
                f"wire block_tokens {wire.get('block_tokens')} != "
                f"engine {bt}")
        toks = t["toks"]
        L = len(toks)
        nfull = L // bt
        entries = {}
        for b in wire.get("blocks", ()):
            i = int(b["idx"])
            if i not in t["table"]:  # resident blocks never re-land
                entries[i] = b
        expected = set(t["need"]) | ({nfull} if t["tail"] else set())
        missing = expected - set(entries)
        if missing:
            raise RuntimeError(
                f"migration wire truncated: missing blocks "
                f"{sorted(missing)} of {sorted(expected)}")
        for i in sorted(entries):
            bid = self.pool.alloc()  # consumes one reserved unit
            t["reserve_left"] -= 1
            t["table"][i] = bid
        with self._lock:
            with jitwatch.hot_region("serve.migrate"):
                for i in sorted(entries):
                    self.pool.k, self.pool.v = \
                        self._migrator.unpack_block(
                            self.pool.k, self.pool.v, entries[i],
                            t["table"][i], mode)
        for i in sorted(entries):
            if i < nfull:
                self.pool.seal(t["table"][i], t["hashes"][i],
                               toks[i * bt:(i + 1) * bt])
        nbytes = int(wire.get("nbytes", 0))
        t["imported"] = True
        self._migrations += 1
        self._migrate_bytes += nbytes
        self._reg.counter("serve.migrations").add(1)
        self._reg.counter("serve.migrate_bytes").add(nbytes)
        self.ledger.migrate_done(t["rec"], len(entries), nbytes)
        self._export_gauges()
        return {"imported": len(entries), "nbytes": nbytes}

    def MigrateDecode(self, ticket: int, first_token: int):
        """Own the decode lifetime of a migrated request: build the
        row from the ticket's imported table, ride the normal
        admission/decode path (slot activation runs the LOCAL draft
        prefill when speculation is armed), and return the full
        emitted token list — ``first_token`` (computed by the prefill
        replica) included."""
        self._enter_request()
        try:
            if self._draining:
                self.ledger.shed_untracked()
                raise ShedError("replica draining (scale-down in "
                                "progress); route elsewhere",
                                retry_after_s=0.05)
            with self._cond:
                t = self._tickets.get(int(ticket))
                if t is not None and not t["imported"]:
                    t = None  # leave it for AbortMigration
                else:
                    self._tickets.pop(int(ticket), None)
            if t is None:
                raise RuntimeError(
                    f"migration ticket {ticket} unknown or not "
                    f"imported")
            row = _PagedRow(t["toks"], t["max_new"], t["stop_token"],
                            t["temperature"], t["top_k"], t["top_p"],
                            t["key"])
            row.migrated = True
            row.hashes = t["hashes"]
            row.reused = t["resident"]
            row.table = [t["table"][i] for i in range(len(t["table"]))]
            row.prefill_pos = len(t["toks"])
            row.reserve_left = t["reserve_left"]
            row.draft_reserve_left = t["draft_reserve_left"]
            row.emitted = [int(first_token)]
            row.rec = t["rec"]
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                # No max_queue gate: this request was admitted (and
                # its blocks committed) at MigratePlan time.
                self._queue.append(row)
                self._reg.gauge("serve.queue_depth").set(
                    len(self._queue))
                self._cond.notify()
            row.done.wait()
            if row.err is not None:
                raise row.err
            return [int(x) for x in row.emitted]
        finally:
            self._exit_request()

    def AbortMigration(self, ticket: int) -> bool:
        """Unwind a ticket whose transfer failed (chaos, transport, a
        dead prefill replica): drop refs, return the reservation,
        retire the ledger record — the request itself is NOT lost,
        the gateway re-runs it as a local prefill on this replica."""
        with self._cond:
            t = self._tickets.pop(int(ticket), None)
        if t is None:
            return False
        for bid in t["table"].values():
            self.pool.deref(bid)
        if t["reserve_left"] > 0:
            self.pool.unreserve(t["reserve_left"])
        if self._dpool is not None and t["draft_reserve_left"] > 0:
            self._dpool.unreserve(t["draft_reserve_left"])
        self.ledger.retired(t["rec"], "cancelled")
        self._export_gauges()
        return True

    # ------------------------------------------------------------ engine

    def _engine(self) -> None:
        """Wrapper: ANY escape — clean close or an engine error — must
        fail every pending row, or callers hang in done.wait()."""
        err: Exception | None = None
        try:
            self._engine_loop()
        except Exception as e:  # noqa: BLE001 — delivered to callers
            err = e
            log.warning("paged engine died", kv={"err": repr(e)})
        with self._cond:
            self._closed = True
            stragglers, self._queue = self._queue, []
            if self._admitting is not None:
                stragglers.append(self._admitting)
                self._admitting = None
        for slot in list(self._slot_state):
            stragglers.append(self._slot_state.pop(slot))
        for r in stragglers:
            if not r.done.is_set():
                r.err = err or RuntimeError("generator actor closed")
                self.ledger.retired(r.rec, "error")
                r.done.set()

    def _engine_loop(self) -> None:
        pending_stall = 0.0
        while True:
            with self._cond:
                while (not self._queue and self._admitting is None
                       and not self._active.any() and not self._closed):
                    self._cond.wait()
                    pending_stall = 0.0  # idle time is not stall
                if self._closed:
                    return
            # Cancelled rows (their caller already got a sibling's
            # error) retire before admission: their blocks are exactly
            # the headroom the queue head is waiting on.
            for slot in list(self._slot_state):
                if self._active[slot] and self._slot_state[slot].cancelled:
                    self._retire(slot, "cancelled")
            # Admission round, bounded by the TOKEN budget: several
            # short prompts (or one chunk of a long one) may prefill,
            # but never more than prefill_chunk prompt tokens — that
            # budget IS the stall bound a co-batched decode step sees.
            # Charge it as stall only when a decode was LIVE to wait
            # on it: the chunk that activates the first row of an
            # idle engine stalls nobody (that row's own first decode
            # is not a co-batched waiter).
            if self._active.any():
                pending_stall += self._admission_round()
            else:
                # Prefill-only iteration (no decode co-batched): still
                # an engine iteration — metered, so `serve.steps`
                # advances (a burst of max_new=1 requests completing
                # entirely inside prefill must not read as a stalled
                # engine with a non-empty queue) and this round's
                # chunk accounting lands on its own record instead of
                # being charged to the next unrelated decode step.
                with self.ledger.iteration(active=0, stall_ms=0.0):
                    self._admission_round()
                pending_stall = 0.0
            if not self._active.any():
                continue
            stall_ms, pending_stall = pending_stall * 1e3, 0.0
            self._record_stall(stall_ms)
            with metrics_mod.annotate("serve.step"):
                # The iteration meter is the batch-composition seam:
                # step wall, active slots, this round's prefill split,
                # and the co-batched stall — one record per iteration
                # (a speculative window sets its ragged emitted total
                # on the meter before the scope closes).
                with self.ledger.iteration(int(self._active.sum()),
                                           stall_ms) as it:
                    self._step(it)

    def _admission_round(self) -> float:
        """Prefill up to ``prefill_chunk`` prompt tokens; returns the
        wall seconds spent (the stall charged to the next step)."""
        budget = self.prefill_chunk
        spent = 0.0
        while budget > 0:
            with self._cond:
                self._maybe_start_admission_locked()
                row = self._admitting
                if row is not None and row.cancelled:
                    # Withdrawn mid-prefill: drop its blocks +
                    # reservation.
                    self._admitting = None
            if row is not None and row.cancelled:
                self._finish_row(row, "cancelled")
                continue
            if row is None:
                break
            with metrics_mod.annotate("serve.prefill"):
                n, dur_s = self._prefill_one_chunk(row, budget)
            budget -= n
            spent += dur_s
        return spent

    def _maybe_start_admission_locked(self) -> None:
        """(under _cond) Move the queue head into admission when a
        slot is free and the pool can cover its worst case. FIFO:
        head-of-line blocking is the fairness contract."""
        if self._admitting is not None or not self._queue:
            return
        if self._active.all():
            return  # no slot to land in
        row = self._queue[0]
        if row.migrated:
            # A migrated row's worst case was reserved at MigratePlan
            # and its prompt KV imported already — admission is just
            # taking the slot.
            self._queue.pop(0)
            self.ledger.admitted(row.rec)
            self._admitting = row
            return
        need = -(-(len(row.prompt) + row.max_new) // self.block_tokens)
        reserved = self.pool.try_reserve(need)
        if reserved and self._dpool is not None \
                and not self._dpool.try_reserve(need):
            # Both pools or neither: a row admitted against the target
            # pool only would dead-end at its first draft write.
            self.pool.unreserve(need)
            reserved = False
        if not reserved:
            # Blocks come back at retire; re-checked each loop. But a
            # bounded wait only: past admit_timeout_s AT THE QUEUE
            # HEAD (not counting time spent behind other requests —
            # backlog depth must not convert momentary pressure into
            # sheds) the pool is EXHAUSTED for this request and it
            # sheds typed — the frontdoor re-routes on that, a burned
            # gateway deadline reads as replica failure.
            head_wait = self.ledger.head_refused(row.rec)
            if (self.admit_timeout_s > 0
                    and head_wait > self.admit_timeout_s):
                self._queue.pop(0)
                row.err = ShedError(
                    f"kv pool exhausted: need {need} blocks, "
                    f"free {self.pool.free_blocks()} after "
                    f"{self.admit_timeout_s:g}s at queue head",
                    retry_after_s=self._retry_after())
                self.ledger.retired(row.rec, "shed")
                row.done.set()
            return
        row.reserve_left = need
        if self._dpool is not None:
            row.draft_reserve_left = need
        self._queue.pop(0)
        self.ledger.admitted(row.rec)
        self._admitting = row

    def _chunk_prog(self, C: int):
        prog = self._chunk_progs.get(C)
        if prog is None:
            def run(params, kb, vb, tokens, start, length, table):
                return gen.prefill_paged_chunk(
                    params, tokens, start, length, self.cfg, kb, vb,
                    table)

            prog = jax.jit(run, donate_argnums=(1, 2))
            self._chunk_progs[C] = prog
        return prog

    def _prefill_one_chunk(self, row, budget: int | None = None
                           ) -> tuple[int, float]:
        """Prefill one bounded chunk of ``row`` (the admitting row,
        handed over by ``_admission_round`` — reading it back off
        ``self._admitting`` here would be a bare cross-thread read);
        returns (prompt tokens written — the budget consumed, chunk
        seconds — the stall charge)."""
        if row.migrated:
            return self._activate_migrated(row)
        toks = row.prompt
        L = len(toks)
        bt = self.block_tokens
        if row.prefill_pos < 0:
            # Reuse walk first: ref every leading resident full block.
            # Never through the LAST prompt token — its logits must be
            # computed to emit the first token, so at least one token
            # always prefills.
            row.hashes = block_hashes(toks, bt)
            cap = min(len(row.hashes), (L - 1) // bt)
            for i in range(cap):
                bid = self.pool.lookup(row.hashes[i],
                                       toks[i * bt:(i + 1) * bt])
                if bid is None:
                    break
                self.pool.ref(bid)
                row.reserve_left -= 1
                row.table.append(bid)
                row.reused += 1
            self._prefix_hits += row.reused
            self._prefix_misses += len(row.hashes) - row.reused
            row.prefill_pos = row.reused * bt
            row.rec.reused_blocks = row.reused
        start = row.prefill_pos
        n = min(self.prefill_chunk, L - start)
        if budget is not None:
            n = max(1, min(n, budget))  # always progress: a 0-token
            #                             chunk would loop forever
        while len(row.table) * bt < start + n:
            row.table.append(self.pool.alloc())
            row.reserve_left -= 1
        C = max(16, _pow2(n))
        padded = np.zeros((1, C), np.int32)
        padded[0, :n] = toks[start:start + n]
        table_arr = np.zeros(self.nb, np.int32)
        table_arr[:len(row.table)] = row.table
        # The meter stays open through the FINAL chunk's first-token
        # sampling: under async dispatch the program call returns
        # before the device runs, and the np.asarray/sample host sync
        # below is where that chunk's wall is actually paid — closing
        # the meter early would under-report the stall charge (and the
        # chunk span) by the final chunk's compute.
        cm = self.ledger.chunk(row.rec, n)
        with cm:
            # The dispatch lock orders this bank-donating call against
            # ExportBlocks' pack reads on RPC threads (ISSUE 16): a
            # pack that dispatched first still reads the pre-donation
            # buffers; one that dispatches after sees the NEW bank
            # refs — never a half-donated alias.
            with self._lock:
                logits, self.pool.k, self.pool.v = self._chunk_prog(C)(
                    self.params, self.pool.k, self.pool.v,
                    jnp.asarray(padded), jnp.int32(start), jnp.int32(n),
                    jnp.asarray(table_arr))
            row.prefill_pos += n
            done = row.prefill_pos >= L
            if done:
                # Prompt fully resident: seal the freshly-computed
                # full blocks (reused ones are already in the index)
                # and emit the first token.
                for i in range(row.reused, len(row.hashes)):
                    self.pool.seal(row.table[i], row.hashes[i],
                                   toks[i * bt:(i + 1) * bt])
                if row.temperature == 0.0:
                    first = int(np.asarray(logits)[0].argmax())
                else:
                    first = int(self._sample_first(
                        logits, jnp.asarray(row.key),
                        jnp.float32(row.temperature),
                        jnp.int32(row.top_k),
                        jnp.float32(row.top_p)))
                if (self._dpool is not None and row.max_new > 1
                        and not (row.stop_token >= 0
                                 and first == row.stop_token)):
                    # The row will take a slot: give the draft model
                    # its prompt KV (inside this chunk's meter, so the
                    # activation cost is a charged stall, not free).
                    self._draft_prefill(row, toks, L)
        self._prefill_chunks += 1
        self._prefill_tokens += n
        if not done:
            return n, cm.dur_s
        # The TTFT stamp: the first token exists on the host here.
        self.ledger.first_token(row.rec)
        row.emitted.append(first)
        with self._cond:
            self._admitting = None
        self._export_gauges()
        if row.export_id is not None:
            self._stash_export(row)
            return n, cm.dur_s
        if (row.max_new == 1
                or (row.stop_token >= 0 and first == row.stop_token)):
            self._finish_row(row,
                             "stop" if (row.stop_token >= 0
                                        and first == row.stop_token)
                             else "complete")
            return n, cm.dur_s
        self._take_slot(row, first, L)
        return n, cm.dur_s

    def _take_slot(self, row: _PagedRow, first: int, L: int) -> None:
        """Land a prompt-complete row in a free slot (the caller
        guaranteed one exists — admission gates on it)."""
        slot = int(np.flatnonzero(~self._active)[0])
        self._slot_state[slot] = row
        self._tables[slot] = 0
        self._tables[slot, :len(row.table)] = row.table
        self._nalloc[slot] = len(row.table)
        self._tok[slot] = first
        self._pos[slot] = L
        self._active[slot] = True
        self._keys[slot] = row.key
        self._temps[slot] = row.temperature
        self._topk[slot] = row.top_k
        self._topp[slot] = row.top_p
        self._eidx[slot] = 1
        if self._dpool is not None:
            self._dtables[slot] = 0
            self._dtables[slot, :len(row.draft_table)] = \
                row.draft_table
            self._dnalloc[slot] = len(row.draft_table)
            self._sctr[slot] = 0
            self._dpos[slot] = L  # draft prefill wrote 0..L-1
        self._dev = None  # slot state changed: re-upload next step
        self._sdev = None

    def _activate_migrated(self, row: _PagedRow) -> tuple[int, float]:
        """Land an imported migration in a slot: no prefill — the
        prompt KV arrived over the wire — but when speculation is
        armed the DRAFT model prefills locally from the prompt tokens
        (draft KV is draft-params specific and never rides the wire),
        so migration cannot introduce draft/target disagreement and
        the accept rate is untouched by the transfer. The TTFT stamp
        here is the decode replica's own attribution: plan →
        activation, the migration leg included."""
        toks = row.prompt
        L = len(toks)
        first = row.emitted[0]
        cm = self.ledger.chunk(row.rec, 0)
        with cm:
            if (self._dpool is not None and row.max_new > 1
                    and not (row.stop_token >= 0
                             and first == row.stop_token)):
                self._draft_prefill(row, toks, L)
        self.ledger.first_token(row.rec)
        with self._cond:
            self._admitting = None
        self._export_gauges()
        if (row.max_new == 1
                or (row.stop_token >= 0 and first == row.stop_token)):
            self._finish_row(row,
                             "stop" if (row.stop_token >= 0
                                        and first == row.stop_token)
                             else "complete")
            return 0, cm.dur_s
        self._take_slot(row, first, L)
        return 0, cm.dur_s

    def _stash_export(self, row: _PagedRow) -> None:
        """Disaggregated prefill complete: park the prompt's block
        refs under the export id (ExportBlocks packs from them;
        ReleaseExport drops them) and return every unused reservation
        unit now — an export row never decodes here, so holding its
        decode worst-case would starve admission for nothing."""
        if row.reserve_left > 0:
            self.pool.unreserve(row.reserve_left)
            row.reserve_left = 0
        if self._dpool is not None and row.draft_reserve_left > 0:
            self._dpool.unreserve(row.draft_reserve_left)
            row.draft_reserve_left = 0
        with self._cond:
            self._exports[row.export_id] = row
        self.ledger.retired(row.rec, "complete")
        row.done.set()

    def _step(self, meter=None) -> None:
        """One engine iteration over the live slots: a speculative
        window when speculation is armed and earns its depth, else the
        plain one-token batched decode step."""
        if self._spec is not None:
            k_eff = self._spec_k_eff()
            if k_eff >= 1:
                # The speculation chaos seam: "reject" poisons the
                # window (this iteration falls back to the plain step
                # — correct tokens, just slower), "delay" stalls the
                # draft forward; the next committed window beacons the
                # paired recovery.
                f = chaos.hit("serve.spec", f"k={k_eff}")
                if f is not None and f.action == "delay":
                    f.sleep()
                    f = None
                if f is None:
                    self._spec_step(k_eff, meter)
                    return
        self._plain_step()

    def _plain_step(self) -> None:
        # Boundary crossings first: a slot whose next write lands past
        # its allocated blocks materializes one from its reservation
        # (guaranteed — admission reserved the worst case).
        for slot in np.flatnonzero(self._active):
            if self._pos[slot] == self._nalloc[slot] * self.block_tokens:
                row = self._slot_state[slot]
                bid = self.pool.alloc()
                row.reserve_left -= 1
                row.table.append(bid)
                self._tables[slot, self._nalloc[slot]] = bid
                self._nalloc[slot] += 1
                self._dev = None  # tables changed: re-upload
                self._sdev = None
        sampled = bool((self._temps[self._active] > 0.0).any())
        if self._dev is None:
            self._dev = {
                "tok": jnp.asarray(self._tok),
                "pos": jnp.asarray(self._pos),
                "tables": jnp.asarray(self._tables),
                "active": jnp.asarray(self._active),
                "keys": jnp.asarray(self._keys),
                "eidx": jnp.asarray(self._eidx),
                "temps": jnp.asarray(self._temps),
                "topk": jnp.asarray(self._topk),
                "topp": jnp.asarray(self._topp),
            }
        d = self._dev
        self._steps += 1
        self._max_live = max(self._max_live, int(self._active.sum()))
        with self._lock:
            # Armed (PTYPE_JITWATCH=1), the hot region makes any
            # unsanctioned implicit transfer into the decode step
            # raise at the call — the steady-state step re-uploads
            # NOTHING, and jitwatch counts its compiles.
            with jitwatch.hot_region("serve.decode"):
                (self.pool.k, self.pool.v, nxt, d["pos"],
                 d["eidx"]) = self._engine_step(
                    sampled, self.params, self.pool.k, self.pool.v,
                    d["tok"], d["pos"], d["tables"], d["active"],
                    d["keys"], d["eidx"], d["temps"], d["topk"],
                    d["topp"])
        d["tok"] = nxt
        nxt_host = np.array(nxt)  # host mirror for retire bookkeeping
        self._pos[self._active] += 1
        self._eidx[self._active] += 1
        self._tok = nxt_host
        live = [(slot, self._slot_state[slot])
                for slot in list(self._slot_state)
                if self._active[slot]]
        # One shared stamp for every row that just emitted — the
        # per-token decode-delta trail behind the TPOT histogram.
        self.ledger.tokens_emitted([row.rec for _, row in live])
        for slot, row in live:
            t = int(nxt_host[slot])
            row.emitted.append(t)
            if row.stop_token >= 0 and t == row.stop_token:
                self._retire(slot, "stop")
            elif len(row.emitted) >= row.max_new:
                self._retire(slot, "complete")
        if self._steps % 32 == 0:
            self._export_gauges()  # sampler cadence is ~50 ms+; the
            #                        retire/admission exports keep the
            #                        block gauges fresh between these.

    # ------------------------------------------------------ speculation

    def _draft_prefill(self, row: _PagedRow, toks, L: int) -> None:
        """Whole-prompt draft prefill into the row's draft tables at
        activation (no prefix reuse — draft KV is draft-params
        specific, and the draft model is the cheap one). Runs inside
        the final chunk's meter; the trailing block wait pins the
        draft compute's wall there instead of deferring it into the
        first speculation window under async dispatch."""
        bt = self.block_tokens
        while len(row.draft_table) * bt < L:
            row.draft_table.append(self._dpool.alloc())
            row.draft_reserve_left -= 1
        table_arr = np.zeros(self.nb, np.int32)
        table_arr[:len(row.draft_table)] = row.draft_table
        C = max(16, _pow2(L))
        padded = np.zeros((1, C), np.int32)
        padded[0, :L] = toks
        _, self._dpool.k, self._dpool.v = self._draft_chunk_prog(C)(
            self._spec.draft_params, self._dpool.k, self._dpool.v,
            jnp.asarray(padded), jnp.int32(0), jnp.int32(L),
            jnp.asarray(table_arr))
        self._dpool.k.block_until_ready()

    def _draft_chunk_prog(self, C: int):
        prog = self._draft_chunk_progs.get(C)
        if prog is None:
            dcfg = self._spec.draft_cfg

            def run(params, kb, vb, tokens, start, length, table):
                return gen.prefill_paged_chunk(
                    params, tokens, start, length, dcfg, kb, vb,
                    table)

            prog = jax.jit(run, donate_argnums=(1, 2))
            self._draft_chunk_progs[C] = prog
        return prog

    def _draft_catch_up(self, slot: int, row: _PagedRow) -> None:
        """Backfill the draft pool's KV for positions the row
        committed through PLAIN decode steps (chaos-rejected windows,
        adaptive k=0 stretches, remaining-1 tails): one chunked draft
        pass over the known committed tokens in
        ``[_dpos, pos)`` — without it, every later window's draft
        forward attends through garbage at those positions, silently
        depressing the accept rate (including the k=1 re-probe that
        decides whether a backed-off draft re-earns its depth)."""
        start = int(self._dpos[slot])
        end = int(self._pos[slot])
        if start >= end:
            return
        seq = np.concatenate(
            [np.asarray(row.prompt, np.int32),
             np.asarray(row.emitted, np.int32)])
        n = end - start
        C = max(16, _pow2(n))
        padded = np.zeros((1, C), np.int32)
        padded[0, :n] = seq[start:end]
        table_arr = np.zeros(self.nb, np.int32)
        table_arr[:len(row.draft_table)] = row.draft_table
        _, self._dpool.k, self._dpool.v = self._draft_chunk_prog(C)(
            self._spec.draft_params, self._dpool.k, self._dpool.v,
            jnp.asarray(padded), jnp.int32(start), jnp.int32(n),
            jnp.asarray(table_arr))
        self._dpos[slot] = end

    def _spec_k_eff(self) -> int:
        """Proposal depth for this iteration: the adaptive depth,
        capped so no window can overshoot the deepest live row's
        remaining budget (k ≤ remaining − 1 keeps every write inside
        the reservation the row admitted with — the worst-case cover
        the extended pool audit asserts). 0 = plain decode (depth
        backed off to nothing, or every live row is one token from
        done); while disabled, a k=1 probe window re-runs every
        ``probe_every`` plain iterations."""
        if self._k_cur == 0:
            self._spec_probe_left -= 1
            if self._spec_probe_left > 0:
                return 0
            self._k_cur = 1
            # Fresh evidence decides: park the EWMA at the floor so
            # the probe window's own accept rate dominates via alpha.
            self._spec_ewma = self._spec.accept_floor
        live = [self._slot_state[s]
                for s in np.flatnonzero(self._active)]
        if not live:
            return 0
        max_r = max(r.max_new - len(r.emitted) for r in live)
        return max(0, min(self._k_cur, max_r - 1))

    def _spec_adapt(self) -> None:
        """Adaptive k (docs/PERF.md "Speculative decoding"): shed one
        proposal depth per window while the accept-rate EWMA sits
        under the floor; at depth 1 and under half the floor, disable
        outright (plain decode + periodic k=1 re-probe — a stale
        draft must not tax every token forever); climb back one depth
        at a time once the rate clears the floor with margin."""
        sp = self._spec
        ew = self._spec_ewma
        if ew < sp.accept_floor:
            if self._k_cur > 1:
                self._k_cur -= 1
            elif self._k_cur == 1 and ew < sp.accept_floor / 2:
                self._k_cur = 0
                self._spec_probe_left = int(sp.probe_every)
        elif ew > sp.accept_floor + 0.15 and self._k_cur < sp.k:
            self._k_cur += 1

    def _window_prog(self, W: int, sampled: bool):
        """ONE fused program per (window width, sampled): draft scan →
        batched target verify → acceptance, with the write routing
        computed in-graph from the device-resident tables — a window
        costs one dispatch and one host sync, whatever k is. That
        amortization (weights read once per window on memory-bound
        hardware, dispatch+sync paid once per window on a host mesh)
        is the whole speedup; three separate dispatches plus
        host-built routing arrays measurably gave it back."""
        key = (W, sampled)
        prog = self._window_progs.get(key)
        if prog is None:
            dcfg = self._spec.draft_cfg
            bt = self.block_tokens
            nb = self.nb

            def run(tparams, dparams, tok, pos, kb, vb, dkb, dvb,
                    tables, dtables, nalloc, dnalloc, active, keys,
                    sctr, temps, topk, topp):
                ap = pos[:, None] + jnp.arange(W)[None, :]  # (B, W)
                blk = jnp.minimum(ap // bt, nb - 1)
                wr_o = ap % bt
                # Inactive lanes and positions past a row's allocated
                # span (an overshooting window on a nearly-done row)
                # scatter to the trash block.
                ok_t = active[:, None] & (ap // bt < nalloc[:, None])
                wr_b = jnp.where(
                    ok_t, jnp.take_along_axis(tables, blk, axis=1), 0)
                ok_d = active[:, None] & (ap // bt < dnalloc[:, None])
                dwr_b = jnp.where(
                    ok_d, jnp.take_along_axis(dtables, blk, axis=1),
                    0)
                prop, dlg, dkb, dvb = gen.draft_propose_paged(
                    dparams, tok, pos, dcfg, dkb, dvb, dtables,
                    dwr_b, wr_o, keys, sctr, temps, topk, topp,
                    n_steps=W, sampled=sampled)
                toks_w = jnp.concatenate(
                    [tok[:, None], prop[:, :W - 1]], axis=1)
                tlg, kb, vb = gen.verify_step_paged(
                    tparams, toks_w, pos, self.cfg, kb, vb, tables,
                    wr_b, wr_o)
                out, n_acc = gen.spec_accept_rows(
                    prop[:, :W - 1], dlg[:, :W - 1], tlg, keys, sctr,
                    temps, topk, topp, sampled=sampled)
                return out, n_acc, kb, vb, dkb, dvb

            prog = jax.jit(run, donate_argnums=(4, 5, 6, 7))
            self._window_progs[key] = prog
        return prog

    def _spec_step(self, k_eff: int, meter=None) -> None:
        """One speculation window over the live slots: the draft
        proposes ``k_eff`` tokens per slot (one scanned program), the
        target verifies all ``k_eff + 1`` positions in ONE batched
        forward through the per-slot gather path, and acceptance
        sampling commits each row's accepted prefix plus one
        corrected/bonus token — ONE dispatch and ONE host sync per
        window instead of one per token. Rejected positions roll back
        as a position rewind (their writes sit in the row's own
        reserved blocks, masked by the position limit until
        overwritten) — block tables are never
        truncated-and-reallocated."""
        W = k_eff + 1
        bt = self.block_tokens
        live = [int(s) for s in np.flatnonzero(self._active)]
        # Worst-case block cover for the window, BOTH pools: every
        # allocation consumes a unit the row reserved at admission
        # (the span cap keeps pos + W inside ceil(span / bt) blocks,
        # so reservation exhaustion is structurally impossible — the
        # extended check_invariants audit pins that).
        for slot in live:
            row = self._slot_state[slot]
            span = len(row.prompt) + row.max_new
            need_tokens = min(int(self._pos[slot]) + W, span)
            while self._nalloc[slot] * bt < need_tokens:
                bid = self.pool.alloc()
                row.reserve_left -= 1
                self._tables[slot, self._nalloc[slot]] = bid
                self._nalloc[slot] += 1
                row.table.append(bid)
                self._sdev = None  # tables changed: re-mirror
            while self._dnalloc[slot] * bt < need_tokens:
                bid = self._dpool.alloc()
                row.draft_reserve_left -= 1
                self._dtables[slot, self._dnalloc[slot]] = bid
                self._dnalloc[slot] += 1
                row.draft_table.append(bid)
                self._sdev = None
            # Positions committed through plain steps left draft-KV
            # holes: backfill before this window's draft attends
            # through them.
            self._draft_catch_up(slot, row)
        if self._sdev is None:
            # Device mirror of the SLOW-moving slot state (tables,
            # routing bounds, sampling params): refreshed only on
            # admission/retire/boundary allocation — the steady-state
            # window uploads just tok/pos/sctr.
            self._sdev = {
                "tables": jnp.asarray(self._tables),
                "dtables": jnp.asarray(self._dtables),
                "nalloc": jnp.asarray(self._nalloc),
                "dnalloc": jnp.asarray(self._dnalloc),
                "active": jnp.asarray(self._active),
                "keys": jnp.asarray(self._keys),
                "temps": jnp.asarray(self._temps),
                "topk": jnp.asarray(self._topk),
                "topp": jnp.asarray(self._topp),
            }
        sd = self._sdev
        sampled = bool((self._temps[self._active] > 0.0).any())
        self._steps += 1
        self._max_live = max(self._max_live, len(live))
        tok_dev = jnp.asarray(self._tok)
        pos_dev = jnp.asarray(self._pos)
        sctr_dev = jnp.asarray(self._sctr)
        with self._lock:
            with jitwatch.hot_region("serve.spec_window"):
                (out_toks, n_acc, self.pool.k, self.pool.v,
                 self._dpool.k, self._dpool.v) = \
                    self._window_prog(W, sampled)(
                        self.params, self._spec.draft_params,
                        tok_dev, pos_dev,
                        self.pool.k, self.pool.v, self._dpool.k,
                        self._dpool.v, sd["tables"], sd["dtables"],
                        sd["nalloc"], sd["dnalloc"], sd["active"],
                        sd["keys"], sctr_dev, sd["temps"],
                        sd["topk"], sd["topp"])
        out_host = np.asarray(out_toks)   # the window's ONE host sync
        acc_host = np.asarray(n_acc)
        emit_recs, emit_counts = [], []
        retires: list[tuple[int, str]] = []
        total_acc = total_emit = 0
        for slot in live:
            row = self._slot_state[slot]
            remaining = row.max_new - len(row.emitted)
            a = int(acc_host[slot])
            toks = [int(t) for t in out_host[slot, :min(a + 1,
                                                        remaining)]]
            reason = None
            if row.stop_token >= 0 and row.stop_token in toks:
                # Stop mid-window: commit through the stop token only
                # (the tail past it was never part of the sequence).
                toks = toks[:toks.index(row.stop_token) + 1]
                reason = "stop"
            row.emitted.extend(toks)
            n = len(toks)
            self._pos[slot] += n
            self._eidx[slot] += n
            self._tok[slot] = toks[-1]
            # Draft KV is correct through the accepted prefix; the
            # new position's token (this window's corrected/bonus, or
            # a rejected slot's overwrite) is written by the NEXT
            # window's first draft step.
            self._dpos[slot] = self._pos[slot]
            self._sctr[slot] += W + 1
            total_acc += a
            total_emit += n
            emit_recs.append(row.rec)
            emit_counts.append(n)
            if reason is None and len(row.emitted) >= row.max_new:
                reason = "complete"
            if reason is not None:
                retires.append((slot, reason))
        self.ledger.tokens_emitted(emit_recs, emit_counts)
        rate = total_acc / max(1, k_eff * len(live))
        al = self._spec.ewma_alpha
        self._spec_ewma = (rate if self._spec_windows == 0
                           else al * rate + (1 - al) * self._spec_ewma)
        self._spec_windows += 1
        self.ledger.spec_window(k_eff * len(live), total_acc,
                                total_emit, self._spec_ewma)
        if meter is not None:
            meter.decode_tokens = total_emit
        chaos.note_ok("serve.spec")
        for slot, reason in retires:
            self._retire(slot, reason)
        if self._spec.adaptive:
            self._spec_adapt()
        # Ragged per-slot advances: the host copy is authoritative.
        self._dev = None
        if self._steps % 32 == 0:
            self._export_gauges()

    def check_spec_reservations(self) -> list[str]:
        """Audit both pools' reservation discipline against the
        worst-case speculative advance of every live row (the ISSUE 12
        :meth:`BlockPool.check_invariants` extension). Call from the
        engine thread (tests wrap ``_spec_step``) — row state is
        mid-mutation on any other thread."""
        if self._spec is None:
            return []
        rows_t, rows_d = [], []
        for slot in np.flatnonzero(self._active):
            row = self._slot_state.get(int(slot))
            if row is None:
                continue
            remaining = row.max_new - len(row.emitted)
            adv = min(self._k_cur, max(0, remaining - 1)) + 1
            rows_t.append((int(self._pos[slot]),
                           int(self._nalloc[slot]),
                           row.reserve_left, adv))
            rows_d.append((int(self._pos[slot]),
                           int(self._dnalloc[slot]),
                           row.draft_reserve_left, adv))
        bad = self.pool.check_invariants(spec_rows=rows_t)
        bad += [f"draft: {b}"
                for b in self._dpool.check_invariants(
                    spec_rows=rows_d)]
        return bad

    def _retire(self, slot: int, reason: str = "complete") -> None:
        self._active[slot] = False
        self._temps[slot] = 0.0
        self._dev = None  # slot state changed: re-upload next step
        self._sdev = None
        self._finish_row(self._slot_state.pop(slot), reason)
        self._export_gauges()

    def _finish_row(self, row: _PagedRow,
                    reason: str = "complete") -> None:
        for bid in row.table:
            self.pool.deref(bid)
        if row.reserve_left > 0:
            self.pool.unreserve(row.reserve_left)
        row.reserve_left = 0
        if self._dpool is not None:
            for bid in row.draft_table:
                self._dpool.deref(bid)
            row.draft_table = []
            if row.draft_reserve_left > 0:
                self._dpool.unreserve(row.draft_reserve_left)
            row.draft_reserve_left = 0
        self.ledger.retired(row.rec, reason)
        row.done.set()

    # -------------------------------------------------------- telemetry

    def _record_stall(self, stall_ms: float) -> None:
        self._last_stall_ms = stall_ms
        if stall_ms > self._max_stall_ms:
            self._max_stall_ms = stall_ms

    def begin_drain(self) -> None:
        """Engine drain seam (ISSUE 13): flip the admission gate —
        Generate sheds typed from here on — and let the engine loop
        run the queue + live slots dry. Lifecycle lands in Info() and
        the ``serve.lifecycle`` gauge so the gateway pool (which sorts
        draining replicas last) and ``obs serve`` both see it."""
        super().begin_drain()
        self._export_gauges()

    def drained(self) -> bool:
        """True once draining AND nothing is admitted, queued, live
        in a slot, or still blocked in a caller thread — the exact
        point where deregister-and-exit loses zero requests."""
        if not self._draining:
            return False
        with self._load_lock:
            if self._in_flight:
                return False
        with self._cond:
            if self._queue or self._admitting is not None:
                return False
            if self._exports or self._tickets:
                # An in-flight migration still references this
                # replica's blocks (export refs on the prefill side,
                # a planned-but-undecoded ticket on the decode side)
                # — exiting now would strand it mid-transfer.
                return False
        return not self._active.any()

    def _export_gauges(self) -> None:
        reg = self._reg
        reg.gauge("serve.lifecycle").set(
            LIFECYCLE_CODES.get(self.lifecycle, 2))
        reg.gauge("serve.class").set(
            SERVE_CLASS_CODES.get(self.serve_class, 0))
        # Open migration legs on this replica (tickets planned but not
        # yet decoded) — the migration-stall health rule pages when
        # this sits non-zero while serve.migrations stops advancing.
        reg.gauge("serve.migrate_inflight").set(
            len(self._tickets) + len(self._exports))
        st = self.pool.stats()
        reg.gauge("serve.kv_free_blocks").set(st["kv_free_blocks"])
        reg.gauge("serve.kv_util_pct").set(st["kv_util_pct"])
        reg.gauge("serve.prefix_hit_rate").set(self.prefix_hit_rate())
        reg.gauge("serve.prefill_stall_ms").set(
            round(self._max_stall_ms, 3))
        # len() read without _cond on purpose: a point-in-time gauge,
        # and the exporters run on the engine thread mid-admission.
        reg.gauge("serve.queue_depth").set(
            len(self._queue))  # ptlint: disable=PT013 -- point-in-time gauge; list len is GIL-atomic and the engine thread must not contend admission for a sample
        # The kv.* pressure sample the serving alert rules key on.
        self.ledger.kv_sample(st, self.prefix_hit_rate())

    def prefix_hit_rate(self) -> float:
        total = self._prefix_hits + self._prefix_misses
        return round(self._prefix_hits / total, 4) if total else 0.0

    def Info(self) -> dict:
        info = super().Info()
        info["n_slots"] = self.n_slots
        info["engine_steps"] = self._steps
        info["max_live_slots"] = self._max_live
        # Disaggregated-serving surface (ISSUE 16): the class the
        # gateway's two-stage router and the per-class reconcilers
        # key on, plus the migration counters `obs serve` renders.
        info["serve_class"] = self.serve_class
        info["migrations"] = self._migrations
        info["migrate_bytes"] = self._migrate_bytes
        info["migrate_dedup_hits"] = self._migrate_dedup_hits
        with self._cond:
            info["migrate_inflight"] = (len(self._tickets)
                                        + len(self._exports))
        with self._cond:
            info["queue_depth"] = len(self._queue)
        info["live_slots"] = int(self._active.sum())
        info.update(self.pool.stats())
        info["block_tokens"] = self.block_tokens
        info["prefill_chunk"] = self.prefill_chunk
        info["admit_timeout_s"] = self.admit_timeout_s
        info["prefix_hits"] = self._prefix_hits
        info["prefix_misses"] = self._prefix_misses
        info["prefix_hit_rate"] = self.prefix_hit_rate()
        info["prefill_chunks"] = self._prefill_chunks
        info["prefill_tokens"] = self._prefill_tokens
        info["prefill_stall_ms"] = round(self._max_stall_ms, 3)
        info["prefill_stall_last_ms"] = round(self._last_stall_ms, 3)
        # Serving-ledger surface (ISSUE 10): TTFT/TPOT/e2e tails the
        # gateway's probes and `obs serve` read, plus the recent
        # per-request TTFT samples the pool drains into the fleet SLO
        # tracker (sequence-tagged so probes never double-count).
        info.update(self.ledger.summary())
        info["ttft_recent"] = self.ledger.ttft_recent()
        if self._spec is not None:
            # Speculation surface (ISSUE 12): the accept rate the
            # gateway probes carry fleet-wide (same plumbing as
            # kv_free_blocks / prefix_hit_rate) plus the adaptive-k
            # state an operator diagnoses a collapse with. Totals
            # come from the ledger (the one accumulation home).
            prop, acc, toks = self.ledger.spec_totals()
            info["spec_k"] = int(self._spec.k)
            info["spec_k_cur"] = self._k_cur
            info["spec_windows"] = self._spec_windows
            info["spec_proposed"] = prop
            info["spec_accepted"] = acc
            info["spec_tokens"] = toks
            if prop:
                # Only once speculation actually RAN: the gateway
                # snapshot / runbook contract distinguishes "never
                # speculated" (absent, renders "-") from "collapsed
                # to 0" — a fresh idle replica must not fake a 0.0.
                info["spec_accept_rate"] = round(acc / prop, 4)
            info["spec_accept_ewma"] = round(self._spec_ewma, 4)
            info["kv_draft_free_blocks"] = self._dpool.free_blocks()
        return info

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
