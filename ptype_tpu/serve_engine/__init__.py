"""Paged KV-cache serving engine (ISSUE 9).

The serving-side analogue of the training data plane's bucketed
collectives: one device-resident bank of fixed-size KV blocks shared by
every live sequence, so resident cache memory tracks *actual* token
counts instead of ``n_slots × reach``:

- :mod:`~ptype_tpu.serve_engine.blocks` — the :class:`BlockPool`
  (ref-counted fixed-size blocks, per-sequence block tables, LRU
  eviction of released blocks, content-addressing by the same FNV-1a
  prefix hash chain the gateway's affinity routing keys on);
- :mod:`~ptype_tpu.serve_engine.engine` — the
  :class:`PagedGeneratorActor` continuous engine rebased onto the
  pool: chunked prefill (a long prompt can no longer stall co-batched
  decodes for its whole prefill), prefix reuse (an affinity-routed
  request skips prefill for every already-resident full block), and
  per-slot RNG sampling on the continuous path.

The host-mesh probe behind ``bench.py --serve``'s
``serve_prefix_hit_speedup`` / ``serve_kv_util_pct`` /
``serve_prefill_stall_ms`` tail fields is ``_serve_paged_probe`` in
the top-level ``bench.py``.

The decode attention path is an XLA gather through the block table
(``models/generate.decode_step_paged``); the optional Pallas kernel
lives in :mod:`ptype_tpu.ops.paged_attention`, gated behind the same
``check_tpu_lowering`` machinery as the flash kernel.
"""

from ptype_tpu.serve_engine.blocks import (BlockPool, block_hashes,
                                           prefix_affinity_key)
from ptype_tpu.serve_engine.engine import (SERVE_CLASS_CODES,
                                           SERVE_CLASSES,
                                           PagedGeneratorActor,
                                           SpecConfig)
from ptype_tpu.serve_engine.migrate import WIRE_MODES, KVMigrator

__all__ = ["BlockPool", "block_hashes", "prefix_affinity_key",
           "PagedGeneratorActor", "SpecConfig", "SERVE_CLASSES",
           "SERVE_CLASS_CODES", "KVMigrator", "WIRE_MODES"]
