"""Block pool: device-resident paged KV storage + content addressing.

One bank of fixed-size KV blocks ``(L, n_blocks, block_tokens, Kh,
Dh)`` backs every live sequence on a serving actor. Sequences hold
*block tables* (ordered block ids); position ``p`` of a sequence lives
in table entry ``p // block_tokens`` at offset ``p % block_tokens``.
Three lifetimes per block:

- **active** (refcount > 0): owned by one or more live sequences —
  prompt blocks shared through prefix reuse carry refcount > 1;
- **cached** (refcount 0, content-hashed): released but kept resident
  in an LRU so a later request with the same prefix re-refs it without
  recomputing prefill — eviction (oldest first) only happens when an
  allocation needs the slot;
- **free**: never written, or evicted.

Admission is deadlock-free by *reservation*: a request reserves its
worst-case block count (``ceil((prompt + max_new) / block_tokens)``)
up front, and every later acquisition — a prefix-reuse ref or a fresh
allocation, including the decode-time boundary crossings — consumes
one reserved unit, so a decode step can never find the pool empty.
``free_blocks()`` (free + cached − reserved) is the admission headroom
the gateway's probes read as ``kv_free_blocks``.

Content addressing uses a hash *chain* over block token contents built
on :func:`ptype_tpu.rpc.fnv32a` — the SAME hash the gateway's
prefix-affinity routing keys on (gateway/pool.py pins
``fnv32a(affinity_key)``), so a request routed to its affinity replica
lands where its prefix blocks are actually resident.
:func:`prefix_affinity_key` derives the routing key from a prompt
(first block's chain hash); 32-bit chains can collide, so the pool
stores each sealed block's token contents and :meth:`BlockPool.lookup`
verifies them — reuse is exact, never probabilistic.

Block 0 is a reserved *trash* block: padded/inactive lanes of the
batched engine step scatter their garbage writes there, so a masked
write can never corrupt a real (possibly shared) block.
"""

from __future__ import annotations

import collections

from ptype_tpu import lockcheck

import jax.numpy as jnp

from ptype_tpu.models import transformer as tfm
from ptype_tpu.rpc import fnv32a

#: Sublane width of the f32 Mosaic tile: block_tokens must divide by
#: it so a (block_tokens, head_dim) block tile is layout-aligned on
#: TPU (the gather path tolerates anything; the Pallas kernel and the
#: lane-aligned bank layout do not).
SUBLANES = 8


def block_hashes(tokens, block_tokens: int) -> list[int]:
    """Chain hashes for every FULL block of ``tokens``: ``h_i`` covers
    tokens ``[0, (i+1)·block_tokens)`` — block i's hash commits to the
    whole prefix through it, so equal hashes mean equal *prefixes*,
    not just equal blocks (the property reuse needs)."""
    out: list[int] = []
    h: int | None = None
    for i in range(len(tokens) // block_tokens):
        blk = tokens[i * block_tokens:(i + 1) * block_tokens]
        body = ",".join(str(int(t)) for t in blk)
        prefix = "" if h is None else f"{h:08x}|"
        h = fnv32a(prefix + body)
        out.append(h)
    return out


def prefix_affinity_key(tokens, block_tokens: int) -> str | None:
    """Gateway affinity key for a prompt: the FIRST full block's chain
    hash, hex-tagged. Keying on the first block (not the longest
    prefix) routes every request sharing ≥ one block to the same
    replica — the block-granular sharing the pool can actually serve.
    None when the prompt has no full block (nothing reusable)."""
    hs = block_hashes(tokens[:block_tokens], block_tokens)
    return f"kv:{hs[0]:08x}" if hs else None


class BlockPool:
    """Ref-counted, content-addressed pool of KV blocks on device.

    Thread contract: mutating calls come from the one engine thread;
    :meth:`stats` / :meth:`free_blocks` are read from Info/probe
    threads — all state sits under one lock.
    """

    def __init__(self, cfg: tfm.TransformerConfig, n_blocks: int,
                 block_tokens: int):
        if block_tokens % SUBLANES:
            raise ValueError(
                f"block_tokens {block_tokens} must divide by "
                f"{SUBLANES} (sublane-aligned KV tiles)")
        if n_blocks < 2:
            raise ValueError("n_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        self.block_tokens = int(block_tokens)
        self.n_blocks = int(n_blocks)
        shape = (cfg.n_layers, n_blocks, block_tokens, cfg.kv_heads,
                 cfg.head_dim)
        #: The banks. The engine owns these references — jitted
        #: steps/prefills donate and replace them.
        self.k = jnp.zeros(shape, cfg.dtype)
        self.v = jnp.zeros(shape, cfg.dtype)
        self._lock = lockcheck.lock("serve_engine.pool")
        # Block 0 never allocated: the trash target for masked writes.
        self._free: list[int] = list(range(1, n_blocks))
        #: LRU of refcount-0 hashed blocks (oldest first).
        self._cached: collections.OrderedDict[int, None] = \
            collections.OrderedDict()
        self._ref: dict[int, int] = {}
        self._hash_of: dict[int, int] = {}
        self._by_hash: dict[int, int] = {}
        self._content: dict[int, tuple] = {}
        self._reserved = 0
        self.evictions = 0
        self.sealed = 0

    # --------------------------------------------------------- capacity

    @property
    def capacity(self) -> int:
        """Allocatable blocks (total minus the trash block)."""
        return self.n_blocks - 1

    def _available(self) -> int:
        return len(self._free) + len(self._cached)

    def free_blocks(self) -> int:
        """Admission headroom: blocks a NEW reservation could still
        claim (free + cached − already reserved)."""
        with self._lock:
            return max(0, self._available() - self._reserved)

    def used_blocks(self) -> int:
        """Blocks held by live sequences (refcount > 0)."""
        with self._lock:
            return len(self._ref)

    def try_reserve(self, n: int) -> bool:
        """Claim ``n`` future acquisitions; False when the pool can't
        cover them (the caller queues or sheds — never dead-ends a
        decode mid-flight)."""
        with self._lock:
            if self._available() - self._reserved < n:
                return False
            self._reserved += n
            return True

    def unreserve(self, n: int) -> None:
        """Return unused reservation units (early stop / retire)."""
        with self._lock:
            self._reserved = max(0, self._reserved - n)

    # ------------------------------------------------------- lifecycle

    def alloc(self) -> int:
        """Materialize one reserved unit into a fresh block id: free
        list first, else evict the LRU cached block (its hash leaves
        the index — the content is about to be overwritten)."""
        with self._lock:
            if self._free:
                bid = self._free.pop()
            elif self._cached:
                bid, _ = self._cached.popitem(last=False)  # LRU
                h = self._hash_of.pop(bid, None)
                if h is not None:
                    self._by_hash.pop(h, None)
                self._content.pop(bid, None)
                self.evictions += 1
            else:
                raise RuntimeError(
                    "block pool exhausted despite reservation — "
                    "reserve/acquire accounting is broken")
            self._ref[bid] = 1
            self._reserved = max(0, self._reserved - 1)
            return bid

    def ref(self, bid: int) -> None:
        """Take a reference on a looked-up block (prefix reuse),
        consuming one reserved unit: a cached block leaves the LRU
        (it is live again); an already-active block just gains a
        holder (and the unit effectively returns to the pool)."""
        with self._lock:
            if self._ref.get(bid, 0) == 0:
                self._cached.pop(bid, None)
                self._ref[bid] = 1
            else:
                self._ref[bid] += 1
            self._reserved = max(0, self._reserved - 1)

    def deref(self, bid: int) -> None:
        """Drop one reference. At zero, a hashed block parks in the
        LRU (reusable until evicted); an unhashed one (decode tail)
        frees outright."""
        with self._lock:
            n = self._ref.get(bid, 0) - 1
            if n > 0:
                self._ref[bid] = n
                return
            self._ref.pop(bid, None)
            if bid in self._hash_of:
                self._cached[bid] = None
                self._cached.move_to_end(bid)
            else:
                self._free.append(bid)

    # ------------------------------------------------- content address

    def seal(self, bid: int, h: int, content) -> None:
        """Publish a fully-written prompt block into the hash index.
        First writer wins: a concurrent recompute of the same prefix
        keeps its private copy unhashed (it frees on deref)."""
        with self._lock:
            if h in self._by_hash:
                return
            self._hash_of[bid] = h
            self._by_hash[h] = bid
            self._content[bid] = tuple(int(t) for t in content)
            self.sealed += 1

    def lookup(self, h: int, content) -> int | None:
        """Resident block for chain hash ``h`` — contents verified, so
        a 32-bit collision is a miss, never silent corruption."""
        with self._lock:
            bid = self._by_hash.get(h)
            if bid is None:
                return None
            want = tuple(int(t) for t in content)
            return bid if self._content.get(bid) == want else None

    # ------------------------------------------------------ inspection

    def stats(self) -> dict:
        with self._lock:
            used = len(self._ref)
            cached = len(self._cached)
            free = len(self._free)
            return {
                "kv_total_blocks": self.capacity,
                "kv_used_blocks": used,
                "kv_cached_blocks": cached,
                "kv_free_blocks": max(0, free + cached - self._reserved),
                "kv_reserved_blocks": self._reserved,
                "kv_evictions": self.evictions,
                "kv_sealed_blocks": self.sealed,
                "kv_util_pct": round(100.0 * used / self.capacity, 2)
                if self.capacity else 0.0,
            }

    def check_invariants(self, spec_rows=()) -> list[str]:
        """Consistency audit for tests: every block in exactly one
        lifetime, index bijective, reservation covered.

        ``spec_rows`` (speculative decoding, ISSUE 12): per-live-row
        ``(pos, nalloc, reserve_left, advance)`` tuples — asserts each
        row's remaining reservation covers its worst-case
        ``advance``-token speculative window (positions
        ``[pos, pos + advance)``, including decode-boundary block
        crossings mid-speculation), so a verify step can never find
        the pool empty. The engine builds these via
        ``PagedGeneratorActor.check_spec_reservations()``."""
        bad: list[str] = []
        bt = self.block_tokens
        for i, (pos, nalloc, reserve_left, advance) in \
                enumerate(spec_rows):
            need = -(-(int(pos) + int(advance)) // bt) - int(nalloc)
            if need > int(reserve_left):
                bad.append(
                    f"row {i}: reservation does not cover a "
                    f"{advance}-token advance from pos {pos} "
                    f"(needs {need} new blocks past its {nalloc} "
                    f"allocated, holds {reserve_left} reserved)")
        with self._lock:
            free, cached, active = (set(self._free), set(self._cached),
                                    set(self._ref))
            if free & cached or free & active or cached & active:
                bad.append("block in two lifetime sets")
            if len(free) + len(cached) + len(active) != self.capacity:
                bad.append(
                    f"lost blocks: {len(free)}+{len(cached)}+"
                    f"{len(active)} != {self.capacity}")
            if any(n <= 0 for n in self._ref.values()):
                bad.append("non-positive refcount")
            for h, bid in self._by_hash.items():
                if self._hash_of.get(bid) != h:
                    bad.append(f"hash index not bijective at {bid}")
            if not set(self._hash_of) >= cached:
                bad.append("cached block without a hash")
            if self._reserved > len(free) + len(cached):
                bad.append("reservation exceeds available blocks")
        return bad
