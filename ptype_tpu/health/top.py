"""``obs top``: the live cluster health view.

:func:`render_top` turns one cluster snapshot + the alert engine's
history into the operator one-pager (per-node goodput, step
breakdown, throughput, memory, and the active alert list);
:func:`run_top` is the refresh loop behind ``python -m ptype_tpu obs
top`` — snapshot, evaluate the rules, repaint.
:func:`render_serve` / :func:`run_serve` are the serving-plane
siblings behind ``obs serve`` (ISSUE 10): per-replica TTFT/TPOT/e2e
tails, queue/batch occupancy, and KV-pool pressure from the serving
ledger's metrics. Pure string rendering here; the CLI owns stdout
(PT004: framework code never prints).
"""

from __future__ import annotations

import sys
import threading
import time

from ptype_tpu.health.rules import AlertEngine

#: ANSI clear-screen + home, prefixed per repaint by the live loop.
CLEAR = "\x1b[2J\x1b[H"


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "?"


def _gauge(telem: dict, name: str):
    return telem.get("metrics", {}).get("gauges", {}).get(name)


def render_top(snapshot: dict, alerts=(), max_nodes: int = 32) -> str:
    """One repaint: header, per-node health table, alert tail."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    lines = [
        f"ptype health @ {snapshot.get('ts')} — {len(nodes)} nodes, "
        f"{len(errors)} unreachable",
        f"{'node':<28} {'good%':>6} {'step':>8} {'coll':>8} "
        f"{'opt':>8} {'stall':>8} {'tok/s':>9} {'mfu':>7} {'mem':>9} "
        f"{'loss':>8}",
    ]
    for key in sorted(nodes)[:max_nodes]:
        t = nodes[key]
        good = _gauge(t, "goodput.pct")
        step = _gauge(t, "goodput.step_ms")
        coll = _gauge(t, "goodput.collective_ms")
        opt = _gauge(t, "goodput.optimizer_ms")
        stall = _gauge(t, "goodput.stall_ms")
        tps = _gauge(t, "goodput.tokens_per_sec")
        mfu = _gauge(t, "goodput.mfu")
        mem = (_gauge(t, "mem.device_bytes_in_use")
               or _gauge(t, "mem.rss_bytes"))
        loss = _gauge(t, "train.loss")

        def num(v, fmt="{:.1f}", dash="-"):
            return fmt.format(v) if v is not None else dash

        lines.append(
            f"{key[:28]:<28} {num(good):>6} {num(step):>7}m "
            f"{num(coll):>7}m {num(opt):>7}m {num(stall):>7}m "
            f"{num(tps):>9} {num(mfu, '{:.3f}'):>7} "
            f"{_fmt_bytes(mem):>9} {num(loss, '{:.3f}'):>8}")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def _hist(telem: dict, name: str) -> dict:
    return telem.get("metrics", {}).get("histograms", {}).get(name) \
        or {}


#: Code → name for the ``serve.lifecycle`` gauge (metric series carry
#: floats; the reconciler's state machine carries names). Mirrors
#: ``ptype_tpu.serve.LIFECYCLES`` — kept inline so the operator views
#: stay importable without the serving stack; a test pins the two in
#: sync.
_LIFECYCLE_NAMES = ("spawning", "warm", "active", "draining",
                    "drained")


def _lifecycle_name(code) -> str | None:
    if code is None:
        return None
    i = int(code)
    return (_LIFECYCLE_NAMES[i] if 0 <= i < len(_LIFECYCLE_NAMES)
            else "?")


#: Code → name for the ``serve.class`` gauge (disaggregated serving,
#: ISSUE 16). Mirrors ``ptype_tpu.serve_engine.SERVE_CLASSES`` — same
#: inline-copy contract as ``_LIFECYCLE_NAMES``; a test pins the two
#: in sync.
_SERVE_CLASS_NAMES = ("unified", "prefill", "decode")


def _serve_class_name(code) -> str | None:
    if code is None:
        return None
    i = int(code)
    return (_SERVE_CLASS_NAMES[i] if 0 <= i < len(_SERVE_CLASS_NAMES)
            else "?")


def render_serve(snapshot: dict, alerts=(),
                 max_nodes: int = 32) -> str:
    """``obs serve``: the serving-plane one-pager — per-replica
    TTFT/TPOT/e2e tails from the serving ledger's histograms, queue
    and batch occupancy, KV-pool pressure (free blocks, utilization,
    prefix hit rate, evictions), the co-batched prefill stall, and —
    on a disaggregated fleet (ISSUE 16) — each replica's serving
    class plus its migration counters (completed transfers, wire
    bytes, dedup hits).
    Replicas are rows; nodes with no serving metrics (trainers, the
    coordinator) are skipped — this is the serving view, ``obs top``
    is the fleet view."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    serving = {k: t for k, t in nodes.items()
               if _hist(t, "serve.ttft_ms")
               or _gauge(t, "serve.step_ms") is not None}
    lines = [
        f"ptype serving @ {snapshot.get('ts')} — "
        f"{len(serving)} serving replicas "
        f"({len(nodes)} nodes, {len(errors)} unreachable)",
        f"{'replica':<28} {'state':>9} {'class':>8} {'ttft99':>8} "
        f"{'tpot':>7} {'e2e99':>8} {'q':>4} {'live':>5} "
        f"{'kvfree':>7} {'util%':>6} {'hit%':>6} {'spec%':>6} "
        f"{'evic':>6} {'stall':>7} {'mig':>5} {'migMB':>7} "
        f"{'dedup':>6}",
    ]

    def num(v, fmt="{:.1f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    for key in sorted(serving)[:max_nodes]:
        t = serving[key]
        ttft = _hist(t, "serve.ttft_ms").get("p99")
        tpot = _hist(t, "serve.tpot_ms").get("p50")
        e2e = _hist(t, "serve.e2e_ms").get("p99")
        q = _gauge(t, "serve.queue_depth")
        live = _gauge(t, "serve.active_slots")
        free = _gauge(t, "kv.free_blocks")
        util = _gauge(t, "kv.util_pct")
        hit = _gauge(t, "kv.prefix_hit_rate")
        # Speculative-decoding accept rate (ISSUE 12): absent on
        # replicas that never ran a window — "-" means no speculation,
        # a number near 0 means a collapsed draft.
        spec = _gauge(t, "serve.spec_accept_rate")
        evic = (t.get("metrics", {}).get("counters", {})
                .get("kv.evictions"))
        stall = _gauge(t, "serve.stall_ms")
        # Lifecycle column (ISSUE 13): the fleet view matches the
        # reconciler's state machine; "-" = the replica predates the
        # lifecycle story (no serve.lifecycle gauge).
        state = _lifecycle_name(_gauge(t, "serve.lifecycle")) or "-"
        # Serving class + migration counters (ISSUE 16): a
        # disaggregated fleet reads its split and its wire traffic
        # here first (the migration-stall runbook starts at this
        # view); "-" class = a replica predating the disagg story.
        cls = _serve_class_name(_gauge(t, "serve.class")) or "-"
        counters = t.get("metrics", {}).get("counters", {})
        mig = counters.get("serve.migrations")
        mig_mb = counters.get("serve.migrate_bytes")
        mig_mb = mig_mb / 1e6 if mig_mb is not None else None
        dedup = counters.get("serve.migrate_dedup_hits")
        lines.append(
            f"{key[:28]:<28} {state:>9} {cls:>8} "
            f"{num(ttft, '{:.0f}'):>7}m "
            f"{num(tpot):>6}m {num(e2e, '{:.0f}'):>7}m "
            f"{num(q, '{:.0f}'):>4} {num(live, '{:.0f}'):>5} "
            f"{num(free, '{:.0f}'):>7} {num(util):>6} "
            f"{num(hit * 100 if hit is not None else None):>6} "
            f"{num(spec * 100 if spec is not None else None):>6} "
            f"{num(evic, '{:.0f}'):>6} {num(stall):>6}m "
            f"{num(mig, '{:.0f}'):>5} {num(mig_mb, '{:.2f}'):>7} "
            f"{num(dedup, '{:.0f}'):>6}")
    if not serving:
        lines.append("  (no serving replicas report serve.* metrics)")
    # Gateway goodput (ISSUE 19): the SLO-attributed good/violation
    # split per gateway service — the series the capacity frontier
    # reads, surfaced where the serving tails already live.
    gateways: list[tuple[str, str, dict]] = []
    for key, t in sorted(nodes.items()):
        counters = t.get("metrics", {}).get("counters", {})
        for cname in sorted(counters):
            if (cname.startswith("gateway.")
                    and cname.endswith(".requests")):
                gateways.append(
                    (key, cname[len("gateway."):-len(".requests")],
                     counters))
    if gateways:
        lines.append("")
        lines.append(f"{'gateway':<28} {'svc':>10} {'req':>7} "
                     f"{'ans':>7} {'shed':>6} {'good':>7} "
                     f"{'viol':>6} {'good%':>6}")
        for key, svc, counters in gateways[:max_nodes]:
            g = counters.get(f"gateway.{svc}.slo_good_requests")
            v = counters.get(f"gateway.{svc}.slo_violations")
            pct = (100.0 * g / (g + v) if g is not None
                   and v is not None and (g + v) > 0 else None)
            lines.append(
                f"{key[:28]:<28} {svc[:10]:>10} "
                f"{num(counters.get(f'gateway.{svc}.requests'), '{:.0f}'):>7} "
                f"{num(counters.get(f'gateway.{svc}.answered'), '{:.0f}'):>7} "
                f"{num(counters.get(f'gateway.{svc}.shed'), '{:.0f}'):>6} "
                f"{num(g, '{:.0f}'):>7} {num(v, '{:.0f}'):>6} "
                f"{num(pct):>6}")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def render_scale(snapshot: dict, alerts=(),
                 max_nodes: int = 32) -> str:
    """``obs scale``: the elastic-fleet one-pager (ISSUE 13). Top:
    every node exporting ``scale.*`` gauges (the reconcilers) with
    desired vs actual, warm/draining/pending counts, and the
    lifetime decision/spawn/drain/escalation counters. Below: every
    serving replica with its lifecycle state and queue/live occupancy
    — the same fleet the reconciler is steering, so a scale decision
    and its effect sit in one screen."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    recs = {k: t for k, t in nodes.items()
            if _gauge(t, "scale.desired") is not None}
    serving = {k: t for k, t in nodes.items()
               if _gauge(t, "serve.lifecycle") is not None
               or _hist(t, "serve.ttft_ms")}

    def num(v, fmt="{:.0f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    def cnt(t, name):
        return t.get("metrics", {}).get("counters", {}).get(name)

    lines = [
        f"ptype scale @ {snapshot.get('ts')} — {len(recs)} "
        f"reconcilers, {len(serving)} serving replicas "
        f"({len(nodes)} nodes, {len(errors)} unreachable)",
        f"{'reconciler':<28} {'want':>5} {'have':>5} {'warm':>5} "
        f"{'drng':>5} {'pend':>5} {'dec':>5} {'spawn':>6} "
        f"{'drain':>6} {'esc':>4} {'dead':>5} {'fail':>5}",
    ]
    for key in sorted(recs)[:max_nodes]:
        t = recs[key]
        lines.append(
            f"{key[:28]:<28} {num(_gauge(t, 'scale.desired')):>5} "
            f"{num(_gauge(t, 'scale.actual')):>5} "
            f"{num(_gauge(t, 'scale.warm')):>5} "
            f"{num(_gauge(t, 'scale.draining')):>5} "
            f"{num(_gauge(t, 'scale.pending_spawns')):>5} "
            f"{num(cnt(t, 'scale.decisions')):>5} "
            f"{num(cnt(t, 'scale.spawns')):>6} "
            f"{num(cnt(t, 'scale.drains')):>6} "
            f"{num(cnt(t, 'scale.drain_escalations')):>4} "
            f"{num(cnt(t, 'scale.deaths')):>5} "
            f"{num(cnt(t, 'scale.spawn_failures')):>5}")
    if not recs:
        lines.append("  (no node exports scale.* — no reconciler "
                     "running, or its telemetry is not registered)")
    lines.append("")
    lines.append(f"{'replica':<28} {'state':>9} {'q':>4} {'live':>5} "
                 f"{'kvfree':>7} {'ttft99':>8}")
    for key in sorted(serving)[:max_nodes]:
        t = serving[key]
        state = _lifecycle_name(_gauge(t, "serve.lifecycle")) or "-"
        lines.append(
            f"{key[:28]:<28} {state:>9} "
            f"{num(_gauge(t, 'serve.queue_depth')):>4} "
            f"{num(_gauge(t, 'serve.active_slots')):>5} "
            f"{num(_gauge(t, 'serve.kv_free_blocks')):>7} "
            f"{num(_hist(t, 'serve.ttft_ms').get('p99')):>7}m")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def _srate(telem: dict, name: str):
    """Last sampled value of a series (the sampler's ``<ctr>.rate`` /
    ``<hist>.p99`` stamps) — None when the node publishes no series
    store or the series has no points yet."""
    pts = telem.get("series", {}).get(name)
    return pts[-1][1] if pts else None


def render_traffic(snapshot: dict, alerts=(),
                   max_nodes: int = 32) -> str:
    """``obs traffic``: the traffic-plane one-pager (ISSUE 19). One
    row per node driving open-loop load (anything exporting
    ``loadgen.*``): the schedule's target rate, the live offered /
    achieved rates off the sampler, SLO-attributed goodput, the
    shed/overrun/chaos-drop split, the open-loop TTFT tail, and the
    last measured capacity knee with live headroom against it — the
    same numbers the ``capacity-headroom`` rule warns on, so the
    operator and the rule read one surface."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    drivers = {k: t for k, t in nodes.items()
               if _gauge(t, "loadgen.offered_rps") is not None
               or (t.get("metrics", {}).get("counters", {})
                   .get("loadgen.offered")) is not None}

    def num(v, fmt="{:.0f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    def cnt(t, name):
        return t.get("metrics", {}).get("counters", {}).get(name)

    lines = [
        f"ptype traffic @ {snapshot.get('ts')} — {len(drivers)} "
        f"load drivers ({len(nodes)} nodes, "
        f"{len(errors)} unreachable)",
        f"{'driver':<28} {'target':>7} {'off/s':>7} {'ach/s':>7} "
        f"{'good%':>6} {'shed':>6} {'ovrn':>6} {'drop':>5} "
        f"{'infl':>5} {'ttft99':>8} {'knee':>7} {'head%':>6}",
    ]
    for key in sorted(drivers)[:max_nodes]:
        t = drivers[key]
        good = cnt(t, "loadgen.slo_good")
        bad = cnt(t, "loadgen.slo_bad")
        pct = (100.0 * good / (good + bad)
               if good is not None and bad is not None
               and (good + bad) > 0 else None)
        off_rate = _srate(t, "loadgen.offered.rate")
        knee = _gauge(t, "loadgen.knee_rps")
        head = (100.0 * off_rate / knee
                if off_rate is not None and knee else None)
        lines.append(
            f"{key[:28]:<28} "
            f"{num(_gauge(t, 'loadgen.offered_rps')):>7} "
            f"{num(off_rate, '{:.1f}'):>7} "
            f"{num(_srate(t, 'loadgen.answered.rate'), '{:.1f}'):>7} "
            f"{num(pct, '{:.1f}'):>6} "
            f"{num(cnt(t, 'loadgen.shed')):>6} "
            f"{num(cnt(t, 'loadgen.overrun')):>6} "
            f"{num(cnt(t, 'loadgen.dropped')):>5} "
            f"{num(_gauge(t, 'loadgen.inflight')):>5} "
            f"{num(_hist(t, 'loadgen.ttft_ms').get('p99')):>7}m "
            f"{num(knee):>7} {num(head, '{:.0f}'):>6}")
    if not drivers:
        lines.append("  (no node exports loadgen.* — no open-loop "
                     "driver is running, or its registry is not "
                     "published; see docs/OBSERVABILITY.md "
                     "'Traffic plane')")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def render_topo(snapshot: dict, alerts=(),
                max_nodes: int = 32) -> str:
    """``obs topo``: the topology one-pager (ISSUE 18). Top:
    per-domain replica counts — every node exporting the
    ``serve.domain`` gauge (stamped by ReplicaHost from its
    placement) grouped by domain ordinal, with lifecycle and
    queue/live occupancy folded per domain. Middle: per-leg
    collective wire traffic from the ``collectives.leg_bytes.*``
    counters (fast inner leg vs slow outer leg vs the flat-baseline
    footprint) on every node that launched hierarchical buckets.
    Bottom: the gateway's KV-migration locality split
    (``serve.migrate.local_domain`` vs ``.cross_domain``) — the
    cross-domain-pressure runbook row lands here after ``obs
    serve``."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})

    def cnt(t, name):
        return t.get("metrics", {}).get("counters", {}).get(name)

    def num(v, fmt="{:.0f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    domains: dict = {}
    for key, t in sorted(nodes.items()):
        d = _gauge(t, "serve.domain")
        if d is None:
            continue
        domains.setdefault(int(d), []).append((key, t))
    lines = [
        f"ptype topology @ {snapshot.get('ts')} — "
        f"{sum(len(v) for v in domains.values())} placed replicas "
        f"in {len(domains)} domains ({len(nodes)} nodes, "
        f"{len(errors)} unreachable)",
        f"{'domain':<7} {'replicas':>9} {'active':>7} {'drng':>5} "
        f"{'q':>4} {'live':>5}",
    ]
    for d in sorted(domains):
        rows = domains[d]
        states = [_lifecycle_name(_gauge(t, "serve.lifecycle"))
                  for _, t in rows]
        q = sum(_gauge(t, "serve.queue_depth") or 0 for _, t in rows)
        live = sum(_gauge(t, "serve.active_slots") or 0
                   for _, t in rows)
        names = " ".join(k[:24] for k, _ in rows[:4])
        lines.append(
            f"{d:<7} {len(rows):>9} "
            f"{states.count('active'):>7} "
            f"{states.count('draining'):>5} {q:>4.0f} {live:>5.0f}  "
            f"{names}")
    if not domains:
        lines.append("  (no node exports serve.domain — flat fleet, "
                     "or replicas predate the topology story)")

    lines.append("")
    lines.append(f"{'node':<28} {'launches':>9} {'innerB':>9} "
                 f"{'outerB':>9} {'flatB':>9} {'slow%':>6}")
    any_legs = False
    for key in sorted(nodes)[:max_nodes]:
        t = nodes[key]
        launches = cnt(t, "collectives.hier_launches")
        if not launches:
            continue
        any_legs = True
        inner = cnt(t, "collectives.leg_bytes.inner") or 0
        outer = cnt(t, "collectives.leg_bytes.outer") or 0
        flat = cnt(t, "collectives.leg_bytes.flat_outer") or 0
        pct = 100.0 * outer / flat if flat else None
        lines.append(
            f"{key[:28]:<28} {launches:>9.0f} "
            f"{_fmt_bytes(inner):>9} {_fmt_bytes(outer):>9} "
            f"{_fmt_bytes(flat):>9} {num(pct, '{:.1f}'):>6}")
    if not any_legs:
        lines.append("  (no hierarchical collective launches — flat "
                     "axis everywhere)")

    lines.append("")
    loc = sum(cnt(t, "serve.migrate.local_domain") or 0
              for t in nodes.values())
    x = sum(cnt(t, "serve.migrate.cross_domain") or 0
            for t in nodes.values())
    tot = loc + x
    tail = (f" ({100.0 * x / tot:.1f}% crossing the slow leg)"
            if tot else "")
    lines.append(f"KV migrations: {loc:.0f} local-domain, "
                 f"{x:.0f} cross-domain{tail}")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def render_jit(snapshot: dict, alerts=(), max_nodes: int = 32,
               max_fns: int = 12) -> str:
    """``obs jit``: the dispatch-discipline one-pager (ISSUE 15) —
    per-node compile/recompile totals from the jitwatch seam
    (``jit.compiles``/``jit.recompiles`` counters, sampled into
    series) plus the per-function ``jit.fn.*`` recompile books, worst
    offender first. A node with no ``jit.*`` families is disarmed
    (``PTYPE_JITWATCH=1`` arms it) — shown so an operator chasing a
    recompile-storm page can tell 'quiet' from 'blind'."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})

    def cnt(t, name):
        return t.get("metrics", {}).get("counters", {}).get(name)

    armed = {k: t for k, t in nodes.items()
             if cnt(t, "jit.compiles") is not None
             or (t.get("series") or {}).get("jit.recompiles")}
    lines = [
        f"ptype jit @ {snapshot.get('ts')} — {len(armed)} armed "
        f"nodes ({len(nodes)} nodes, {len(errors)} unreachable)",
        f"{'node':<28} {'compiles':>9} {'recomp':>7} {'sanct':>6} "
        f"{'worst offender':<32}",
    ]

    def num(v, fmt="{:.0f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    for key in sorted(armed)[:max_nodes]:
        t = armed[key]
        fns = []
        for name, val in (t.get("metrics", {})
                          .get("gauges", {})).items():
            if name.startswith("jit.fn."):
                fns.append((name[len("jit.fn."):], val))
        for name, pts in (t.get("series") or {}).items():
            if name.startswith("jit.fn.") and pts:
                fn = name[len("jit.fn."):]
                if not any(f == fn for f, _ in fns):
                    fns.append((fn, pts[-1][1]))
        fns.sort(key=lambda kv: -kv[1])
        worst = (f"{fns[0][0]} ({fns[0][1]:.0f}x)" if fns else "-")
        lines.append(
            f"{key[:28]:<28} {num(cnt(t, 'jit.compiles')):>9} "
            f"{num(cnt(t, 'jit.recompiles')):>7} "
            f"{num(cnt(t, 'jit.sanctioned_transfers')):>6} "
            f"{worst[:32]:<32}")
        for fn, val in fns[1:max_fns]:
            lines.append(f"  {fn[:40]:<40} {val:>6.0f}x")
    if not armed:
        lines.append("  (no node exports jit.* — arm the watchdog "
                     "with PTYPE_JITWATCH=1 or jitwatch.enable())")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def run_jit(registry, iters: int = 0, interval_s: float = 2.0,
            engine: AlertEngine | None = None,
            services: list[str] | None = None,
            include_local: bool = False, out=None,
            clear: bool = True) -> AlertEngine:
    """The ``obs jit`` loop: :func:`run_top`'s poll contract with the
    dispatch-discipline rendering (the recompile-storm rule fires off
    the same snapshot)."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_jit)


def run_scale(registry, iters: int = 0, interval_s: float = 2.0,
              engine: AlertEngine | None = None,
              services: list[str] | None = None,
              include_local: bool = False, out=None,
              clear: bool = True) -> AlertEngine:
    """The ``obs scale`` loop: :func:`run_top`'s poll contract with
    the elastic-fleet rendering."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_scale)


def run_traffic(registry, iters: int = 0, interval_s: float = 2.0,
                engine: AlertEngine | None = None,
                services: list[str] | None = None,
                include_local: bool = False, out=None,
                clear: bool = True) -> AlertEngine:
    """The ``obs traffic`` loop: :func:`run_top`'s poll contract with
    the traffic-plane rendering (the capacity-headroom rule fires off
    the same snapshot)."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_traffic)


def run_topo(registry, iters: int = 0, interval_s: float = 2.0,
             engine: AlertEngine | None = None,
             services: list[str] | None = None,
             include_local: bool = False, out=None,
             clear: bool = True) -> AlertEngine:
    """The ``obs topo`` loop: :func:`run_top`'s poll contract with
    the topology rendering (domain placement, per-leg wire traffic,
    migration locality)."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_topo)


def run_serve(registry, iters: int = 0, interval_s: float = 2.0,
              engine: AlertEngine | None = None,
              services: list[str] | None = None,
              include_local: bool = False, out=None,
              clear: bool = True) -> AlertEngine:
    """The ``obs serve`` loop: :func:`run_top`'s poll contract with
    the serving-plane rendering (the serving rules fire off the same
    snapshot either way)."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_serve)


def run_top(registry, iters: int = 0, interval_s: float = 2.0,
            engine: AlertEngine | None = None,
            services: list[str] | None = None,
            include_local: bool = False, out=None,
            clear: bool = True, render=None) -> AlertEngine:
    """The ``obs top`` loop: pull, evaluate, repaint. ``iters=0``
    runs until KeyboardInterrupt (the caller catches it); tests pass
    ``iters=1`` and a capture ``out``. ``render`` swaps the view
    (:func:`render_serve` for ``obs serve``) without forking the
    loop. Returns the engine so callers can inspect the alert
    history."""
    from ptype_tpu import telemetry as telemetry_mod

    render = render if render is not None else render_top
    write = out if out is not None else sys.stdout.write
    engine = engine if engine is not None else AlertEngine()
    tick = threading.Event()
    n = 0
    while True:
        snap = telemetry_mod.cluster_snapshot(
            registry, services=services, include_local=include_local)
        engine.evaluate(snap)
        prefix = CLEAR if clear else ""
        write(prefix + render(snap, engine.recent()) + "\n")
        n += 1
        if iters and n >= iters:
            return engine
        tick.wait(interval_s)
