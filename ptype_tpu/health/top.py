"""``obs top``: the live cluster health view.

:func:`render_top` turns one cluster snapshot + the alert engine's
history into the operator one-pager (per-node goodput, step
breakdown, throughput, memory, and the active alert list);
:func:`run_top` is the refresh loop behind ``python -m ptype_tpu obs
top`` — snapshot, evaluate the rules, repaint.
:func:`render_serve` / :func:`run_serve` are the serving-plane
siblings behind ``obs serve`` (ISSUE 10): per-replica TTFT/TPOT/e2e
tails, queue/batch occupancy, and KV-pool pressure from the serving
ledger's metrics. Pure string rendering here; the CLI owns stdout
(PT004: framework code never prints).
"""

from __future__ import annotations

import sys
import threading
import time

from ptype_tpu.health.rules import AlertEngine

#: ANSI clear-screen + home, prefixed per repaint by the live loop.
CLEAR = "\x1b[2J\x1b[H"


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "?"


def _gauge(telem: dict, name: str):
    return telem.get("metrics", {}).get("gauges", {}).get(name)


def render_top(snapshot: dict, alerts=(), max_nodes: int = 32) -> str:
    """One repaint: header, per-node health table, alert tail."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    lines = [
        f"ptype health @ {snapshot.get('ts')} — {len(nodes)} nodes, "
        f"{len(errors)} unreachable",
        f"{'node':<28} {'good%':>6} {'step':>8} {'coll':>8} "
        f"{'opt':>8} {'stall':>8} {'tok/s':>9} {'mfu':>7} {'mem':>9} "
        f"{'loss':>8}",
    ]
    for key in sorted(nodes)[:max_nodes]:
        t = nodes[key]
        good = _gauge(t, "goodput.pct")
        step = _gauge(t, "goodput.step_ms")
        coll = _gauge(t, "goodput.collective_ms")
        opt = _gauge(t, "goodput.optimizer_ms")
        stall = _gauge(t, "goodput.stall_ms")
        tps = _gauge(t, "goodput.tokens_per_sec")
        mfu = _gauge(t, "goodput.mfu")
        mem = (_gauge(t, "mem.device_bytes_in_use")
               or _gauge(t, "mem.rss_bytes"))
        loss = _gauge(t, "train.loss")

        def num(v, fmt="{:.1f}", dash="-"):
            return fmt.format(v) if v is not None else dash

        lines.append(
            f"{key[:28]:<28} {num(good):>6} {num(step):>7}m "
            f"{num(coll):>7}m {num(opt):>7}m {num(stall):>7}m "
            f"{num(tps):>9} {num(mfu, '{:.3f}'):>7} "
            f"{_fmt_bytes(mem):>9} {num(loss, '{:.3f}'):>8}")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def _hist(telem: dict, name: str) -> dict:
    return telem.get("metrics", {}).get("histograms", {}).get(name) \
        or {}


def render_serve(snapshot: dict, alerts=(),
                 max_nodes: int = 32) -> str:
    """``obs serve``: the serving-plane one-pager — per-replica
    TTFT/TPOT/e2e tails from the serving ledger's histograms, queue
    and batch occupancy, KV-pool pressure (free blocks, utilization,
    prefix hit rate, evictions), and the co-batched prefill stall.
    Replicas are rows; nodes with no serving metrics (trainers, the
    coordinator) are skipped — this is the serving view, ``obs top``
    is the fleet view."""
    nodes = snapshot.get("nodes", {})
    errors = snapshot.get("errors", {})
    serving = {k: t for k, t in nodes.items()
               if _hist(t, "serve.ttft_ms")
               or _gauge(t, "serve.step_ms") is not None}
    lines = [
        f"ptype serving @ {snapshot.get('ts')} — "
        f"{len(serving)} serving replicas "
        f"({len(nodes)} nodes, {len(errors)} unreachable)",
        f"{'replica':<28} {'ttft99':>8} {'tpot':>7} {'e2e99':>8} "
        f"{'q':>4} {'live':>5} {'kvfree':>7} {'util%':>6} "
        f"{'hit%':>6} {'spec%':>6} {'evic':>6} {'stall':>7}",
    ]

    def num(v, fmt="{:.1f}", dash="-"):
        return fmt.format(v) if v is not None else dash

    for key in sorted(serving)[:max_nodes]:
        t = serving[key]
        ttft = _hist(t, "serve.ttft_ms").get("p99")
        tpot = _hist(t, "serve.tpot_ms").get("p50")
        e2e = _hist(t, "serve.e2e_ms").get("p99")
        q = _gauge(t, "serve.queue_depth")
        live = _gauge(t, "serve.active_slots")
        free = _gauge(t, "kv.free_blocks")
        util = _gauge(t, "kv.util_pct")
        hit = _gauge(t, "kv.prefix_hit_rate")
        # Speculative-decoding accept rate (ISSUE 12): absent on
        # replicas that never ran a window — "-" means no speculation,
        # a number near 0 means a collapsed draft.
        spec = _gauge(t, "serve.spec_accept_rate")
        evic = (t.get("metrics", {}).get("counters", {})
                .get("kv.evictions"))
        stall = _gauge(t, "serve.stall_ms")
        lines.append(
            f"{key[:28]:<28} {num(ttft, '{:.0f}'):>7}m "
            f"{num(tpot):>6}m {num(e2e, '{:.0f}'):>7}m "
            f"{num(q, '{:.0f}'):>4} {num(live, '{:.0f}'):>5} "
            f"{num(free, '{:.0f}'):>7} {num(util):>6} "
            f"{num(hit * 100 if hit is not None else None):>6} "
            f"{num(spec * 100 if spec is not None else None):>6} "
            f"{num(evic, '{:.0f}'):>6} {num(stall):>6}m")
    if not serving:
        lines.append("  (no serving replicas report serve.* metrics)")
    for key in sorted(errors)[:8]:
        lines.append(f"{key[:28]:<28} UNREACHABLE ({errors[key]})")
    lines.append("")
    alerts = list(alerts)
    if alerts:
        lines.append(f"ALERTS ({len(alerts)} recent):")
        for a in alerts[-12:]:
            ts = time.strftime("%H:%M:%S", time.localtime(a.ts))
            lines.append(
                f"  {ts} [{a.severity:<4}] {a.rule:<14} "
                f"{a.node[:28]:<28} {a.message}")
    else:
        lines.append("no alerts")
    return "\n".join(lines)


def run_serve(registry, iters: int = 0, interval_s: float = 2.0,
              engine: AlertEngine | None = None,
              services: list[str] | None = None,
              include_local: bool = False, out=None,
              clear: bool = True) -> AlertEngine:
    """The ``obs serve`` loop: :func:`run_top`'s poll contract with
    the serving-plane rendering (the serving rules fire off the same
    snapshot either way)."""
    return run_top(registry, iters=iters, interval_s=interval_s,
                   engine=engine, services=services,
                   include_local=include_local, out=out, clear=clear,
                   render=render_serve)


def run_top(registry, iters: int = 0, interval_s: float = 2.0,
            engine: AlertEngine | None = None,
            services: list[str] | None = None,
            include_local: bool = False, out=None,
            clear: bool = True, render=None) -> AlertEngine:
    """The ``obs top`` loop: pull, evaluate, repaint. ``iters=0``
    runs until KeyboardInterrupt (the caller catches it); tests pass
    ``iters=1`` and a capture ``out``. ``render`` swaps the view
    (:func:`render_serve` for ``obs serve``) without forking the
    loop. Returns the engine so callers can inspect the alert
    history."""
    from ptype_tpu import telemetry as telemetry_mod

    render = render if render is not None else render_top
    write = out if out is not None else sys.stdout.write
    engine = engine if engine is not None else AlertEngine()
    tick = threading.Event()
    n = 0
    while True:
        snap = telemetry_mod.cluster_snapshot(
            registry, services=services, include_local=include_local)
        engine.evaluate(snap)
        prefix = CLEAR if clear else ""
        write(prefix + render(snap, engine.recent()) + "\n")
        n += 1
        if iters and n >= iters:
            return engine
        tick.wait(interval_s)
