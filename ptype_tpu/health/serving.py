"""Serving observability plane: the request-lifecycle ledger (ISSUE 10).

The training side has a goodput ledger (health/goodput.py) that turns
``metrics.annotate`` regions into per-step attribution; the paged
serving engine (serve_engine/engine.py) had only an end-to-end latency
number at the gateway. This module is the serving analogue — a
:class:`ServingLedger` fed from metering seams inside the engine:

- **Request lifecycle**: every prompt row gets a
  :class:`RequestRecord` — queue wait (enqueue → head of line),
  reservation wait (head of line → pool reservation), every prefill
  chunk (wall start + duration + tokens), the first-token stamp, a
  per-token decode delta trail, and the retire reason (``complete`` /
  ``stop`` / ``cancelled`` / ``shed`` / ``error``). Retired records
  fold into **TTFT / TPOT / e2e histograms** (``serve.ttft_ms``,
  ``serve.tpot_ms``, ``serve.e2e_ms`` — the health
  :class:`~ptype_tpu.health.series.Sampler` stamps their ``.p99`` /
  ``.count`` series, which the ``ttft-p99`` alert rule reads).
- **Engine-iteration composition**: one record per engine iteration —
  active slots, decode-vs-prefill token split, per-iteration wall and
  the co-batched stall — published as ``serve.step_ms`` /
  ``serve.active_slots`` / ``serve.stall_ms`` gauges and
  ``serve.steps`` / ``serve.decode_tokens`` / ``serve.prefill_tokens``
  counters (the ``serve-stall`` rule watches ``serve.steps`` progress
  against ``serve.queue_depth``).
- **KV-pool pressure**: :meth:`ServingLedger.kv_sample` turns
  :meth:`~ptype_tpu.serve_engine.blocks.BlockPool.stats` into the
  ``kv.free_blocks`` / ``kv.cached_blocks`` / ``kv.total_blocks`` /
  ``kv.prefix_hit_rate`` gauges and the ``kv.evictions`` counter
  (whose sampler-stamped ``kv.evictions.rate`` series gates the
  ``kv-pressure`` rule's eviction floor).
- **Span tree**: when tracing is armed and the request carried a
  traceparent (the engine captures it inside the actor handler span),
  :meth:`ServingLedger.retired` synthesizes the request's span tree
  into the flight recorder — ``serve.admit`` (queue + reservation
  wait), one ``serve.prefill.chunk[i]`` per chunk, and
  ``serve.decode`` carrying the ``first_token`` event and the retire
  reason — all children of the handler span, so the stitched Perfetto
  view reads gateway.request → rpc.call → actor/Generator.Generate →
  admit/chunks/decode for one request across processes. Spans are
  synthesized from the record's own stamps at retire (the lifecycle
  crosses the caller thread and the engine thread, so no single
  ``with`` scope could cover it); their wall-clock starts are the
  stamps the ledger's TTFT is computed from, which is what lets tests
  assert ledger-vs-span agreement.

Timer discipline: lint rule PT010 bars raw ``time.perf_counter()`` /
``time.time()`` calls inside ``serve_engine/`` — every stamp the
engine needs comes from a seam on this ledger (``enqueued`` /
``head_refused`` / ``admitted`` / ``chunk`` / ``first_token`` /
``tokens_emitted`` / ``iteration`` / ``retired``), so latency math has
exactly one home and the bench can cost it
(:func:`measure_seam_cost_us` backs ``serving_ledger_overhead_pct``
in ``bench.py --serve``'s tail, the <1%-per-engine-iteration bar).
"""

from __future__ import annotations

import collections
import time

from ptype_tpu import lockcheck

from ptype_tpu import metrics as metrics_mod
from ptype_tpu import trace

#: Retired request records a ledger keeps.
REQUEST_WINDOW = 256
#: Engine-iteration records a ledger keeps.
ITER_WINDOW = 512
#: Recent per-request (seq, ttft_ms) samples served in ``Info()`` —
#: the gateway's probe drains new ones into its own SLO tracker.
TTFT_RECENT = 32

#: Retire reasons a record can close with.
RETIRE_REASONS = ("complete", "stop", "cancelled", "shed", "error")


class RequestRecord:
    """One prompt row's lifecycle stamps, engine-thread owned.

    Monotonic (``t_*``) stamps drive every duration; wall-clock
    (``w_*``) twins, taken at the same instants, anchor the
    synthesized spans on the cluster's shared timeline.
    """

    __slots__ = ("tp", "prompt_tokens", "max_new", "reused_blocks",
                 "t_enqueue", "w_enqueue", "t_head", "t_admit",
                 "chunks", "t_first", "w_first", "tok_t",
                 "t_done", "reason", "closed", "t_mig0", "w_mig0",
                 "t_mig1", "migrate_blocks", "migrate_bytes")

    def __init__(self, prompt_tokens: int, max_new: int,
                 tp: str | None):
        self.tp = tp
        self.prompt_tokens = int(prompt_tokens)
        self.max_new = int(max_new)
        self.reused_blocks = 0
        self.t_enqueue = time.perf_counter()
        self.w_enqueue = time.time()
        self.t_head: float | None = None
        self.t_admit: float | None = None
        #: [(wall_start, dur_s, tokens), ...] — one per prefill chunk.
        self.chunks: list[tuple[float, float, int]] = []
        self.t_first: float | None = None
        self.w_first: float | None = None
        #: Monotonic stamp per emitted token (first token included).
        self.tok_t: list[float] = []
        self.t_done: float | None = None
        self.reason: str | None = None
        self.closed = False
        #: Migration leg (ISSUE 16, decode-side records only): plan →
        #: import-complete stamps plus the transfer's block/byte
        #: totals — its own TTFT attribution inside the request.
        self.t_mig0: float | None = None
        self.w_mig0: float | None = None
        self.t_mig1: float | None = None
        self.migrate_blocks = 0
        self.migrate_bytes = 0

    # ------------------------------------------------------- durations

    def queue_wait_s(self) -> float:
        """Enqueue → head of line (or admission, when the reservation
        never refused)."""
        anchor = (self.t_head if self.t_head is not None
                  else self.t_admit)
        return max(0.0, (anchor - self.t_enqueue)
                   if anchor is not None else 0.0)

    def reserve_wait_s(self) -> float:
        """Head-of-line reservation wait (0 when the pool covered the
        worst case on the first try)."""
        if self.t_head is None or self.t_admit is None:
            return 0.0
        return max(0.0, self.t_admit - self.t_head)

    def ttft_s(self) -> float | None:
        if self.t_first is None:
            return None
        return max(0.0, self.t_first - self.t_enqueue)

    def tpot_s(self) -> float | None:
        """Mean inter-token time after the first token."""
        if self.t_first is None or self.t_done is None:
            return None
        n = len(self.tok_t)
        if n < 2:
            return None
        return max(0.0, (self.tok_t[-1] - self.t_first) / (n - 1))

    def decode_deltas_ms(self) -> list[float]:
        """Per-token decode gaps (ms) — the raw TPOT trail."""
        return [round((b - a) * 1e3, 3)
                for a, b in zip(self.tok_t, self.tok_t[1:])]

    def migrate_s(self) -> float | None:
        """Migration-leg wall (plan → import complete); None when the
        request never migrated or the transfer never finished."""
        if self.t_mig0 is None or self.t_mig1 is None:
            return None
        return max(0.0, self.t_mig1 - self.t_mig0)

    def to_dict(self) -> dict:
        ttft = self.ttft_s()
        tpot = self.tpot_s()
        d = {
            "t": round(self.w_enqueue, 3),
            "prompt_tokens": self.prompt_tokens,
            "max_new": self.max_new,
            "reused_blocks": self.reused_blocks,
            "queue_wait_ms": round(self.queue_wait_s() * 1e3, 3),
            "reserve_wait_ms": round(self.reserve_wait_s() * 1e3, 3),
            "prefill_chunks": len(self.chunks),
            "prefill_tokens": sum(c[2] for c in self.chunks),
            "prefill_ms": round(
                sum(c[1] for c in self.chunks) * 1e3, 3),
            "tokens_out": len(self.tok_t),
            "reason": self.reason,
        }
        if ttft is not None:
            d["ttft_ms"] = round(ttft * 1e3, 3)
        mig = self.migrate_s()
        if mig is not None:
            d["migrate_ms"] = round(mig * 1e3, 3)
            d["migrate_blocks"] = self.migrate_blocks
            d["migrate_bytes"] = self.migrate_bytes
        if tpot is not None:
            d["tpot_ms"] = round(tpot * 1e3, 3)
            d["decode_deltas_ms"] = self.decode_deltas_ms()
        if self.t_done is not None:
            d["e2e_ms"] = round(
                max(0.0, self.t_done - self.t_enqueue) * 1e3, 3)
        return d


class _ChunkMeter:
    """Times one prefill chunk into its record + the ledger's
    per-iteration prefill accumulator."""

    __slots__ = ("_led", "_rec", "tokens", "dur_s", "_t0", "_w0")

    def __init__(self, led: "ServingLedger", rec: RequestRecord,
                 tokens: int):
        self._led = led
        self._rec = rec
        self.tokens = int(tokens)
        self.dur_s = 0.0

    def __enter__(self) -> "_ChunkMeter":
        self._w0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.dur_s = time.perf_counter() - self._t0
        self._rec.chunks.append((self._w0, self.dur_s, self.tokens))
        led = self._led
        with led._lock:
            led._iter_prefill_s += self.dur_s
            led._iter_prefill_tokens += self.tokens
        return False


class _IterMeter:
    """Times one engine iteration (the batched decode step) and folds
    the iteration record: active slots, decode/prefill token split,
    the co-batched stall the engine charged to this step."""

    __slots__ = ("_led", "active", "stall_ms", "decode_tokens", "_t0")

    def __init__(self, led: "ServingLedger", active: int,
                 stall_ms: float):
        self._led = led
        self.active = int(active)
        self.stall_ms = float(stall_ms)
        #: Tokens this iteration actually decoded. Defaults to the
        #: active-slot count (one token per live row); a speculative
        #: window overwrites it with its emitted total before the
        #: scope closes, so ``serve.decode_tokens`` stays the real
        #: throughput counter either way.
        self.decode_tokens: int | None = None

    def __enter__(self) -> "_IterMeter":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        led = self._led
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        dtoks = (self.active if self.decode_tokens is None
                 else int(self.decode_tokens))
        with led._lock:
            prefill_s, led._iter_prefill_s = led._iter_prefill_s, 0.0
            ptoks, led._iter_prefill_tokens = \
                led._iter_prefill_tokens, 0
            rec = {"step_ms": round(dur_ms, 3),
                   "active": self.active,
                   "decode_tokens": dtoks,
                   "prefill_tokens": ptoks,
                   "prefill_ms": round(prefill_s * 1e3, 3),
                   "stall_ms": round(self.stall_ms, 3)}
            led._iters.append(rec)
        led.c_steps.add(1)
        led.c_decode_tokens.add(dtoks)
        if ptoks:
            led.c_prefill_tokens.add(ptoks)
        led.g_step_ms.set(rec["step_ms"])
        led.g_active.set(self.active)
        led.g_stall.set(rec["stall_ms"])
        return False


class ServingLedger:
    """Per-engine request-lifecycle + iteration + KV-pressure ledger.

    One per :class:`~ptype_tpu.serve_engine.engine
    .PagedGeneratorActor`; publishes into that engine's metrics
    registry (the process default, or a per-node registry in drills /
    simulated fleets), which the health sampler turns into the series
    the serving alert rules evaluate.
    """

    def __init__(self,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 window: int = REQUEST_WINDOW):
        self.registry = (registry if registry is not None
                         else metrics_mod.metrics)
        reg = self.registry
        self.h_ttft = reg.histogram("serve.ttft_ms")
        self.h_tpot = reg.histogram("serve.tpot_ms")
        self.h_e2e = reg.histogram("serve.e2e_ms")
        self.h_queue_wait = reg.histogram("serve.queue_wait_ms")
        # Per-iteration families resolved once: the iteration meter
        # runs on the hot decode path, and six locked registry name
        # lookups per engine step is exactly the kind of avoidable
        # cost the seam-cost probe would price into the overhead bar.
        self.c_steps = reg.counter("serve.steps")
        self.c_decode_tokens = reg.counter("serve.decode_tokens")
        self.c_prefill_tokens = reg.counter("serve.prefill_tokens")
        self.g_step_ms = reg.gauge("serve.step_ms")
        self.g_active = reg.gauge("serve.active_slots")
        self.g_stall = reg.gauge("serve.stall_ms")
        self._lock = lockcheck.lock("health.serving.ledger")
        self._records: collections.deque = collections.deque(
            maxlen=int(window))
        self._iters: collections.deque = collections.deque(
            maxlen=ITER_WINDOW)
        self._reasons: dict[str, int] = {}
        self._retired = 0
        self._svc_ewma_s = 0.0
        self._ttft_seq = 0
        self._ttft_recent: collections.deque = collections.deque(
            maxlen=TTFT_RECENT)
        self._iter_prefill_s = 0.0
        self._iter_prefill_tokens = 0
        self._evictions_last = 0.0
        # Speculative decoding (ISSUE 12): cumulative window totals
        # behind the summary's spec_accept_rate / spec_tokens; the
        # counter/gauge families resolved lazily in spec_window so a
        # non-speculative engine's registry stays spec-free.
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_tokens = 0
        #: Requests whose KV arrived by migration (ISSUE 16); the
        #: migrate histogram/summary keys stay absent until > 0, so
        #: a non-disaggregated replica's Info() is migration-free.
        self._migrated = 0

    # --------------------------------------------------- request seams

    def enqueued(self, prompt_tokens: int, max_new: int,
                 tp: str | None = None) -> RequestRecord:
        """A row entered the waiting room; ``tp`` is the caller's
        traceparent (captured inside the actor handler span) the
        synthesized span tree will parent under."""
        self.registry.counter("serve.requests").add(1)
        return RequestRecord(prompt_tokens, max_new, tp)

    def head_refused(self, rec: RequestRecord) -> float:
        """The head-of-line reservation was refused; returns seconds
        spent AT THE HEAD so far (the engine's admit-timeout input).
        First refusal stamps the head arrival."""
        now = time.perf_counter()
        if rec.t_head is None:
            rec.t_head = now
        return now - rec.t_head

    def admitted(self, rec: RequestRecord) -> None:
        rec.t_admit = time.perf_counter()

    def chunk(self, rec: RequestRecord, tokens: int) -> _ChunkMeter:
        """Meter one prefill chunk (wrap exactly the chunk compute)."""
        return _ChunkMeter(self, rec, tokens)

    def first_token(self, rec: RequestRecord) -> None:
        rec.w_first = time.time()
        rec.t_first = time.perf_counter()
        rec.tok_t.append(rec.t_first)

    def migrate_begin(self, rec: RequestRecord) -> None:
        """Decode-side migration plan accepted (blocks reserved,
        resident refs taken): the migration leg opens here."""
        rec.w_mig0 = time.time()
        rec.t_mig0 = time.perf_counter()

    def migrate_done(self, rec: RequestRecord, blocks: int,
                     nbytes: int) -> None:
        """The migration wire landed (imported + sealed): close the
        leg, fold ``serve.migrate_ms`` — the histogram behind the
        migration leg's own TTFT attribution (a slow transfer shows
        up HERE before it shows up in ttft_p99)."""
        rec.t_mig1 = time.perf_counter()
        rec.migrate_blocks = int(blocks)
        rec.migrate_bytes = int(nbytes)
        mig = rec.migrate_s()
        if mig is not None:
            self.registry.histogram("serve.migrate_ms").observe(
                mig * 1e3)
        with self._lock:
            self._migrated += 1

    def tokens_emitted(self, recs, counts=None) -> None:
        """One decode step emitted a token on each of ``recs`` — one
        shared stamp (the step boundary), appended per row.
        ``counts`` (speculative windows): per-rec emitted-token counts
        — the window's tokens share the commit stamp, so TPOT stays
        the mean inter-token time of what the caller actually saw."""
        now = time.perf_counter()
        if counts is None:
            for rec in recs:
                rec.tok_t.append(now)
            return
        for rec, n in zip(recs, counts):
            rec.tok_t.extend([now] * int(n))

    def shed_untracked(self) -> None:
        """A shed before any record existed (the chaos admit seam)."""
        self.registry.counter("serve.sheds").add(1)

    def retired(self, rec: RequestRecord | None, reason: str) -> None:
        """Close a row's lifecycle: fold histograms/counters, update
        the service-time EWMA, emit the span tree. Idempotent — engine
        teardown may sweep rows whose shed path already closed them."""
        if rec is None or rec.closed:
            return
        rec.closed = True
        rec.t_done = time.perf_counter()
        rec.reason = reason if reason in RETIRE_REASONS else "error"
        reg = self.registry
        reg.counter("serve.retired").add(1)
        reg.counter(f"serve.retired.{rec.reason}").add(1)
        if rec.reason == "shed":
            reg.counter("serve.sheds").add(1)
        ttft = rec.ttft_s()
        tpot = rec.tpot_s()
        if rec.reason in ("complete", "stop"):
            e2e = rec.t_done - rec.t_enqueue
            self.h_e2e.observe(e2e * 1e3)
            self.h_queue_wait.observe(rec.queue_wait_s() * 1e3)
            if ttft is not None:
                self.h_ttft.observe(ttft * 1e3)
            if tpot is not None:
                self.h_tpot.observe(tpot * 1e3)
            with self._lock:
                self._svc_ewma_s = (
                    e2e if self._svc_ewma_s == 0.0
                    else 0.3 * e2e + 0.7 * self._svc_ewma_s)
                if ttft is not None:
                    self._ttft_seq += 1
                    self._ttft_recent.append(
                        (self._ttft_seq, round(ttft * 1e3, 3)))
        with self._lock:
            self._retired += 1
            self._reasons[rec.reason] = \
                self._reasons.get(rec.reason, 0) + 1
            self._records.append(rec.to_dict())
        self._emit_spans(rec)

    # ------------------------------------------------- iteration seams

    def iteration(self, active: int, stall_ms: float = 0.0) -> _IterMeter:
        """Meter one engine iteration (wrap the batched decode step)."""
        return _IterMeter(self, active, stall_ms)

    def spec_window(self, proposed: int, accepted: int, emitted: int,
                    rate: float) -> None:
        """One committed speculative-decoding window (ISSUE 12):
        ``proposed`` draft tokens scored, ``accepted`` of them kept,
        ``emitted`` tokens committed (accepted prefixes + one
        corrected/bonus token per live row). ``rate`` is the engine's
        accept-rate EWMA — published as the ``serve.spec_accept_rate``
        gauge the gateway probes, ``obs serve``, and a fleet-wide
        collapse diagnosis all read; the counters
        (``serve.spec_windows`` / ``spec_proposed`` / ``spec_accepted``
        / ``spec_tokens``) carry the cumulative totals behind the
        summary's measured speedup accounting."""
        reg = self.registry
        reg.counter("serve.spec_windows").add(1)
        if proposed:
            reg.counter("serve.spec_proposed").add(int(proposed))
        if accepted:
            reg.counter("serve.spec_accepted").add(int(accepted))
        if emitted:
            reg.counter("serve.spec_tokens").add(int(emitted))
        reg.gauge("serve.spec_accept_rate").set(round(float(rate), 4))
        with self._lock:
            self._spec_proposed += int(proposed)
            self._spec_accepted += int(accepted)
            self._spec_tokens += int(emitted)

    def kv_sample(self, stats: dict, prefix_hit_rate: float) -> None:
        """Publish one KV-pool pressure sample from
        ``BlockPool.stats()`` — the ``kv.*`` names the serving alert
        rules key on; the eviction counter carries deltas so the
        sampler's ``kv.evictions.rate`` series is a real rate."""
        reg = self.registry
        reg.gauge("kv.free_blocks").set(stats["kv_free_blocks"])
        reg.gauge("kv.cached_blocks").set(stats["kv_cached_blocks"])
        reg.gauge("kv.used_blocks").set(stats["kv_used_blocks"])
        reg.gauge("kv.total_blocks").set(stats["kv_total_blocks"])
        reg.gauge("kv.util_pct").set(stats["kv_util_pct"])
        reg.gauge("kv.prefix_hit_rate").set(float(prefix_hit_rate))
        ev = float(stats.get("kv_evictions", 0))
        with self._lock:
            delta, self._evictions_last = \
                ev - self._evictions_last, ev
        if delta > 0:
            reg.counter("kv.evictions").add(delta)

    # ------------------------------------------------------- readouts

    def spec_totals(self) -> tuple[int, int, int]:
        """Cumulative (proposed, accepted, emitted) speculative
        totals — the ONE accumulation home (the engine derives its
        Info() surface from this; a second engine-side copy would be
        a drift surface)."""
        with self._lock:
            return (self._spec_proposed, self._spec_accepted,
                    self._spec_tokens)

    def svc_ewma_s(self) -> float:
        """EWMA of completed-request service seconds — the engine's
        backlog-proportional retry-after hint."""
        with self._lock:
            return self._svc_ewma_s

    def ttft_recent(self) -> list[list[float]]:
        """Recent (seq, ttft_ms) samples for ``Info()`` — the gateway
        probe feeds NEW ones (seq above its high-water mark) into the
        fleet-level SLO tracker, so its ttft percentiles are fed from
        real per-request samples, never percentile-of-percentile."""
        with self._lock:
            return [[s, ms] for s, ms in self._ttft_recent]

    def records(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._records)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def summary(self) -> dict:
        with self._lock:
            retired = self._retired
            reasons = dict(self._reasons)
            spec_prop = self._spec_proposed
            spec_acc = self._spec_accepted
            spec_toks = self._spec_tokens
            migrated = self._migrated
        out = {}
        if spec_prop:
            # Only once speculation actually ran: a non-speculative
            # replica's Info() stays spec-free, so fleet views can
            # tell "no speculation" from "accept rate 0".
            out["spec_accept_rate"] = round(spec_acc / spec_prop, 4)
            out["spec_tokens"] = spec_toks
        if migrated:
            # Same contract for migration: only once a wire actually
            # landed here.
            out["migrated_requests"] = migrated
            out["migrate_p99_ms"] = round(
                self.registry.histogram("serve.migrate_ms")
                .percentile(99), 3)
        return {
            **out,
            "requests_retired": retired,
            "retire_reasons": reasons,
            "ttft_p50_ms": round(self.h_ttft.percentile(50), 3),
            "ttft_p99_ms": round(self.h_ttft.percentile(99), 3),
            "tpot_p50_ms": round(self.h_tpot.percentile(50), 3),
            "tpot_p99_ms": round(self.h_tpot.percentile(99), 3),
            "e2e_p50_ms": round(self.h_e2e.percentile(50), 3),
            "e2e_p99_ms": round(self.h_e2e.percentile(99), 3),
            "queue_wait_p99_ms": round(
                self.h_queue_wait.percentile(99), 3),
        }

    def iteration_summary(self) -> dict:
        with self._lock:
            iters = list(self._iters)
        if not iters:
            return {"iterations": 0, "step_ms_mean": 0.0,
                    "active_mean": 0.0, "prefill_token_share": 0.0}
        n = len(iters)
        dtoks = sum(r["decode_tokens"] for r in iters)
        ptoks = sum(r["prefill_tokens"] for r in iters)
        return {
            "iterations": n,
            "step_ms_mean": round(
                sum(r["step_ms"] for r in iters) / n, 3),
            "active_mean": round(
                sum(r["active"] for r in iters) / n, 2),
            "stall_ms_max": round(
                max(r["stall_ms"] for r in iters), 3),
            "prefill_token_share": round(
                ptoks / (ptoks + dtoks), 4) if ptoks + dtoks else 0.0,
        }

    # ----------------------------------------------------- span trees

    def _emit_spans(self, rec: RequestRecord) -> None:
        """Synthesize the request's span tree into the flight recorder
        (no-op unless tracing is armed AND the request carried a
        traceparent). Children of the actor handler span that carried
        the request, anchored at the record's own wall stamps."""
        recd = trace.recorder()
        if recd is None or rec.tp is None:
            return
        parent = trace.parse_traceparent(rec.tp)
        if parent is None:
            return
        trace_id, parent_id = parent
        admit = trace.Span("serve.admit", trace_id, parent_id)
        admit.start_s = rec.w_enqueue
        anchor = (rec.t_admit if rec.t_admit is not None
                  else rec.t_done)
        admit.dur_s = max(0.0, (anchor or rec.t_enqueue)
                          - rec.t_enqueue)
        admit.attrs = {
            "queue_wait_ms": round(rec.queue_wait_s() * 1e3, 3),
            "reserve_wait_ms": round(rec.reserve_wait_s() * 1e3, 3),
            "prompt_tokens": rec.prompt_tokens,
            "reused_blocks": rec.reused_blocks,
            # Forensics stage tag: an admit wait on a decode-class
            # engine (KV arrived over the wire) is decode-queue time,
            # not front-door queue-wait.
            "stage": ("decode-queue" if rec.t_mig0 is not None
                      else "queue-wait"),
        }
        if rec.reason == "shed":
            admit.status = "shed"
        elif rec.reason not in ("complete", "stop"):
            admit.status = rec.reason or "error"
        recd.record(admit)
        mig = rec.migrate_s()
        if mig is not None:
            sp = trace.Span("serve.migrate", trace_id, parent_id)
            sp.start_s = rec.w_mig0
            sp.dur_s = mig
            sp.attrs = {"blocks": rec.migrate_blocks,
                        "bytes": rec.migrate_bytes,
                        "dedup_blocks": rec.reused_blocks,
                        "stage": "migrate"}
            recd.record(sp)
        for i, (w0, dur, tokens) in enumerate(rec.chunks):
            sp = trace.Span(f"serve.prefill.chunk[{i}]", trace_id,
                            parent_id)
            sp.start_s = w0
            sp.dur_s = dur
            sp.attrs = {"tokens": tokens, "stage": "prefill"}
            recd.record(sp)
        if rec.t_first is not None:
            dec = trace.Span("serve.decode", trace_id, parent_id)
            dec.start_s = rec.w_first
            dec.dur_s = max(0.0, rec.t_done - rec.t_first)
            dec.attrs = {"tokens": len(rec.tok_t),
                         "reason": rec.reason,
                         "stage": "decode",
                         "ttft_ms": round(rec.ttft_s() * 1e3, 3)}
            tpot = rec.tpot_s()
            if tpot is not None:
                dec.attrs["tpot_ms"] = round(tpot * 1e3, 3)
            # The acceptance event: where the request's first token
            # materialized on the shared timeline.
            dec.events.append({"name": "first_token", "t": 0.0})
            recd.record(dec)


# --------------------------------------------------------- bench probe


def measure_seam_cost_us(iters: int = 5000) -> dict:
    """Direct cost of the ledger seams one engine iteration pays (one
    ``iteration`` scope + one shared ``tokens_emitted`` stamp) —
    measured the same way PR 8 costs the profiling plane
    (``profile_overhead_pct``): a tight loop over the real calls,
    because the signal is microseconds against a multi-millisecond
    engine step and a wall-clock A/B on a shared host reports
    scheduler jitter, not the seam. ``bench.py --serve`` divides this
    by the measured engine-iteration time for
    ``serving_ledger_overhead_pct`` (<1% bar, reported not asserted).
    """
    led = ServingLedger(registry=metrics_mod.MetricsRegistry())
    rec = led.enqueued(8, 8)
    led.admitted(rec)
    led.first_token(rec)
    t0 = time.perf_counter()
    for _ in range(iters):
        with led.iteration(active=1, stall_ms=0.0):
            pass
        led.tokens_emitted((rec,))
        rec.tok_t.clear()
    cost_s = (time.perf_counter() - t0) / iters
    return {"seam_cost_us": round(cost_s * 1e6, 3), "iters": iters}
