"""Time-series tier of the cluster health plane.

PR 4's telemetry pull returned point-in-time `MetricsRegistry`
snapshots — a number with no history, which no alert rule can reason
about (a shed *rate*, a loss *spike*, memory *growth* are all
derivatives). This module adds the bounded history:

- :class:`SeriesRing` — one named series, a fixed-capacity ring of
  (wall-clock t, value) points with non-decreasing timestamps;
- :class:`SeriesStore` — the per-process map of rings, snapshotted as
  plain ``{name: [[t, v], ...]}`` JSON for the telemetry endpoint;
- :class:`Sampler` — a background thread stamping the registry into
  the store at a fixed cadence. Change-driven: a family that did not
  move since the last tick appends nothing, and the walk list is
  cached against the registry's version, so an idle process's tick
  allocates nothing. Counters additionally get their rate window
  stamped (:meth:`~ptype_tpu.metrics.Counter.sample`) and a
  ``<name>.rate`` series.

Arm the process-wide default with :func:`start`; the built-in
``ptype.Telemetry`` actor endpoint then includes ``series`` in every
pull, so ``telemetry.cluster_snapshot`` carries recent series per
node — the input the alert rules (:mod:`ptype_tpu.health.rules`)
evaluate.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from ptype_tpu import lockcheck

from ptype_tpu import metrics as metrics_mod

#: Default points kept per series: ~8.5 min of history at the default
#: 1 s cadence — enough for every rule window, bounded per process.
SERIES_CAPACITY = 512
#: Default sampler cadence.
DEFAULT_CADENCE_S = 1.0
#: Points returned per series in a telemetry pull (bounds the wire).
SNAPSHOT_LIMIT = 180


class SeriesRing:
    """One bounded time series: (t, value) points, timestamps clamped
    non-decreasing (a wall-clock step backwards — NTP slew — must not
    produce a series that runs backwards)."""

    __slots__ = ("name", "_points", "_lock")

    def __init__(self, name: str, capacity: int = SERIES_CAPACITY):
        self.name = name
        self._points: collections.deque = collections.deque(
            maxlen=int(capacity))
        self._lock = lockcheck.lock("health.series.ring")

    def append(self, t: float, value: float) -> None:
        with self._lock:
            if self._points and t < self._points[-1][0]:
                t = self._points[-1][0]
            self._points.append((float(t), float(value)))

    def points(self, limit: int | None = None) -> list[tuple[float, float]]:
        with self._lock:
            out = list(self._points)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def last(self) -> tuple[float, float] | None:
        with self._lock:
            return self._points[-1] if self._points else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)


class SeriesStore:
    """Named series for one process — what a ``ptype.Telemetry`` pull
    serializes and the alert rules read back per node."""

    def __init__(self, capacity: int = SERIES_CAPACITY):
        self.capacity = int(capacity)
        self._series: dict[str, SeriesRing] = {}
        self._lock = lockcheck.lock("health.series.store")

    def series(self, name: str) -> SeriesRing:
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                ring = self._series[name] = SeriesRing(name, self.capacity)
            return ring

    def get(self, name: str) -> SeriesRing | None:
        with self._lock:
            return self._series.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot(self, limit: int = SNAPSHOT_LIMIT) -> dict:
        """``{name: [[t, v], ...]}`` — plain JSON for the wire."""
        with self._lock:
            rings = list(self._series.values())
        return {r.name: [[round(t, 3), v] for t, v in r.points(limit)]
                for r in rings}


class Sampler:
    """Background registry→series sampler at a fixed cadence.

    Change-driven: per family the last stamped value (counters,
    gauges) or observation count (timings, histograms) is remembered,
    and an unchanged family appends no point — the zero-alloc-when-
    idle contract. The family walk list itself is cached against
    ``registry.version`` so a quiet tick is reads only.
    """

    def __init__(self, registry: metrics_mod.MetricsRegistry | None = None,
                 store: SeriesStore | None = None,
                 cadence_s: float = DEFAULT_CADENCE_S,
                 capacity: int = SERIES_CAPACITY,
                 memory: bool = True):
        self.registry = (registry if registry is not None
                         else metrics_mod.metrics)
        self.store = store if store is not None else SeriesStore(capacity)
        self.cadence_s = float(cadence_s)
        #: Also refresh the ``mem.*`` watermark gauges each tick, so
        #: memory-growth alerts have a series without any caller
        #: touching record_memory_gauges.
        self.memory = memory
        self.ticks = 0
        #: Wall time of the most recent tick — the measured overhead
        #: number (sampler_overhead_pct = last_tick_s / cadence_s).
        self.last_tick_s = 0.0
        self._last: dict[str, float] = {}
        self._walk: tuple | None = None
        self._walk_version = -1
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        #: sample_once is called both by the background loop and by
        #: callers flushing final values — unserialized ticks would
        #: double-append points and double-stamp rate windows.
        self._tick_lock = lockcheck.lock("health.sampler.tick")

    # ---------------------------------------------------------- sampling

    def _families(self) -> tuple:
        version = self.registry.version
        if self._walk is None or version != self._walk_version:
            version, counters, timings, gauges, hists = \
                self.registry.families()
            self._walk = (counters, timings, gauges, hists)
            self._walk_version = version
        return self._walk

    def sample_once(self, now: float | None = None,
                    now_mono: float | None = None) -> int:
        """One tick: stamp every family that moved. Returns points
        appended. ``now`` (wall clock — series timestamps must stitch
        across nodes) and ``now_mono`` (rate windows) are injectable
        for deterministic tests."""
        now = time.time() if now is None else now
        now_mono = time.monotonic() if now_mono is None else now_mono
        with self._tick_lock:
            return self._sample_locked(now, now_mono)

    def _sample_locked(self, now: float, now_mono: float) -> int:
        if self.memory:
            metrics_mod.record_memory_gauges(self.registry)
        counters, timings, gauges, hists = self._families()
        last = self._last
        appended = 0
        for name, c in counters.items():
            v = c.value
            if last.get("c:" + name) == v:
                # Value flat — but a previously non-zero rate must
                # DECAY to zero, not freeze at its last busy reading:
                # keep stamping the rate window until it reads 0, then
                # go fully idle (the zero-alloc contract resumes).
                if last.get("r:" + name):
                    c.sample(now_mono)
                    rate = c.rate(now=now_mono)
                    if rate < 1e-9:
                        rate = 0.0
                    last["r:" + name] = rate
                    self.store.series(f"{name}.rate").append(now, rate)
                    appended += 1
                continue
            last["c:" + name] = v
            c.sample(now_mono)
            self.store.series(name).append(now, v)
            rate = c.rate(now=now_mono)
            last["r:" + name] = rate
            self.store.series(f"{name}.rate").append(now, rate)
            appended += 2
        for name, g in gauges.items():
            v = g.value
            if last.get("g:" + name) == v:
                continue
            last["g:" + name] = v
            self.store.series(name).append(now, v)
            appended += 1
        for name, t in timings.items():
            n = t.count
            if last.get("t:" + name) == n:
                continue
            last["t:" + name] = n
            self.store.series(f"{name}.last_s").append(now, t.last)
            self.store.series(f"{name}.count").append(now, n)
            appended += 2
        for name, h in hists.items():
            n = h.count
            if last.get("h:" + name) == n:
                continue
            last["h:" + name] = n
            self.store.series(f"{name}.p99").append(
                now, h.percentile(99.0))
            self.store.series(f"{name}.count").append(now, n)
            appended += 2
        self.ticks += 1
        return appended

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Sampler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="health-sampler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._closed.wait(self.cadence_s):
            t0 = time.perf_counter()
            self.sample_once()
            self.last_tick_s = time.perf_counter() - t0

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# --------------------------------------------------- process-wide default

_default: Sampler | None = None
_default_lock = threading.Lock()


def start(registry: metrics_mod.MetricsRegistry | None = None,
          cadence_s: float = DEFAULT_CADENCE_S,
          capacity: int = SERIES_CAPACITY) -> Sampler:
    """Arm (or return) the process-wide default sampler. Its store is
    what the built-in ``ptype.Telemetry`` endpoint serves as
    ``series`` — one call turns a node's metrics into history every
    cluster_snapshot carries."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Sampler(registry, cadence_s=cadence_s,
                               capacity=capacity).start()
        return _default


def stop() -> None:
    global _default
    with _default_lock:
        sampler, _default = _default, None
    if sampler is not None:
        sampler.close()


def default() -> Sampler | None:
    return _default


def default_snapshot(limit: int = SNAPSHOT_LIMIT) -> dict:
    """The default sampler's series snapshot; ``{}`` when not armed —
    what :func:`ptype_tpu.trace.telemetry` includes per pull."""
    sampler = _default
    return sampler.store.snapshot(limit) if sampler is not None else {}


def telemetry_endpoint(registry: metrics_mod.MetricsRegistry,
                       store: SeriesStore, service: str = ""):
    """A per-node ``ptype.Telemetry`` handler for processes hosting
    several SIMULATED nodes (drills, demos, tests): same shape as
    :func:`ptype_tpu.trace.telemetry` but over THIS node's registry
    and series store. Register it per server:

    >>> server.register_function(
    ...     "ptype.Telemetry", telemetry_endpoint(reg, sampler.store))
    """

    def handler(span_limit: int = 256) -> dict:
        del span_limit  # simulated nodes carry no flight recorder
        return {
            "pid": os.getpid(),
            "service": service,
            "tracing": False,
            "ts": round(time.time(), 3),
            "metrics": registry.snapshot(),
            "series": store.snapshot(),
            "spans": [],
            "spans_finished": 0,
        }

    return handler
