"""Tail forensics: per-request critical-path attribution.

The serving path crosses admission queue -> route -> prefill replica ->
KV migration wire -> decode queue -> decode (spec windows, host syncs)
— and when ``ttft-p99`` pages, a number is not a culprit.  This module
turns a stitched cross-process span tree (``telemetry.stitch_traces``)
into a **stage-attributed waterfall**: every microsecond of the request
wall is assigned to exactly one named stage, most-specific span wins,
and the residue is reported as an honest unattributed gap (budgeted at
<=5% of wall — anything larger means the span vocabulary has a hole).

Three layers share the stage vocabulary defined here:

* :func:`extract_waterfall` / :func:`render_waterfall` — the per-trace
  forensic view (``obs request <trace_id>``).
* :func:`stage_budgets_ms` / :func:`culprit_stage` — decompose
  ``slo_ttft_p99_ms`` into per-stage ceilings; the ``slo-stage-breach``
  health rule and the loadgen ledger's per-request blame both price
  against these.
* :func:`render_tail` — the fleet's worst exemplars + stage breakdown
  (``obs tail``), fed by :class:`~ptype_tpu.metrics.Histogram`
  exemplars riding the ordinary telemetry pull.

Stage names (the shared vocabulary):

================  ====================================================
``queue-wait``    gateway admission gate + engine-side admit queue
``route``         replica pick (directory walk, class filtering)
``prefill``       prefill compute (gateway rpc wall, engine chunks)
``migrate``       KV wire: plan/export/import/release legs
``decode-queue``  admit wait on the decode engine (KV already landed)
``decode``        decode compute incl. speculative windows
``spec-window``   speculative propose/verify wall (engine detail)
``host-sync``     host blocking on device (engine detail)
``rpc``           residual RPC wall not covered by a finer span —
                  serialization + socket time, honestly named
================  ====================================================
"""

from __future__ import annotations

import json
import os

__all__ = [
    "STAGES", "DEFAULT_STAGE_FRACTIONS", "stage_budgets_ms",
    "culprit_stage", "stage_of", "extract_waterfall",
    "render_waterfall", "render_tail", "measure_forensics_overhead",
    "COVERAGE_FLOOR_PCT",
]

#: The full stage vocabulary, coarse-to-fine.
STAGES = ("queue-wait", "route", "prefill", "migrate", "decode-queue",
          "decode", "spec-window", "host-sync", "rpc")

#: A waterfall attributing less than this share of wall clock to named
#: stages indicates a hole in the span vocabulary (tentpole bar).
COVERAGE_FLOOR_PCT = 95.0

# ------------------------------------------------------- stage budgets

#: Per-stage ceilings as fractions of ``slo_ttft_p99_ms``.  These are
#: *ceilings*, not a partition — they deliberately sum past 1.0 because
#: a healthy request never maxes every stage at once; a single stage
#: crossing its ceiling is what names the culprit.  Decode runs past
#: first-token so it prices against the full SLO.
DEFAULT_STAGE_FRACTIONS = {
    "queue-wait": 0.20,
    "route": 0.05,
    "prefill": 0.60,
    "migrate": 0.50,
    "decode-queue": 0.15,
    "decode": 1.00,
    "spec-window": 0.50,
    "host-sync": 0.10,
    "rpc": 1.00,
}


def stage_budgets_ms(slo_ttft_p99_ms: float,
                     fractions: dict | None = None) -> dict:
    """Decompose a TTFT SLO into per-stage millisecond ceilings."""
    frac = DEFAULT_STAGE_FRACTIONS if fractions is None else fractions
    slo = float(slo_ttft_p99_ms)
    return {s: slo * f for s, f in frac.items()}


def culprit_stage(stages: dict, budgets: dict | None = None) -> str | None:
    """Name the stage to blame for a slow request.

    The stage with the largest *overage* past its budget wins; when no
    stage is over budget (or no budgets are given) the longest stage
    wins — a slow request always gets exactly one culprit, so tail
    counts sum to the ``slo_bad`` total.
    """
    if not stages:
        return None
    if budgets:
        over = {s: d - budgets[s] for s, d in stages.items()
                if s in budgets and d - budgets[s] > 0.0}
        if over:
            return max(over, key=over.get)
    return max(stages, key=stages.get)


# ------------------------------------------------ span -> stage mapping

#: Attribution priority when spans overlap: engine-side spans are the
#: finer truth inside a gateway RPC wall (the admit wait *inside* the
#: prefill call is queue time, not compute), and generic ``rpc.call``
#: walls are the coarsest cover of all.
_TIER_SERVE, _TIER_GATEWAY, _TIER_RPC = 3, 2, 1

#: Tie-break between same-tier overlapping spans (e.g. the decode
#: engine's migrate import vs its admit queue): the rarer, more
#: diagnostic stage wins.
_STAGE_RANK = {s: i for i, s in enumerate(
    ("rpc", "queue-wait", "route", "decode", "decode-queue", "prefill",
     "migrate", "spec-window", "host-sync"))}

#: RPC methods that *are* a stage: the migration wire legs and the
#: combined migrate+decode call.
_RPC_METHOD_STAGE = {
    "MigratePlan": "migrate",
    "ExportBlocks": "migrate",
    "ImportBlocks": "migrate",
    "ReleaseExport": "migrate",
    "MigrateDecode": "decode",
}


def stage_of(span: dict) -> tuple[str, int] | None:
    """Map one span to ``(stage, priority_tier)`` or ``None``.

    An explicit ``stage`` attr (stamped by the serving ledger's span
    synthesis) always wins — name matching is the fallback for spans
    recorded before the attr existed or by the gateway side.
    """
    name = span.get("name", "")
    attrs = span.get("attrs") or {}
    stage = attrs.get("stage")
    if stage in _STAGE_RANK:
        tier = _TIER_SERVE if name.startswith("serve.") else _TIER_GATEWAY
        return stage, tier
    if name.startswith("serve."):
        if name.startswith("serve.admit"):
            return "queue-wait", _TIER_SERVE
        if name.startswith("serve.prefill"):
            return "prefill", _TIER_SERVE
        if name.startswith("serve.migrate"):
            return "migrate", _TIER_SERVE
        if name.startswith("serve.decode"):
            return "decode", _TIER_SERVE
        if name.startswith("serve.spec"):
            return "spec-window", _TIER_SERVE
        return None
    if name.startswith("host.") or "block_until_ready" in name:
        return "host-sync", _TIER_SERVE
    if name.startswith("gateway."):
        leaf = name.split(".", 1)[1]
        if leaf == "admit":
            return "queue-wait", _TIER_GATEWAY
        if leaf == "route":
            return "route", _TIER_GATEWAY
        if leaf == "prefill":
            return "prefill", _TIER_GATEWAY
        if leaf == "migrate":
            return "migrate", _TIER_GATEWAY
        return None
    if name == "rpc.call":
        method = str(attrs.get("method", ""))
        method = method.rsplit(".", 1)[-1]
        stage = _RPC_METHOD_STAGE.get(method)
        if stage is not None:
            return stage, _TIER_GATEWAY
        return "rpc", _TIER_RPC
    return None


# ------------------------------------------------- waterfall extraction


def extract_waterfall(spans: list, trace_id: str | None = None) -> dict:
    """Attribute a stitched trace's wall clock to named stages.

    ``spans`` is a list of span dicts (``Span.to_dict`` shape — what
    ``telemetry.all_spans`` / ``stitch_traces`` yield).  The request
    envelope is the root span when one exists (``gateway.request``, or
    the earliest parentless span), else the min/max span hull.  Every
    elementary interval inside the envelope is assigned to the
    highest-priority covering span's stage; uncovered intervals are the
    unattributed gap.

    Returns ``{"trace_id", "wall_ms", "t0", "stages": {stage: ms},
    "segments": [{stage, start_ms, dur_ms}], "spans": [...],
    "attributed_ms", "unattributed_ms", "coverage_pct", "ok"}`` where
    ``ok`` is the tentpole bar (coverage >= 95%).
    """
    rows = [s for s in spans
            if trace_id is None or s.get("trace_id") == trace_id]
    if not rows:
        raise ValueError(f"no spans for trace {trace_id!r}")
    tids = {s.get("trace_id") for s in rows}
    if trace_id is None:
        if len(tids) != 1:
            raise ValueError(
                f"{len(tids)} traces in span set; pass trace_id")
        trace_id = next(iter(tids))
    rows.sort(key=lambda s: float(s.get("start_s", 0.0)))

    # Envelope: the root request span when present, else the hull.
    root = None
    for s in rows:
        if s.get("name") == "gateway.request":
            root = s
            break
    if root is None:
        for s in rows:
            if not s.get("parent_id"):
                root = s
                break
    if root is not None and float(root.get("dur_s", 0.0)) > 0.0:
        t0 = float(root["start_s"])
        t1 = t0 + float(root["dur_s"])
    else:
        t0 = min(float(s.get("start_s", 0.0)) for s in rows)
        t1 = max(float(s.get("start_s", 0.0)) + float(s.get("dur_s", 0.0))
                 for s in rows)
    wall = max(t1 - t0, 0.0)

    # Staged intervals, clipped to the envelope.
    ivals: list = []   # (a, b, stage, tier)
    annotated: list = []
    for s in rows:
        a = float(s.get("start_s", 0.0))
        b = a + float(s.get("dur_s", 0.0))
        st = stage_of(s)
        annotated.append({
            "name": s.get("name", "?"),
            "node": s.get("node"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
            "start_ms": (a - t0) * 1e3,
            "dur_ms": (b - a) * 1e3,
            "stage": st[0] if st else None,
            "attrs": s.get("attrs") or {},
        })
        if st is None:
            continue
        a, b = max(a, t0), min(b, t1)
        if b > a:
            ivals.append((a, b, st[0], st[1]))

    # Elementary-interval sweep: at each slice the covering span with
    # the highest (tier, stage rank) owns the clock.
    cuts = sorted({t0, t1, *(p for iv in ivals for p in (iv[0], iv[1]))})
    stages_s: dict = {}
    segments: list = []
    for a, b in zip(cuts, cuts[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = None
        for ia, ib, stg, tier in ivals:
            if ia <= mid < ib:
                key = (tier, _STAGE_RANK.get(stg, -1))
                if best is None or key > best[0]:
                    best = (key, stg)
        stg = best[1] if best else None
        if stg is not None:
            stages_s[stg] = stages_s.get(stg, 0.0) + (b - a)
        if segments and segments[-1]["stage"] == stg:
            segments[-1]["dur_ms"] += (b - a) * 1e3
        else:
            segments.append({"stage": stg, "start_ms": (a - t0) * 1e3,
                             "dur_ms": (b - a) * 1e3})

    attributed = sum(stages_s.values())
    coverage = 100.0 * attributed / wall if wall > 0 else 100.0
    return {
        "trace_id": trace_id,
        "t0": t0,
        "wall_ms": wall * 1e3,
        "stages": {s: v * 1e3 for s, v in sorted(
            stages_s.items(), key=lambda kv: -kv[1])},
        "segments": segments,
        "spans": annotated,
        "attributed_ms": attributed * 1e3,
        "unattributed_ms": (wall - attributed) * 1e3,
        "coverage_pct": coverage,
        "ok": coverage >= COVERAGE_FLOOR_PCT,
    }


# ---------------------------------------------------------- rendering


def _bar(frac: float, width: int) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "." * (width - n)


def render_waterfall(wf: dict, width: int = 40) -> str:
    """ASCII waterfall: stage table + per-span timeline rows."""
    wall = wf["wall_ms"] or 1.0
    lines = [
        f"trace {wf['trace_id']}  wall {wf['wall_ms']:.1f}ms  "
        f"coverage {wf['coverage_pct']:.1f}%"
        f"{'' if wf['ok'] else '  (BELOW 95% FLOOR)'}",
        "",
        f"  {'stage':<12} {'ms':>9} {'share':>7}",
    ]
    for stage, ms in wf["stages"].items():
        lines.append(f"  {stage:<12} {ms:>9.2f} {ms / wall:>6.1%}  "
                     f"|{_bar(ms / wall, width)}|")
    gap = wf["unattributed_ms"]
    lines.append(f"  {'(gap)':<12} {gap:>9.2f} {gap / wall:>6.1%}")
    lines.append("")
    for sp in wf["spans"]:
        a = sp["start_ms"] / wall
        d = sp["dur_ms"] / wall
        lead = int(round(a * width))
        body = max(1, int(round(d * width))) if sp["dur_ms"] > 0 else 1
        body = min(body, width - min(lead, width - 1))
        bar = " " * min(lead, width - 1) + "=" * body
        stage = sp["stage"] or "-"
        node = f" @{sp['node']}" if sp.get("node") else ""
        lines.append(
            f"  [{bar:<{width}}] {sp['start_ms']:>8.1f} "
            f"+{sp['dur_ms']:>8.1f}ms  {sp['name']}"
            f" ({stage}){node}")
    return "\n".join(lines)


def render_tail(snapshot: dict, limit: int = 8) -> str:
    """The fleet's worst tail, from an ordinary telemetry snapshot:
    per-histogram worst exemplars (value + trace id — feed these to
    ``obs request``) and the gateway stage-time breakdown."""
    # Worst exemplars across every node's histogram families.
    rows: list = []          # (value, name, trace_id, node)
    stage_p99: dict = {}     # stage -> worst p99 across nodes
    nodes = dict(snapshot.get("nodes", {}))
    if not nodes and "histograms" in snapshot:
        nodes = {"local": {"metrics": snapshot}}
    for key, telem in nodes.items():
        m = telem.get("metrics", telem) or {}
        for name, summ in (m.get("histograms") or {}).items():
            for ex in summ.get("exemplars", ()):
                rows.append((float(ex["value"]), name,
                             ex.get("trace_id", "?"), key))
            if ".stage_ms." in name:
                stage = name.rsplit(".stage_ms.", 1)[1]
                p99 = float(summ.get("p99", 0.0))
                if p99 > stage_p99.get(stage, -1.0):
                    stage_p99[stage] = p99
    rows.sort(key=lambda r: -r[0])
    lines = [f"worst exemplars ({min(limit, len(rows))} of {len(rows)}):"]
    if not rows:
        lines.append("  (none — histograms carry no trace-linked "
                     "observations yet)")
    for value, name, tid, node in rows[:limit]:
        lines.append(f"  {value:>10.2f}  {name:<40} trace={tid}  @{node}")
    lines.append("")
    lines.append("stage p99 (worst node):")
    if not stage_p99:
        lines.append("  (no gateway stage histograms in snapshot)")
    for stage, p99 in sorted(stage_p99.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {stage:<12} {p99:>9.2f}ms")
    lines.append("")
    lines.append("next: obs request <trace_id> renders the waterfall.")
    return "\n".join(lines)


# ------------------------------------------------------- obs plumbing


def waterfall_from_snapshot(snapshot: dict, trace_id: str) -> dict:
    """Stitch a cluster snapshot (or a flight-recorder dump already
    loaded as ``{"traces": ...}``) and extract one trace's waterfall."""
    traces = snapshot.get("traces")
    if traces is None:
        from ptype_tpu import telemetry
        traces = telemetry.stitch_traces(telemetry.all_spans(snapshot))
    spans = traces.get(trace_id)
    if spans is None:
        # Prefix match: operators paste the short id from obs tail.
        hits = [t for t in traces if t.startswith(trace_id)]
        if len(hits) == 1:
            spans = traces[hits[0]]
            trace_id = hits[0]
    if spans is None:
        raise KeyError(
            f"trace {trace_id!r} not found "
            f"({len(traces)} traces in snapshot)")
    return extract_waterfall(spans, trace_id)


def load_dump_traces(path: str) -> dict:
    """Read a flight-recorder ``.jsonl`` dump (``trace.maybe_dump``
    output) into ``{trace_id: [span, ...]}`` — the post-mortem source
    for ``obs request`` when the cluster is gone."""
    spans: list = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if "span_id" in d:
                spans.append(d)
    from ptype_tpu import telemetry
    return telemetry.stitch_traces(spans)


def latest_dump(dump_dir: str) -> str | None:
    """Newest flight-recorder dump in a directory, or None."""
    try:
        names = [n for n in os.listdir(dump_dir)
                 if n.startswith("flight-") and n.endswith(".jsonl")]
    except OSError:
        return None
    if not names:
        return None
    names.sort(key=lambda n: os.path.getmtime(os.path.join(dump_dir, n)))
    return os.path.join(dump_dir, names[-1])


# --------------------------------------------------------- bench probe


def measure_forensics_overhead(iters: int = 20000) -> dict:
    """Marginal cost of the armed exemplar seam on the serving path,
    measured the way every observability probe here is (tight loop over
    the real calls, never a wall-clock A/B): ``Histogram.observe`` with
    a trace id racing the replace-min exemplar slots vs the same
    observe with the seam cold.  ``bench.py --forensics`` divides by
    the engine-iteration wall for the <=1% bar."""
    import time as _time

    from ptype_tpu import metrics as metrics_mod

    reg = metrics_mod.MetricsRegistry()  # private: a probe, not telemetry
    h_plain = reg.histogram("probe.plain")
    h_armed = reg.histogram("probe.armed")
    # Pre-fill the exemplar slots so the steady-state (full-slot
    # replace-min scan) is what gets measured, not the append ramp.
    for i in range(metrics_mod.EXEMPLAR_SLOTS):
        h_armed.observe(1e9 + i, trace_id=f"warm{i}")
    t0 = _time.perf_counter()
    for i in range(iters):
        h_plain.observe(float(i % 997))
    plain_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    for i in range(iters):
        h_armed.observe(float(i % 997), trace_id="deadbeefcafef00d")
    armed_s = _time.perf_counter() - t0
    per_obs_us = max(0.0, (armed_s - plain_s)) / iters * 1e6
    return {
        "iters": iters,
        "observe_plain_us": plain_s / iters * 1e6,
        "observe_armed_us": armed_s / iters * 1e6,
        "exemplar_marginal_us": per_obs_us,
    }
