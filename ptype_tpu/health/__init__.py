"""Cluster health plane: series, goodput accounting, alerting.

The layer that turns the PR 4 telemetry pull plane into live cluster
health (ISSUE 5): bounded per-process time series sampled from the
metrics registry (:mod:`~ptype_tpu.health.series`), a per-step
goodput ledger + cross-node straggler detection over the
``metrics.annotate`` seam (:mod:`~ptype_tpu.health.goodput`),
declarative alert rules with an engine that logs, counts, and
triggers flight-recorder dumps (:mod:`~ptype_tpu.health.rules`), the
live ``obs top`` view (:mod:`~ptype_tpu.health.top`), and — since
ISSUE 8 — the profiling plane (:mod:`~ptype_tpu.health.profiling`):
the ``ptype.Profile`` actor endpoint, alert-triggered device-profile
capture, and compiled-cost MFU accounting. See
docs/OBSERVABILITY.md ("Health plane & alerting") and the per-alert
runbook in docs/OPERATIONS.md.
"""

from ptype_tpu.health.goodput import (GoodputLedger, detect_stragglers,
                                      node_series_means, node_span_means)
from ptype_tpu.health.profiling import (AlertCapture, ProfileError,
                                        compiled_cost,
                                        measure_compiled_cost,
                                        summarize)
from ptype_tpu.health.rules import (Alert, AlertEngine, BurnRateRule,
                                    CapacityHeadroomRule,
                                    ClusterView, CoordFlapRule,
                                    KvPressureRule, LossRule,
                                    MemoryGrowthRule, MfuGapRule,
                                    MigrationStallRule,
                                    P99Rule, PrefixHitCollapseRule,
                                    RecompileStormRule,
                                    ReshardStallRule, Rule,
                                    ServeStallRule, StallRule,
                                    StragglerRule, TtftRule,
                                    default_rules)
from ptype_tpu.health.series import (Sampler, SeriesRing, SeriesStore,
                                     telemetry_endpoint)
from ptype_tpu.health.serving import (RequestRecord, ServingLedger,
                                      measure_seam_cost_us)
from ptype_tpu.health.top import (render_jit, render_scale,
                                  render_serve, render_top,
                                  render_topo, render_traffic,
                                  run_jit, run_scale, run_serve,
                                  run_top, run_topo, run_traffic)

__all__ = [
    "SeriesRing", "SeriesStore", "Sampler", "telemetry_endpoint",
    "GoodputLedger", "detect_stragglers", "node_series_means",
    "node_span_means",
    "ServingLedger", "RequestRecord", "measure_seam_cost_us",
    "AlertCapture", "ProfileError", "compiled_cost",
    "measure_compiled_cost", "summarize",
    "Alert", "AlertEngine", "ClusterView", "Rule", "BurnRateRule",
    "P99Rule", "StallRule", "StragglerRule", "LossRule",
    "CoordFlapRule", "MemoryGrowthRule", "MfuGapRule", "TtftRule",
    "KvPressureRule", "PrefixHitCollapseRule", "ServeStallRule",
    "RecompileStormRule", "MigrationStallRule", "ReshardStallRule",
    "CapacityHeadroomRule",
    "default_rules",
    "render_top", "run_top", "render_serve", "run_serve",
    "render_scale", "run_scale", "render_jit", "run_jit",
    "render_topo", "run_topo", "render_traffic", "run_traffic",
]
