"""Profiling plane: on-demand device profiling + compiled-cost MFU.

ROADMAP items 1 and 2 both gate their remaining headroom on "if a
profile shows the reduce still exposed on real ICI" — and until ISSUE 8
the stack had no way to take that profile: ``metrics.trace`` was a
local-only ``jax.profiler`` wrapper nobody could reach from the
cluster, and the goodput ledger's MFU denominator was the analytic
``models.flops_per_token`` formula, never checked against what XLA
actually compiled. This module is the missing plane, four seams:

- **Capture sessions** (:func:`start` / :func:`stop` /
  :func:`capture`): a managed ``jax.profiler`` XPlane capture into an
  artifact directory (``$PTYPE_PROFILE_DIR`` or a tempdir), returning
  a file manifest — and, on request, the artifact BYTES, so a capture
  can ship over the actor wire. ``jax.profiler`` is process-global
  (one capture at a time); the session lock makes a concurrent start a
  typed :class:`ProfileError`, not a crash. HBM snapshots
  (:func:`memory_snapshot` — ``device.memory_stats()`` plus the pprof
  ``device_memory_profile``) ride along with every capture.
- **The ``ptype.Profile`` actor endpoint** (:func:`endpoint`): every
  :class:`~ptype_tpu.actor.ActorServer` serves it built-in (sibling of
  ``ptype.Telemetry``), so any node's device timeline is one RPC away
  — :func:`ptype_tpu.telemetry.cluster_profile` fans a simultaneous
  capture across the whole registry. Regions already line up across
  the stitched span view and the device timeline because
  ``metrics.annotate`` emits BOTH a profiler ``TraceAnnotation`` and a
  distributed-trace span through the one seam.
- **Alert-triggered capture** (:class:`AlertCapture`): an
  :class:`~ptype_tpu.health.rules.AlertEngine` hook that, when
  ``straggler`` / ``train-stall`` / ``slo-p99`` fires, captures a
  short profile on the NAMED node over its actor surface and drops
  the artifacts next to the flight-recorder dump — rate-limited like
  ``trace.maybe_dump``, so an alert storm cannot turn the profiler
  into a disk-filling loop. Every page becomes a post-mortem with the
  device evidence already attached.
- **Compiled-cost accounting** (:func:`compiled_cost` /
  :func:`measure_compiled_cost`): FLOPs/bytes from XLA's
  ``cost_analysis()`` on the jitted step programs, feeding the goodput
  ledger an ``mfu_compiled`` alongside the analytic MFU
  (:meth:`~ptype_tpu.health.goodput.GoodputLedger.set_compiled_flops`)
  and the ``mfu-divergence`` alert rule — a silent remat or dtype
  change shifts real FLOPs, and today somebody notices. One caveat
  XLA imposes: ``cost_analysis`` counts a while-loop (``lax.scan``)
  body ONCE, so cost lowerings of the transformer step unroll the
  layer scan (``scan_unroll=n_layers``, same math, trip count 1);
  :func:`compiled_cost` on an un-unrolled scan program is a lower
  bound and says so.

The host-side parser (:func:`summarize`) reads the ``*.trace.json.gz``
Chrome-trace artifact jax writes next to the ``.xplane.pb`` — stdlib
gzip+json, so top-op tables work on CPU test runs with no TensorBoard.

Lint rule PT008 (tools/ptlint) closes the side door: raw
``jax.profiler.start_trace`` / ``stop_trace`` calls are forbidden in
``ptype_tpu/`` outside metrics.py and this module — every capture goes
through the rate-limited, artifact-managed seam.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import os
import threading
import time

import jax

from ptype_tpu import logs

log = logs.get_logger("profiling")

#: Env var: base directory for capture artifacts (default: a
#: process-qualified tempdir subdirectory).
PROFILE_DIR_ENV = "PTYPE_PROFILE_DIR"
#: Default on-demand capture length.
DEFAULT_CAPTURE_S = 0.5
#: Hard cap on a single capture's duration — a fat-fingered
#: ``duration=300`` from an operator (or a buggy alert hook) must not
#: pin the process-global profiler for minutes.
MAX_CAPTURE_S = 30.0
#: Byte budget for shipping artifact data over the wire in one reply.
MAX_SHIP_BYTES = 32 * 2**20
#: Minimum seconds between alert-triggered captures per (rule, node) —
#: the ``trace.maybe_dump`` contract, applied to device profiles.
CAPTURE_MIN_INTERVAL_S = 60.0


class ProfileError(RuntimeError):
    """Typed misuse of the process-global profiler (double start, stop
    without start, capture path escape)."""


# -------------------------------------------------------- capture session

_lock = threading.Lock()
#: The active session: {"dir", "label", "t0"} — jax.profiler is
#: process-global, so there is at most one.
_active: dict | None = None


def base_dir() -> str:
    """Artifact root: ``$PTYPE_PROFILE_DIR`` or a tempdir subdir."""
    d = os.environ.get(PROFILE_DIR_ENV)
    if d:
        return d
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f"ptype-profile-{os.getpid()}")


def active() -> bool:
    with _lock:
        return _active is not None


def start(label: str = "", base: str | None = None) -> dict:
    """Begin an XPlane capture into a fresh artifact directory.

    Returns ``{"dir", "label", "ts"}``. Raises :class:`ProfileError`
    if a capture is already running (the profiler is process-global).
    """
    global _active
    d = os.path.join(base or base_dir(),
                     f"{label or 'capture'}-{time.monotonic_ns()}")
    with _lock:
        if _active is not None:
            raise ProfileError(
                f"profile capture already active in {_active['dir']!r}")
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
        _active = {"dir": d, "label": label,
                   "t0": time.perf_counter()}
    log.info("profile capture started", kv={"dir": d, "label": label})
    return {"dir": d, "label": label, "ts": round(time.time(), 3)}


def stop(include_data: bool = False,
         max_bytes: int = MAX_SHIP_BYTES) -> dict:
    """End the active capture. Returns the artifact manifest::

        {"dir", "label", "duration_s", "files": [{"path", "size"}],
         "memory": <memory_snapshot()>, "data": {relpath: bytes}?}

    ``data`` (with ``include_data``) carries artifact bytes up to
    ``max_bytes`` total — the wire-shipping path; oversize files are
    listed in the manifest but skipped from ``data`` (``truncated``
    names them). Raises :class:`ProfileError` without an active
    capture.
    """
    global _active
    with _lock:
        if _active is None:
            raise ProfileError("no profile capture active")
        sess, _active = _active, None
        jax.profiler.stop_trace()
    dur = time.perf_counter() - sess["t0"]
    out = {"dir": sess["dir"], "label": sess["label"],
           "duration_s": round(dur, 4),
           "files": artifact_files(sess["dir"]),
           "memory": memory_snapshot()}
    if include_data:
        data: dict[str, bytes] = {}
        truncated: list[str] = []
        budget = int(max_bytes)
        for f in out["files"]:
            if f["size"] > budget:
                truncated.append(f["path"])
                continue
            try:
                with open(os.path.join(sess["dir"], f["path"]),
                          "rb") as fp:
                    data[f["path"]] = fp.read()
            except OSError:
                truncated.append(f["path"])
                continue
            budget -= f["size"]
        out["data"] = data
        if truncated:
            out["truncated"] = truncated
    log.info("profile capture stopped",
             kv={"dir": sess["dir"], "files": len(out["files"]),
                 "duration_s": out["duration_s"]})
    return out


def capture(duration_s: float = DEFAULT_CAPTURE_S, label: str = "",
            include_data: bool = False,
            max_bytes: int = MAX_SHIP_BYTES,
            base: str | None = None) -> dict:
    """One-shot: start, run for ``duration_s`` (capped at
    :data:`MAX_CAPTURE_S`), stop. The remote-capture verb behind the
    ``ptype.Profile`` endpoint and every alert-triggered capture."""
    duration_s = min(max(float(duration_s), 0.0), MAX_CAPTURE_S)
    start(label=label, base=base)
    try:
        threading.Event().wait(duration_s)
    finally:
        result = stop(include_data=include_data, max_bytes=max_bytes)
    return result


def artifact_files(d: str) -> list[dict]:
    """Relative-path manifest of every file under ``d`` (sorted)."""
    out: list[dict] = []
    for dirpath, dirnames, filenames in os.walk(d):
        dirnames.sort()
        for f in sorted(filenames):
            p = os.path.join(dirpath, f)
            out.append({"path": os.path.relpath(p, d),
                        "size": os.path.getsize(p)})
    return out


def fetch(dir_path: str, relpath: str) -> bytes:
    """One artifact file's bytes — the follow-up verb for files the
    capture reply truncated. The resolved path must stay under
    ``dir_path`` (no traversal from the wire)."""
    root = os.path.realpath(dir_path)
    p = os.path.realpath(os.path.join(root, relpath))
    if not p.startswith(root + os.sep):
        raise ProfileError(f"artifact path escapes capture dir: "
                           f"{relpath!r}")
    with open(p, "rb") as fp:
        return fp.read()


def memory_snapshot(include_profile: bool = False) -> dict:
    """Per-device HBM snapshot + host watermarks.

    ``devices``: one row per local device with whatever the backend's
    ``memory_stats()`` reports (PJRT allocator bytes_in_use /
    peak_bytes_in_use / bytes_limit; ``{}`` on backends without stats
    — CPU). ``host`` is :func:`ptype_tpu.metrics.memory_watermarks`
    (always has the RSS fallback). With ``include_profile`` the pprof
    ``device_memory_profile()`` gzip bytes ride along for offline
    ``pprof`` analysis; its size is always reported.
    """
    from ptype_tpu import metrics as metrics_mod

    devices = []
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # noqa: BLE001 — per-backend best effort
            stats = {}
        devices.append({
            "id": dev.id, "platform": dev.platform,
            "kind": getattr(dev, "device_kind", ""),
            "stats": {k: int(v) for k, v in stats.items()
                      if isinstance(v, (int, float))},
        })
    out = {"devices": devices,
           "host": metrics_mod.memory_watermarks()}
    try:
        prof = jax.profiler.device_memory_profile()
        out["memory_profile_size"] = len(prof)
        if include_profile:
            out["memory_profile"] = prof
    except Exception as e:  # noqa: BLE001 — optional, per-backend
        out["memory_profile_note"] = f"{type(e).__name__}: {e}"
    return out


# ----------------------------------------------------- the actor endpoint


def endpoint(cmd: str, options: dict | None = None):
    """The built-in ``ptype.Profile`` actor endpoint (registered by
    every :class:`~ptype_tpu.actor.ActorServer`, sibling of
    ``ptype.Telemetry``). Verbs::

        ("status",)                       -> platform + active session
        ("start",   {"label"})            -> begin a capture
        ("stop",    {"include_data", "max_bytes"})
        ("capture", {"duration_s", "label", "include_data", ...})
        ("memory",  {"include_profile"})  -> HBM snapshot
        ("fetch",   {"dir", "path"})      -> one artifact's bytes

    Errors (double start, unknown verb) surface as typed exceptions —
    the actor layer marshals them to the caller as ``RemoteError``.
    """
    opts = dict(options or {})
    if cmd == "status":
        with _lock:
            sess = dict(_active) if _active is not None else None
        dev = jax.local_devices()[0]
        return {"pid": os.getpid(), "platform": dev.platform,
                "device_kind": getattr(dev, "device_kind", ""),
                "devices": jax.local_device_count(),
                "active": sess is not None,
                "dir": sess["dir"] if sess else None}
    if cmd == "start":
        return start(label=opts.get("label", ""))
    if cmd == "stop":
        return stop(include_data=opts.get("include_data", False),
                    max_bytes=opts.get("max_bytes", MAX_SHIP_BYTES))
    if cmd == "capture":
        return capture(
            duration_s=opts.get("duration_s", DEFAULT_CAPTURE_S),
            label=opts.get("label", ""),
            include_data=opts.get("include_data", True),
            max_bytes=opts.get("max_bytes", MAX_SHIP_BYTES))
    if cmd == "memory":
        return memory_snapshot(
            include_profile=opts.get("include_profile", False))
    if cmd == "fetch":
        return fetch(opts["dir"], opts["path"])
    raise ProfileError(f"ptype.Profile: unknown command {cmd!r}")


def write_artifacts(out_dir: str, result: dict) -> list[str]:
    """Persist a shipped capture reply (the ``data`` bytes from
    :func:`stop`/:func:`capture` over the wire) under ``out_dir``;
    returns the written paths. Relative paths are sanitized the same
    way :func:`fetch` guards reads."""
    root = os.path.realpath(out_dir)
    os.makedirs(root, exist_ok=True)
    written: list[str] = []
    for rel, blob in (result.get("data") or {}).items():
        p = os.path.realpath(os.path.join(root, rel))
        if not p.startswith(root + os.sep):
            continue
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as fp:
            fp.write(blob)
        written.append(p)
    return written


# --------------------------------------------------- alert-driven capture

#: Alerts whose firing auto-captures a profile on the named node:
#: the rules whose runbook first question is "what is that node's
#: device timeline doing" (docs/OPERATIONS.md). The serving rules
#: (ISSUE 10) ride the same hook — a TTFT blowup or a thrashing KV
#: pool is diagnosed from the afflicted REPLICA's engine timeline
#: (prefill chunks vs decode steps vs admission waits), and the
#: replica is exactly what the alert names.
PROFILE_ALERT_RULES = ("straggler", "train-stall", "slo-p99",
                       "ttft-p99", "kv-pressure", "serve-stall")


class AlertCapture:
    """``AlertEngine`` hook: alert → short profile on the NAMED node.

    Install as ``AlertEngine(rules, capture=AlertCapture(...))``. On a
    matching firing it dials the node from the alert's node key
    (``service/addr:port`` — the cluster-snapshot key shape), runs the
    ``ptype.Profile`` ``capture`` verb with artifact shipping on, and
    writes the artifacts next to the flight-recorder dump
    (``out_dir``, defaulting to the trace plane's dump dir) — the page
    and its device evidence land side by side. Rate-limited per
    (rule, node) to one capture per ``min_interval_s``, mirroring
    ``trace.maybe_dump``; unresolvable node keys (the aggregator's own
    ``local`` row) degrade to a local capture. Capture runs on a
    background thread by default so ``evaluate()`` never blocks on a
    slow node; ``background=False`` is the deterministic test mode.
    """

    def __init__(self, out_dir: str | None = None,
                 duration_s: float = 0.25,
                 rules: tuple = PROFILE_ALERT_RULES,
                 min_interval_s: float = CAPTURE_MIN_INTERVAL_S,
                 timeout_s: float = 20.0,
                 background: bool = True):
        from ptype_tpu import trace as trace_mod

        self.out_dir = (out_dir or trace_mod.dump_dir()
                        or os.path.join(base_dir(), "alerts"))
        self.duration_s = float(duration_s)
        self.rules = tuple(rules)
        self.min_interval_s = float(min_interval_s)
        self.timeout_s = float(timeout_s)
        self.background = background
        #: Completed captures: {"rule", "node", "dir", "files"} — the
        #: post-mortem inventory (and the test surface).
        self.captures: list[dict] = []
        self.errors: list[dict] = []
        self._last: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def __call__(self, alert) -> None:
        if alert.rule not in self.rules:
            return
        key = (alert.rule, alert.node)
        now = time.monotonic()
        with self._lock:
            last = self._last.get(key)
            if last is not None and now - last < self.min_interval_s:
                return
            self._last[key] = now
        if self.background:
            threading.Thread(target=self._capture, args=(alert,),
                             name="alert-profile", daemon=True).start()
        else:
            self._capture(alert)

    @staticmethod
    def _parse_node(node_key: str) -> tuple[str, int] | None:
        """``service/addr:port`` → (addr, port); None when the key has
        no dialable endpoint (the aggregator's ``local`` row)."""
        tail = node_key.rsplit("/", 1)[-1]
        addr, sep, port = tail.rpartition(":")
        if not sep or not addr:
            return None
        try:
            return addr, int(port)
        except ValueError:
            return None

    def _capture(self, alert) -> None:
        dest = os.path.join(
            self.out_dir,
            f"profile-{alert.rule}-"
            f"{alert.node.replace('/', '_').replace(':', '_')}-"
            f"{time.monotonic_ns()}")
        try:
            target = self._parse_node(alert.node)
            if target is None:
                result = capture(duration_s=self.duration_s,
                                 label=f"alert-{alert.rule}",
                                 include_data=True)
            else:
                result = self._remote_capture(*target)
            files = write_artifacts(dest, result)
            meta = {"rule": alert.rule, "node": alert.node,
                    "message": alert.message,
                    "ts": round(time.time(), 3),
                    "duration_s": result.get("duration_s"),
                    "remote_dir": result.get("dir"),
                    "memory": result.get("memory"),
                    "files": [os.path.relpath(p, dest) for p in files]}
            os.makedirs(dest, exist_ok=True)
            with open(os.path.join(dest, "capture.json"), "w",
                      encoding="utf-8") as fp:
                json.dump(meta, fp, indent=1, default=str)
            rec = {"rule": alert.rule, "node": alert.node,
                   "dir": dest, "files": len(files)}
            with self._lock:
                self.captures.append(rec)
            log.warning("alert-triggered profile captured", kv=rec)
        except Exception as e:  # noqa: BLE001 — the watchdog hosting
            # this hook must survive any capture failure (dead node,
            # disk full, profiler already busy on the target).
            with self._lock:
                self.errors.append({"rule": alert.rule,
                                    "node": alert.node,
                                    "error": f"{type(e).__name__}: {e}"})
            log.warning("alert-triggered profile capture failed",
                        kv={"rule": alert.rule, "node": alert.node,
                            "err": repr(e)})

    def _remote_capture(self, addr: str, port: int) -> dict:
        from ptype_tpu import telemetry
        from ptype_tpu.registry import Node

        return telemetry.node_profile(
            Node(addr, port), duration_s=self.duration_s,
            timeout=self.timeout_s, label="alert", dial_timeout=5.0)


# ------------------------------------------------- compiled-cost analysis


def tree_avals(tree):
    """Shape/dtype skeleton of a pytree — what :func:`compiled_cost`
    lowers against (no device data, no transfer)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def compiled_cost(fn, *args, **kwargs) -> dict:
    """FLOPs/bytes XLA reports for ``fn`` compiled on ``args`` (arrays
    or :class:`~jax.ShapeDtypeStruct` avals) — the MFU denominator as
    the compiler sees it, not as a formula hopes.

    Returns ``{"flops", "bytes_accessed"}``. Caveat (XLA's, not
    ours): ``cost_analysis`` counts a while-loop (``lax.scan``) body
    once regardless of trip count, so a program with a rolled loop
    reports a LOWER BOUND — cost lowerings of the transformer step
    unroll the layer scan (trip count 1) to make the count exact.
    Raises :class:`ProfileError` when the backend reports no cost
    analysis at all.
    """
    compiled = fn.lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not ca:
        raise ProfileError(
            "backend reported no cost_analysis for this program")
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def transformer_grads_cost(cfg, batch: int, seq: int,
                           stacked: int | None = None) -> dict:
    """Compiled cost of one fwd+bwd over a ``(batch, seq)`` token
    block for ``cfg`` — the dominant term of every trainer's step.

    Lowers ``value_and_grad(loss_fn)`` with the layer scan fully
    unrolled (``scan_unroll=n_layers`` — identical math, trip count 1,
    so ``cost_analysis`` counts every layer; see :func:`compiled_cost`).
    With ``stacked`` the program is vmapped over that many worker
    shards (the store-DP layout; ``batch`` is then per shard). Returns
    flops/bytes plus ``flops_per_token`` / ``tokens_per_step``.
    """
    import jax.numpy as jnp

    from ptype_tpu.models import transformer as tfm

    cost_cfg = dataclasses.replace(
        cfg, scan_unroll=max(1, int(cfg.n_layers)))
    params_avals = jax.eval_shape(
        lambda r: tfm.init_params(r, cfg), jax.random.PRNGKey(0))

    def local_grads(p, b):
        return jax.value_and_grad(tfm.loss_fn)(p, b, cost_cfg)

    shape = (batch, seq) if stacked is None else (stacked, batch, seq)
    batch_avals = {"tokens": jax.ShapeDtypeStruct(shape, jnp.int32),
                   "targets": jax.ShapeDtypeStruct(shape, jnp.int32)}
    fn = (jax.jit(local_grads) if stacked is None  # ptlint: disable=PT019 -- one-shot cost probe: the jit is lowered for cost_analysis only, never dispatched hot
          else jax.jit(jax.vmap(local_grads, in_axes=(None, 0))))
    cost = compiled_cost(fn, params_avals, batch_avals)
    tokens = batch * seq * (stacked or 1)
    cost["tokens_per_step"] = tokens
    cost["flops_per_token"] = cost["flops"] / tokens
    return cost


def measure_compiled_cost(preset: str = "optimus-125m", batch: int = 8,
                          seq: int = 128) -> dict:
    """Compiled-vs-analytic FLOPs on one config — the bench probe
    behind ``compiled_flops_per_token`` and the ISSUE 8 acceptance
    check (``mfu_compiled`` within 10% of analytic MFU on the 125M
    CPU-mesh config, gap REPORTED either way). MFU shares the
    wall-clock and peak factors, so the MFU gap IS the FLOPs gap."""
    from ptype_tpu.models import transformer as tfm

    cfg = tfm.preset(preset)
    t0 = time.perf_counter()
    cost = transformer_grads_cost(cfg, batch, seq)
    analytic = tfm.flops_per_token(cfg, seq)
    compiled = cost["flops_per_token"]
    return {
        "preset": preset, "batch": batch, "seq": seq,
        "compiled_flops_per_token": round(compiled, 1),
        "analytic_flops_per_token": round(analytic, 1),
        "mfu_gap_pct": round(100.0 * (compiled - analytic) / analytic,
                             2),
        "bytes_per_token": round(
            cost["bytes_accessed"] / cost["tokens_per_step"], 1),
        "compile_s": round(time.perf_counter() - t0, 2),
    }


# --------------------------------------------------- host-side summaries


def summarize(profile_dir: str, top: int = 12) -> dict:
    """Host-side artifact summary — stdlib-only (gzip+json over the
    ``*.trace.json.gz`` Chrome trace jax writes beside the
    ``.xplane.pb``), so it works on CPU test runs with no TensorBoard.

    Returns ``{"dir", "files", "events", "top_ops":
    [{"name", "total_us", "count"}, ...]}`` — top ops by total
    duration. Directories with only an ``.xplane.pb`` (some backends)
    still get the file inventory."""
    files = artifact_files(profile_dir)
    totals: dict[str, list] = {}
    n_events = 0
    for f in files:
        if not f["path"].endswith(".trace.json.gz"):
            continue
        try:
            with gzip.open(os.path.join(profile_dir, f["path"]),
                           "rt", encoding="utf-8") as fp:
                doc = json.load(fp)
        except (OSError, ValueError):
            continue
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") != "X":
                continue
            n_events += 1
            name = str(ev.get("name", "?"))
            acc = totals.setdefault(name, [0.0, 0])
            acc[0] += float(ev.get("dur", 0.0))
            acc[1] += 1
    top_ops = [{"name": name, "total_us": round(us, 1), "count": n}
               for name, (us, n) in sorted(
                   totals.items(), key=lambda kv: -kv[1][0])[:top]]
    return {"dir": profile_dir, "files": files, "events": n_events,
            "top_ops": top_ops}


def render_hbm_table(memory: dict) -> str:
    """One-line-per-device HBM table from a :func:`memory_snapshot`
    dict (the ``obs profile`` CLI's printer feeds this to stdout)."""
    lines = []
    for dev in memory.get("devices", ()):
        stats = dev.get("stats", {})
        if stats:
            used = stats.get("bytes_in_use", 0) / 2**20
            peak = stats.get("peak_bytes_in_use", 0) / 2**20
            limit = stats.get("bytes_limit", 0) / 2**20
            lines.append(
                f"  dev{dev['id']} {dev.get('kind') or dev['platform']}:"
                f" {used:.1f} MiB in use (peak {peak:.1f}"
                + (f" / limit {limit:.0f})" if limit else ")"))
        else:
            lines.append(
                f"  dev{dev['id']} {dev.get('kind') or dev['platform']}:"
                f" no allocator stats (host RSS below)")
    host = memory.get("host", {})
    if host.get("rss_bytes"):
        lines.append(f"  host rss: {host['rss_bytes'] / 2**20:.1f} MiB")
    return "\n".join(lines)


# ----------------------------------------------------------- bench probe


def measure_profile_overhead(steps: int = 12, preset: str = "tiny",
                             batch: int = 8, seq: int = 32) -> dict:
    """Capture-disabled cost of the profiling plane on the host-mesh
    store-DP loop — the bench.py ``profile_overhead_pct`` probe and
    the ISSUE 8 acceptance bar (<1%).

    What "armed but not capturing" adds to a step: nothing in the step
    path checks the profiler (the endpoint is pull-only), so the whole
    idle cost is the goodput ledger's ``mfu_compiled`` arithmetic in
    its step-close — costed DIRECTLY (observe("train.step") with
    compiled flops set, microseconds against a step of tens of
    milliseconds; same method as ``telemetry.measure_trace_overhead``
    — a wall-clock A/B on a shared host reports scheduler noise, not
    this signal). The interleaved armed/bare wall clocks ride along
    for transparency, and one short LIVE capture is costed separately
    (``capture_step_ms`` — the price of actually profiling, which is
    allowed to be visible)."""
    from ptype_tpu import metrics as metrics_mod
    from ptype_tpu.health import goodput as goodput_mod
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.topology import DATA_AXIS
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer

    mesh = build_mesh({DATA_AXIS: jax.device_count()})
    cfg = tfm.preset(preset)
    trainer = StoreDPTrainer(cfg, TensorStore(mesh))
    stream = synthetic_batches(cfg.vocab_size, batch, seq)
    trainer.step(next(stream))  # compile outside every measurement

    cost = trainer.compiled_cost()
    ledger = goodput_mod.GoodputLedger(
        registry=metrics_mod.MetricsRegistry(),
        tokens_per_step=batch * seq,
        flops_per_token=tfm.flops_per_token(cfg, seq))
    ledger.set_compiled_flops(cost["flops"])

    # Interleaved armed/bare arms, per-arm MIN (robust to load spikes).
    t_on: list[float] = []
    t_off: list[float] = []
    for i in range(2 * steps):
        armed = bool(i % 2)
        if armed:
            ledger.install()
        else:
            ledger.uninstall()
        t0 = time.perf_counter()
        trainer.step(next(stream))
        (t_on if armed else t_off).append(time.perf_counter() - t0)
    ledger.uninstall()
    step_s = min(t_off)

    # The idle cost, costed directly: one ledger step-close (with the
    # mfu_compiled arithmetic live) per step.
    probe = goodput_mod.GoodputLedger(
        registry=metrics_mod.MetricsRegistry(),
        tokens_per_step=batch * seq,
        flops_per_token=tfm.flops_per_token(cfg, seq))
    probe.set_compiled_flops(cost["flops"])
    n = 5_000
    t0 = time.perf_counter()
    for _ in range(n):
        probe.observe("train.step", step_s)
    close_s = (time.perf_counter() - t0) / n

    # The price of actually capturing (informational, not the bar).
    start(label="bench-profile-overhead")
    t0 = time.perf_counter()
    for _ in range(2):
        trainer.step(next(stream))
    capture_step_s = (time.perf_counter() - t0) / 2
    captured = stop()

    mfu_gap = None
    rec = probe.records()
    if rec and "mfu_gap_pct" in rec[-1]:
        mfu_gap = rec[-1]["mfu_gap_pct"]
    return {
        "bare_step_ms": round(step_s * 1e3, 2),
        "armed_step_ms": round(min(t_on) * 1e3, 2),
        "capture_step_ms": round(capture_step_s * 1e3, 2),
        "ledger_close_us": round(close_s * 1e6, 2),
        "profile_overhead_pct": round(100.0 * close_s / step_s, 4),
        "capture_artifact_files": len(captured["files"]),
        "compiled_flops_per_token": round(
            cost["flops"] / cost["tokens_per_step"], 1),
        "mfu_gap_pct": mfu_gap,
        "steps": steps,
    }
