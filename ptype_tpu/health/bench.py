"""Health-plane cost probe: goodput numbers + measured overhead.

Backs the ``goodput_pct`` / ``step_breakdown`` /
``sampler_overhead_pct`` fields in bench.py's tail record: run the
host-mesh store-DP step loop with the ledger installed on the real
annotate seam and the sampler ticking, then cost the machinery
DIRECTLY (same method as ``telemetry.measure_trace_overhead`` — the
per-call cost measures in microseconds against a step measured in
tens of milliseconds, so a wall-clock A/B reports scheduler noise,
not the signal):

- sampler: ``tick cost / cadence`` — the sampler thread spends one
  tick per cadence window regardless of step rate;
- ledger: ``observe cost × regions/step / step time`` — the observer
  fires once per annotate region.

Acceptance bar (ISSUE 5): sampler overhead < 1% of step time.
"""

from __future__ import annotations

import time


def measure_health_overhead(steps: int = 12, preset: str = "tiny",
                            batch: int = 8, seq: int = 32,
                            cadence_s: float = 0.05) -> dict:
    import jax

    from ptype_tpu import metrics as metrics_mod
    from ptype_tpu.health import goodput as goodput_mod
    from ptype_tpu.health import series as series_mod
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer

    n_chips = jax.device_count()
    mesh = build_mesh({"data": n_chips})
    cfg = tfm.preset(preset)
    trainer = StoreDPTrainer(cfg, TensorStore(mesh))
    stream = synthetic_batches(cfg.vocab_size, batch, seq)
    trainer.step(next(stream))  # compile outside the measurement

    ledger = goodput_mod.install(
        tokens_per_step=batch * seq,
        flops_per_token=tfm.flops_per_token(cfg, seq),
        n_chips=n_chips)
    sampler = series_mod.Sampler(cadence_s=cadence_s).start()
    try:
        t_loop0 = time.perf_counter()
        for _ in range(steps):
            trainer.step(next(stream))
        loop_s = time.perf_counter() - t_loop0
        summary = ledger.summary()

        # Regions per step, from the ledger's own breakdown inputs:
        # every component region + the step region itself fired once
        # through the observer.
        step_s = max(loop_s / steps, 1e-9)

        # Direct sampler tick cost over the live registry.
        n_ticks = 200
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            sampler.sample_once()
        tick_s = (time.perf_counter() - t0) / n_ticks

        # Direct observer cost (a throwaway ledger so the probe does
        # not pollute the measured records).
        probe = goodput_mod.GoodputLedger(
            registry=metrics_mod.MetricsRegistry())
        n_obs = 20_000
        t0 = time.perf_counter()
        for _ in range(n_obs):
            probe.observe("store.push_tree/probe", 0.0)
        obs_s = (time.perf_counter() - t0) / n_obs
    finally:
        sampler.close()
        goodput_mod.uninstall()

    regions_per_step = 3.0  # train.step + train.data + store.push_tree
    return {
        "goodput_pct": summary["goodput_pct"],
        "step_breakdown": summary["step_breakdown"],
        "tokens_per_sec": summary.get("tokens_per_sec"),
        "mfu": summary.get("mfu"),
        "steps": steps,
        "step_ms": round(step_s * 1e3, 2),
        "sampler_tick_us": round(tick_s * 1e6, 2),
        "sampler_cadence_s": cadence_s,
        "sampler_overhead_pct": round(100.0 * tick_s / cadence_s, 4),
        "ledger_observe_us": round(obs_s * 1e6, 3),
        "ledger_overhead_pct": round(
            100.0 * obs_s * regions_per_step / step_s, 5),
    }
