"""Health-plane cost probe: goodput numbers + measured overhead.

Backs the ``goodput_pct`` / ``step_breakdown`` /
``sampler_overhead_pct`` fields in bench.py's tail record: run the
host-mesh store-DP step loop with the ledger installed on the real
annotate seam and the sampler ticking, then cost the machinery
DIRECTLY (same method as ``telemetry.measure_trace_overhead`` — the
per-call cost measures in microseconds against a step measured in
tens of milliseconds, so a wall-clock A/B reports scheduler noise,
not the signal):

- sampler: ``tick cost / cadence`` — the sampler thread spends one
  tick per cadence window regardless of step rate;
- ledger: ``observe cost × regions/step / step time`` — the observer
  fires once per annotate region.

Acceptance bar (ISSUE 5): sampler overhead < 1% of step time.
"""

from __future__ import annotations

import time


def measure_health_overhead(steps: int = 12, preset: str = "tiny",
                            batch: int = 8, seq: int = 32,
                            cadence_s: float = 0.05) -> dict:
    import jax

    from ptype_tpu import metrics as metrics_mod
    from ptype_tpu.health import goodput as goodput_mod
    from ptype_tpu.health import series as series_mod
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.topology import DATA_AXIS
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer

    n_chips = jax.device_count()
    mesh = build_mesh({DATA_AXIS: n_chips})
    cfg = tfm.preset(preset)
    trainer = StoreDPTrainer(cfg, TensorStore(mesh))
    stream = synthetic_batches(cfg.vocab_size, batch, seq)
    trainer.step(next(stream))  # compile outside the measurement

    ledger = goodput_mod.install(
        tokens_per_step=batch * seq,
        flops_per_token=tfm.flops_per_token(cfg, seq),
        n_chips=n_chips)
    sampler = series_mod.Sampler(cadence_s=cadence_s).start()
    try:
        t_loop0 = time.perf_counter()
        for _ in range(steps):
            trainer.step(next(stream))
        loop_s = time.perf_counter() - t_loop0
        summary = ledger.summary()

        # Regions per step, from the ledger's own breakdown inputs:
        # every component region + the step region itself fired once
        # through the observer.
        step_s = max(loop_s / steps, 1e-9)

        # Direct sampler tick cost over the live registry.
        n_ticks = 200
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            sampler.sample_once()
        tick_s = (time.perf_counter() - t0) / n_ticks

        # Direct observer cost (a throwaway ledger so the probe does
        # not pollute the measured records).
        probe = goodput_mod.GoodputLedger(
            registry=metrics_mod.MetricsRegistry())
        n_obs = 20_000
        t0 = time.perf_counter()
        for _ in range(n_obs):
            probe.observe("store.push_tree/probe", 0.0)
        obs_s = (time.perf_counter() - t0) / n_obs
    finally:
        sampler.close()
        goodput_mod.uninstall()

    regions_per_step = 3.0  # train.step + train.data + store.push_tree
    return {
        "goodput_pct": summary["goodput_pct"],
        "step_breakdown": summary["step_breakdown"],
        "tokens_per_sec": summary.get("tokens_per_sec"),
        "mfu": summary.get("mfu"),
        "steps": steps,
        "step_ms": round(step_s * 1e3, 2),
        "sampler_tick_us": round(tick_s * 1e6, 2),
        "sampler_cadence_s": cadence_s,
        "sampler_overhead_pct": round(100.0 * tick_s / cadence_s, 4),
        "ledger_observe_us": round(obs_s * 1e6, 3),
        "ledger_overhead_pct": round(
            100.0 * obs_s * regions_per_step / step_s, 5),
    }


def _lockcheck_probe_pass(ticks: int, families: int) -> float:
    """One pass of the lock-heavy control-plane probe: a fresh
    registry + sampler + series store. Every lock inside them —
    registry walk, histogram rings, series rings, store map, sampler
    tick — is created through the lockcheck seam at construction
    (metrics.py / health/series.py route ALL of them), so the
    armed/disarmed variants differ exactly by the wrapper under
    test. Returns wall seconds for the sample ticks alone (the
    mutation load between ticks is the workload, not the machinery)."""
    from ptype_tpu import metrics as metrics_mod
    from ptype_tpu.health import series as series_mod

    reg = metrics_mod.MetricsRegistry()
    counters = [reg.counter(f"probe.c{i}") for i in range(families)]
    gauges = [reg.gauge(f"probe.g{i}") for i in range(families)]
    sampler = series_mod.Sampler(reg, store=series_mod.SeriesStore(),
                                 memory=False)
    spent = 0.0
    for t in range(ticks):
        for i, c in enumerate(counters):
            c.add(1)
            gauges[i].set(float(t + i))
        t0 = time.perf_counter()
        sampler.sample_once(now=float(t), now_mono=float(t))
        spent += time.perf_counter() - t0
    return spent


def measure_lockcheck_overhead(ticks: int = 1500,
                               families: int = 16,
                               repeats: int = 4,
                               cadence_s: float = 0.05) -> dict:
    """Backs ``lockcheck_overhead_pct`` in bench.py's tail record
    (ISSUE 14 acceptance: <1% with the watchdog disarmed, <5%
    armed).

    Same method as ``sampler_overhead_pct`` above: cost the machinery
    DIRECTLY and charge it against its operating point. The armed
    wrapper's cost lands once per LOCK ACQUIRE, and the health
    plane's acquire rate is one sampler tick's worth per cadence
    window — so the armed overhead is (armed tick − disarmed tick) /
    cadence. A raw wall A/B of a lock-only microloop would report
    the wrapper at ~100% duty cycle, a workload no armed tier runs.
    Best-of-``repeats`` per side so one scheduler hiccup can't fake
    a regression; ``lockcheck_wrap_us_per_acquire`` carries the raw
    per-acquire price for the microloop reader. Disarmed cost: the
    seam's factory returns a PLAIN ``threading.Lock`` when disarmed
    (zero per-acquire residue by construction — the only seam cost
    is one factory call per lock CREATED); the spin A/B demonstrates
    that empirically — a nonzero reading bounds scheduler noise, not
    wrapper cost.
    """
    import threading

    from ptype_tpu import lockcheck

    was = lockcheck.active()
    lockcheck.disable()
    try:
        _lockcheck_probe_pass(ticks // 4, families)  # warm the path
        t_off = min(_lockcheck_probe_pass(ticks, families)
                    for _ in range(repeats))
        # Disarmed residue at the primitive: seam-made vs direct lock.
        n = 400_000
        seam_lock = lockcheck.lock("bench.probe")
        raw_lock = threading.Lock()

        def spin(lk):
            t0 = time.perf_counter()
            for _ in range(n):
                with lk:
                    pass
            return time.perf_counter() - t0

        spin(raw_lock)   # warm BOTH: the first pass over either
        spin(seam_lock)  # object pays cache/allocator noise
        t_raw = min(spin(raw_lock) for _ in range(repeats + 2))
        t_seam = min(spin(seam_lock) for _ in range(repeats + 2))
        disabled_pct = 100.0 * (t_seam - t_raw) / max(t_raw, 1e-9)

        lockcheck.enable()
        _lockcheck_probe_pass(ticks // 4, families)
        t_on = min(_lockcheck_probe_pass(ticks, families)
                   for _ in range(repeats))
        wd = lockcheck.active()
        report = wd.report() if wd is not None else {}
    finally:
        lockcheck.disable()
        if was is not None:
            # Hand back the caller's armed watchdog (graph intact).
            import ptype_tpu.lockcheck as _lc
            _lc._watchdog = was
    tick_off = t_off / ticks
    tick_on = t_on / ticks
    # Acquires per armed tick, from the watchdog's own tally over
    # the armed passes (warm + repeats).
    armed_ticks = (ticks // 4) + repeats * ticks
    per_tick = report.get("acquires", 0) / max(1, armed_ticks)
    wrap_us = (1e6 * (tick_on - tick_off) / per_tick
               if per_tick else 0.0)
    return {
        "lockcheck_overhead_pct": round(
            100.0 * max(0.0, tick_on - tick_off) / cadence_s, 3),
        "lockcheck_disabled_overhead_pct": round(max(disabled_pct,
                                                     0.0), 3),
        "lockcheck_cadence_s": cadence_s,
        "lockcheck_tick_us": round(tick_off * 1e6, 2),
        "lockcheck_tick_armed_us": round(tick_on * 1e6, 2),
        "lockcheck_acquires_per_tick": round(per_tick, 1),
        "lockcheck_wrap_us_per_acquire": round(max(wrap_us, 0.0), 3),
        "lockcheck_cycles": len(report.get("cycles", [])),
    }


def measure_jitwatch_overhead(iters: int = 1500,
                              repeats: int = 5) -> dict:
    """Backs ``jitwatch_overhead_pct`` in bench.py's tail record
    (ISSUE 15 acceptance: armed < 5% vs disarmed).

    The armed watchdog costs per STEP, not per compile: the compile
    hook only fires on a cache miss (zero in steady state), so the
    recurring price is ONE hot-region transfer-guard entry around
    each dispatch. Same method as ``serving_ledger_overhead_pct``:
    price the machinery directly (a bare-dispatch A/B microloop —
    the region costs single-digit microseconds), then charge it
    against the step it actually wraps — an engine-shaped step with
    its one host sync per iteration, measured in the same process.
    A wall A/B of the bare microloop would report the guard at 100%
    duty cycle, a workload no armed engine runs (its step IS the
    model forward). Best-of-``repeats`` per side;
    ``jitwatch_region_us`` carries the raw per-region price, and the
    probe asserts its own steady-state recompiles are zero."""
    import time

    import jax
    import jax.numpy as jnp

    from ptype_tpu import jitwatch

    # The region-cost microloop: bare async dispatch vs dispatch
    # under the guard — the difference IS the per-step armed price.
    f = jax.jit(lambda v: v * 2.0 + 1.0)
    x = jnp.ones((256,), jnp.float32)
    f(x).block_until_ready()  # compile outside the measurement

    # The engine-shaped step the guard wraps in production: a real
    # forward-sized program with the one-per-step host sync the
    # engine pays (np.array(nxt) / the loss readback).
    import numpy as np

    w = jnp.ones((256, 256), jnp.float32) * 0.01
    step = jax.jit(lambda v, m: jnp.tanh(v @ m) @ m)
    sx = jnp.ones((64, 256), jnp.float32)
    np.asarray(step(sx, w))  # compile + settle

    def drive(armed: bool) -> float:
        t0 = time.perf_counter()
        if armed:
            for _ in range(iters):
                with jitwatch.hot_region("bench.step"):
                    f(x)
        else:
            for _ in range(iters):
                f(x)
        f(x).block_until_ready()
        return time.perf_counter() - t0

    def drive_step(n: int = 60) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            np.asarray(step(sx, w))
        return (time.perf_counter() - t0) / n

    was = jitwatch.active()
    jitwatch.disable()
    try:
        drive(False)  # warm the loop path
        t_off = min(drive(False) for _ in range(repeats))
        step_s = min(drive_step() for _ in range(3))
        jw = jitwatch.enable()
        jw.mark_steady()
        drive(True)
        t_on = min(drive(True) for _ in range(repeats))
        steady = jw.recompiles_since_steady()
    finally:
        jitwatch.disable()
        if was is not None:
            # Re-ARM (fresh books) rather than reinstalling the old
            # watch object: disable() tore down the compile-log
            # filters and jax_log_compiles, so a reinstalled watch
            # would report armed while counting nothing.
            jitwatch.enable(was.storm_threshold, was.transfer_level)
    region_s = max(0.0, (t_on - t_off) / iters)
    return {
        "jitwatch_overhead_pct": round(
            100.0 * region_s / max(step_s, 1e-12), 3),
        "jitwatch_region_us": round(region_s * 1e6, 3),
        "jitwatch_dispatch_us": round(t_off / iters * 1e6, 2),
        "jitwatch_step_ms": round(step_s * 1e3, 3),
        "jitwatch_steady_recompiles": sum(steady.values()),
    }
