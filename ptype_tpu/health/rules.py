"""Declarative alert rules over cluster health series.

The watchdog half of the health plane: :class:`Rule`\\ s evaluate a
cluster telemetry snapshot (per-node series + metrics, the shape
``telemetry.cluster_snapshot`` returns once the sampler is armed) and
fire typed :class:`Alert`\\ s. The :class:`AlertEngine` runs a rule
set, de-duplicates within a cooldown, lands every alert in the
structured log (``logs.KVLogger``), bumps ``health.alerts`` counters,
and triggers the flight recorder's ``maybe_dump`` — the moment an
alert fires is exactly when a post-mortem wants the span ring.

Rule catalogue (see docs/OBSERVABILITY.md for the full table and
docs/OPERATIONS.md for the per-alert runbook):

====================  ====================================================
rule                  fires when
====================  ====================================================
``slo-burn-rate``     gateway shed fraction burns the error budget at
                      ≥ ``burn_threshold``× (multi-window SRE math)
``slo-p99``           gateway latency p99 series exceeds the SLO target
``train-stall``       no step-counter progress within N× median step time
``straggler``         one node's step/collective mean exceeds
                      median + k·MAD across the fleet (names the node)
``loss``              training loss goes non-finite (page) or spikes
                      over ``spike_factor``× its recent median (warn)
``coord-flap``        the coordination term bumps more than allowed in a
                      window (promotion churn — dueling standbys)
``memory-growth``     a memory watermark grows past ``growth_frac``
                      within the window above a floor
``mfu-divergence``    compiled-cost MFU (``goodput.mfu_compiled``, from
                      XLA cost_analysis — health/profiling.py) disagrees
                      with the analytic MFU by more than ``gap_frac``
``ttft-p99``          a serving replica's time-to-first-token p99
                      (``serve.ttft_ms.p99``, the serving ledger's
                      histogram) exceeds the SLO target
``kv-pressure``       a replica's paged-KV admission headroom is pinned
                      low while the pool actively evicts (the
                      eviction-rate floor keeps a small-but-idle pool
                      from paging) — names the replica
``prefix-hit-collapse``  a replica's prefix-cache hit rate collapsed
                      from a healthy level (affinity routing broke, or
                      eviction pressure is churning the shared prefix)
``serve-stall``       a serving replica's engine iterations stopped
                      while its admission queue is non-empty — the
                      per-replica wedged-engine page
====================  ====================================================

Every rule takes the evaluation time from the :class:`ClusterView`
(injectable) and reads only series/metrics — deterministic unit tests
feed synthetic snapshots with fabricated timestamps.
"""

from __future__ import annotations

import collections
import math
import statistics
import time

from ptype_tpu import lockcheck
from dataclasses import dataclass, field

from ptype_tpu import logs, trace
from ptype_tpu import metrics as metrics_mod
from ptype_tpu.health.goodput import (_dedup_aliases, detect_stragglers,
                                      node_series_means, node_span_means)

log = logs.get_logger("health")


@dataclass
class Alert:
    """One typed firing: which rule, which node, why."""

    rule: str
    severity: str  # "page" | "warn"
    node: str
    message: str
    value: float | None = None
    threshold: float | None = None
    ts: float = 0.0
    labels: dict = field(default_factory=dict)

    def key(self) -> tuple[str, str]:
        return (self.rule, self.node)

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "node": self.node, "message": self.message,
             "ts": round(self.ts, 3)}
        if self.value is not None:
            d["value"] = round(self.value, 4)
        if self.threshold is not None:
            d["threshold"] = round(self.threshold, 4)
        if self.labels:
            d["labels"] = self.labels
        return d


class ClusterView:
    """Read helpers over one cluster snapshot — what rules evaluate."""

    def __init__(self, snapshot: dict, now: float | None = None):
        self.snapshot = snapshot
        #: Evaluation instant; defaults to the snapshot's own stamp so
        #: replayed/synthetic snapshots evaluate at their own time.
        self.now = (now if now is not None
                    else snapshot.get("ts") or time.time())
        #: Alias-deduped: several registry service names can alias one
        #: process; every rule must see it once or (rule, node-key)
        #: cooldowns can't stop the duplicate alert.
        self.nodes: dict = dict(_dedup_aliases(snapshot))

    def node_keys(self) -> list[str]:
        return sorted(self.nodes)

    def series(self, node: str, name: str) -> list:
        return (self.nodes.get(node, {}).get("series", {})
                .get(name) or [])

    def last(self, node: str, name: str):
        pts = self.series(node, name)
        return pts[-1] if pts else None

    def gauge(self, node: str, name: str):
        return (self.nodes.get(node, {}).get("metrics", {})
                .get("gauges", {}).get(name))

    def each_series(self, name: str) -> dict[str, list]:
        out = {}
        for key in self.nodes:
            pts = self.series(key, name)
            if pts:
                out[key] = pts
        return out


def counter_delta(points: list, window_s: float, now: float) -> float:
    """Increase of a cumulative-counter series over the window: last
    value minus the value at (or just before) the window start.
    Clamped at 0 — a process restart resets the counter, and a reset
    must read as 'no traffic', not negative traffic."""
    if not points:
        return 0.0
    base = None
    for t, v in points:
        if t <= now - window_s:
            base = v
        else:
            break
    if base is None:
        # Whole series inside the window: the first point is the base
        # (its increase happened at/after the window opened).
        base = points[0][1]
    return max(0.0, points[-1][1] - base)


class Rule:
    """Base: a named, severity-tagged predicate over a ClusterView."""

    name = "rule"
    severity = "warn"

    def evaluate(self, view: ClusterView) -> list[Alert]:
        raise NotImplementedError

    def _alert(self, node: str, message: str, *, value=None,
               threshold=None, severity: str | None = None,
               **labels) -> Alert:
        return Alert(rule=self.name,
                     severity=severity or self.severity, node=node,
                     message=message, value=value, threshold=threshold,
                     labels=labels)


class BurnRateRule(Rule):
    """Gateway SLO error-budget burn from the shed/request counter
    series. ``budget`` is the allowed bad fraction (0.01 = 99% of
    requests answered); the burn rate is ``shed_fraction / budget`` —
    1.0 spends the budget exactly at period's end, 14.4 (the classic
    fast-burn page) exhausts a 30-day budget in ~2 days."""

    name = "slo-burn-rate"
    severity = "page"

    def __init__(self, service: str = "llm", budget: float = 0.01,
                 burn_threshold: float = 14.4, window_s: float = 60.0,
                 min_requests: float = 10.0):
        self.service = service
        self.budget = float(budget)
        self.burn_threshold = float(burn_threshold)
        self.window_s = float(window_s)
        self.min_requests = float(min_requests)

    def burn_rate(self, shed_pts: list, req_pts: list,
                  now: float) -> float | None:
        """The deterministic math: windowed shed/requests fraction over
        the budget; None below the traffic floor (an empty window must
        not divide its way into a page)."""
        req = counter_delta(req_pts, self.window_s, now)
        if req < self.min_requests or self.budget <= 0:
            return None
        shed = counter_delta(shed_pts, self.window_s, now)
        return (shed / req) / self.budget

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        p = f"gateway.{self.service}"
        for node in view.node_keys():
            burn = self.burn_rate(view.series(node, f"{p}.shed"),
                                  view.series(node, f"{p}.requests"),
                                  view.now)
            if burn is not None and burn >= self.burn_threshold:
                out.append(self._alert(
                    node,
                    f"gateway {self.service} shed burn rate "
                    f"{burn:.1f}x the error budget "
                    f"(window {self.window_s:.0f}s)",
                    value=burn, threshold=self.burn_threshold,
                    service=self.service))
        return out


class P99Rule(Rule):
    """Gateway latency p99 (histogram series the sampler stamps as
    ``gateway.<svc>.latency_ms.p99``) over the SLO target."""

    name = "slo-p99"
    severity = "warn"

    def __init__(self, service: str = "llm",
                 slo_p99_ms: float = 1000.0):
        self.service = service
        self.slo_p99_ms = float(slo_p99_ms)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        name = f"gateway.{self.service}.latency_ms.p99"
        for node in view.node_keys():
            last = view.last(node, name)
            if last is not None and last[1] > self.slo_p99_ms:
                out.append(self._alert(
                    node,
                    f"gateway {self.service} p99 {last[1]:.0f}ms over "
                    f"SLO {self.slo_p99_ms:.0f}ms",
                    value=last[1], threshold=self.slo_p99_ms,
                    service=self.service))
        return out


class StallRule(Rule):
    """Training stall: the step counter stopped advancing for longer
    than ``factor``× the node's median step time (with an absolute
    floor — a 1 ms CPU-smoke step must not page on a 10 ms pause)."""

    name = "train-stall"
    severity = "page"

    def __init__(self, factor: float = 5.0, min_steps: int = 3,
                 min_gap_s: float = 5.0,
                 steps_series: str = "goodput.steps",
                 step_ms_series: str = "goodput.step_ms"):
        self.factor = float(factor)
        self.min_steps = int(min_steps)
        self.min_gap_s = float(min_gap_s)
        self.steps_series = steps_series
        self.step_ms_series = step_ms_series

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = view.series(node, self.steps_series)
            if not pts or pts[-1][1] < self.min_steps:
                continue
            # The sampler appends only on change: the last point IS
            # the last observed progress.
            last_progress_t = pts[-1][0]
            step_vals = [v for _, v in
                         view.series(node, self.step_ms_series)]
            med_s = (statistics.median(step_vals) / 1e3
                     if step_vals else 0.0)
            threshold = max(self.factor * med_s, self.min_gap_s)
            gap = view.now - last_progress_t
            if gap > threshold:
                out.append(self._alert(
                    node,
                    f"no step progress for {gap:.1f}s "
                    f"(median step {med_s * 1e3:.0f}ms, "
                    f"threshold {threshold:.1f}s)",
                    value=gap, threshold=threshold))
        return out


class StragglerRule(Rule):
    """Cross-node straggler: one node's recent mean of
    ``metric`` (default per-step wall ms) exceeds the fleet's
    median + k·MAD (:func:`~ptype_tpu.health.goodput
    .detect_stragglers`). Falls back to stitched-span durations
    (``span_prefix``) for fleets running the trace plane without the
    sampler."""

    name = "straggler"
    severity = "warn"

    def __init__(self, metric: str = "goodput.step_ms", k: float = 4.0,
                 min_nodes: int = 3, min_excess_ms: float = 50.0,
                 min_ratio: float = 1.5,
                 window_s: float | None = 300.0,
                 span_prefix: str = "store.push_tree"):
        # window_s bounded by default: change-driven sampling retains
        # points indefinitely, and one historic outlier (a warm-up
        # step, an incident hours ago) must not mark a currently-
        # healthy node as a straggler forever.
        self.metric = metric
        self.k = float(k)
        self.min_nodes = int(min_nodes)
        self.min_excess_ms = float(min_excess_ms)
        self.min_ratio = float(min_ratio)
        self.window_s = window_s
        self.span_prefix = span_prefix

    def evaluate(self, view: ClusterView) -> list[Alert]:
        per_node = node_series_means(view.snapshot, self.metric,
                                     self.window_s, view.now)
        source = self.metric
        if len(per_node) < self.min_nodes:
            per_node = node_span_means(view.snapshot, self.span_prefix,
                                       self.window_s, view.now)
            source = f"span:{self.span_prefix}"
        hits = detect_stragglers(per_node, k=self.k,
                                 min_nodes=self.min_nodes,
                                 min_excess=self.min_excess_ms,
                                 min_ratio=self.min_ratio)
        return [self._alert(
            h["node"],
            f"straggler: {source} ~{h['value']:.1f}ms vs cluster "
            f"median {h['median']:.1f}ms "
            f"(threshold {h['threshold']:.1f}ms)",
            value=h["value"], threshold=h["threshold"],
            median=h["median"], metric=source) for h in hits]


class LossRule(Rule):
    """Training loss health from the ``train.loss`` gauge series:
    non-finite pages immediately; a spike over ``spike_factor``× the
    recent median warns."""

    name = "loss"
    severity = "warn"

    def __init__(self, metric: str = "train.loss",
                 spike_factor: float = 3.0, min_points: int = 4):
        self.metric = metric
        self.spike_factor = float(spike_factor)
        self.min_points = int(min_points)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node, pts in view.each_series(self.metric).items():
            last = pts[-1][1]
            if not math.isfinite(last):
                out.append(self._alert(
                    node, f"training loss is {last} — run is diverged",
                    severity="page"))
                continue
            if len(pts) < self.min_points:
                continue
            prev = [v for _, v in pts[:-1] if math.isfinite(v)]
            if not prev:
                continue
            med = statistics.median(prev)
            if med > 0 and last > self.spike_factor * med:
                out.append(self._alert(
                    node,
                    f"loss spike {last:.3f} vs recent median "
                    f"{med:.3f} ({self.spike_factor:.1f}x threshold)",
                    value=last, threshold=self.spike_factor * med))
        return out


class CoordFlapRule(Rule):
    """Coordinator flap: the ``coord.term`` gauge bumped more than
    ``max_increases`` times within the window — promotion churn
    (dueling standbys, a lease TTL racing its keepalive)."""

    name = "coord-flap"
    severity = "page"

    def __init__(self, metric: str = "coord.term",
                 max_increases: int = 1, window_s: float = 300.0):
        self.metric = metric
        self.max_increases = int(max_increases)
        self.window_s = float(window_s)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node, pts in view.each_series(self.metric).items():
            vals = [v for t, v in pts if t >= view.now - self.window_s]
            # The point just before the window anchors the base term.
            older = [v for t, v in pts if t < view.now - self.window_s]
            if older:
                vals = [older[-1]] + vals
            bumps = sum(1 for a, b in zip(vals, vals[1:]) if b > a)
            if bumps > self.max_increases:
                out.append(self._alert(
                    node,
                    f"coordination term bumped {bumps}x in "
                    f"{self.window_s:.0f}s — promotion flapping",
                    value=float(bumps),
                    threshold=float(self.max_increases)))
        return out


class MemoryGrowthRule(Rule):
    """Sustained memory growth: a watermark series grew by more than
    ``growth_frac`` across the window while above ``min_bytes`` —
    the leak signature, not a transient peak. The window is bounded
    by default: change-driven sampling retains flat points
    indefinitely, and hours of legitimate slow growth (compilation
    caches) compared against an ancient baseline is not a leak."""

    name = "memory-growth"
    severity = "warn"

    def __init__(self,
                 metric_names: tuple = ("mem.device_bytes_in_use",
                                        "mem.rss_bytes"),
                 growth_frac: float = 0.5,
                 min_bytes: float = 256 * 1024 * 1024,
                 window_s: float | None = 600.0):
        self.metric_names = tuple(metric_names)
        self.growth_frac = float(growth_frac)
        self.min_bytes = float(min_bytes)
        self.window_s = window_s

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            for name in self.metric_names:
                pts = view.series(node, name)
                if self.window_s is not None:
                    pts = [p for p in pts
                           if p[0] >= view.now - self.window_s]
                if len(pts) < 2:
                    continue
                first, last = pts[0][1], pts[-1][1]
                threshold = first * (1.0 + self.growth_frac)
                if last >= self.min_bytes and first > 0 \
                        and last > threshold:
                    out.append(self._alert(
                        node,
                        f"{name} grew {first / 2**20:.0f}MiB → "
                        f"{last / 2**20:.0f}MiB "
                        f"(+{100 * (last - first) / first:.0f}%)",
                        value=last, threshold=threshold, metric=name))
                    break  # one memory alert per node per pass
        return out


class MfuGapRule(Rule):
    """Compiled-vs-analytic MFU disagreement: both series exist for a
    node (the ledger computed ``mfu`` AND was armed with
    ``set_compiled_flops``) and the latest points differ by more than
    ``gap_frac`` relative — the signature of a silent remat, a dtype
    change, or a stale analytic formula shifting real FLOPs while the
    dashboard keeps smiling."""

    name = "mfu-divergence"
    severity = "warn"

    def __init__(self, gap_frac: float = 0.25,
                 analytic: str = "goodput.mfu",
                 compiled: str = "goodput.mfu_compiled"):
        self.gap_frac = float(gap_frac)
        self.analytic = analytic
        self.compiled = compiled

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            a = view.last(node, self.analytic)
            c = view.last(node, self.compiled)
            if a is None or c is None or a[1] <= 0 or c[1] <= 0:
                continue
            gap = abs(c[1] - a[1]) / a[1]
            if gap > self.gap_frac:
                out.append(self._alert(
                    node,
                    f"compiled-cost MFU {c[1]:.4f} vs analytic "
                    f"{a[1]:.4f} ({100 * gap:.0f}% apart) — check for "
                    f"a silent remat/dtype change or a stale "
                    f"flops-per-token formula",
                    value=gap, threshold=self.gap_frac))
        return out


class TtftRule(Rule):
    """Serving TTFT tail: a replica's ``serve.ttft_ms.p99`` series
    (the serving ledger's histogram, sampler-stamped) exceeds the SLO
    target. This is the prompt-heavy overload signal an e2e-p99 rule
    misses — queue + reservation + prefill wait all land in TTFT long
    before the decode tail moves — and it NAMES the replica, which is
    what lets the profile-capture hook grab that node's timeline."""

    name = "ttft-p99"
    severity = "page"

    def __init__(self, slo_ttft_ms: float = 2000.0,
                 min_count: float = 8.0,
                 metric: str = "serve.ttft_ms"):
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.min_count = float(min_count)
        self.metric = metric

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            count = view.last(node, f"{self.metric}.count")
            if count is None or count[1] < self.min_count:
                continue  # tail of a handful of requests is noise
            last = view.last(node, f"{self.metric}.p99")
            if last is not None and last[1] > self.slo_ttft_ms:
                out.append(self._alert(
                    node,
                    f"serving TTFT p99 {last[1]:.0f}ms over SLO "
                    f"{self.slo_ttft_ms:.0f}ms "
                    f"({count[1]:.0f} requests)",
                    value=last[1], threshold=self.slo_ttft_ms))
        return out


class StageBreachRule(Rule):
    """Stage-budgeted SLO attribution: the gateway decomposes every
    request's wall into named stages (``gateway.<svc>.stage_ms.<stage>``
    histograms — queue-wait / route / prefill / migrate / decode /
    rpc), and this rule prices each stage's p99 against its share of
    the TTFT SLO (:data:`ptype_tpu.health.forensics
    .DEFAULT_STAGE_FRACTIONS`). Where ``ttft-p99`` pages with a
    number, this pages with a CULPRIT — the page message names the
    stage eating the budget and points the runbook at ``obs tail`` →
    ``obs request``. One page per node names only the worst-overage
    stage: three stages breaching at once is one incident, not three
    pages."""

    name = "slo-stage-breach"
    severity = "page"

    def __init__(self, service: str = "llm",
                 slo_ttft_ms: float = 2000.0,
                 fractions: dict | None = None,
                 min_count: float = 8.0):
        from ptype_tpu.health import forensics
        self.service = service
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.budgets = forensics.stage_budgets_ms(slo_ttft_ms, fractions)
        self.min_count = float(min_count)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        prefix = f"gateway.{self.service}.stage_ms."
        for node in view.node_keys():
            series = self.nodes_series(view, node)
            worst = None  # (overage_ms, stage, p99, budget)
            for name in series:
                if not (name.startswith(prefix)
                        and name.endswith(".p99")):
                    continue
                stage = name[len(prefix):-len(".p99")]
                budget = self.budgets.get(stage)
                if budget is None:
                    continue
                count = view.last(node, f"{prefix}{stage}.count")
                if count is None or count[1] < self.min_count:
                    continue  # a handful of requests' tail is noise
                last = view.last(node, name)
                if last is None:
                    continue
                over = last[1] - budget
                if over > 0 and (worst is None or over > worst[0]):
                    worst = (over, stage, last[1], budget)
            if worst is not None:
                over, stage, p99, budget = worst
                out.append(self._alert(
                    node,
                    f"gateway {self.service} stage '{stage}' p99 "
                    f"{p99:.0f}ms over its {budget:.0f}ms budget "
                    f"({over:.0f}ms overage; stage budgets decompose "
                    f"TTFT SLO {self.slo_ttft_ms:.0f}ms) — "
                    f"obs tail, then obs request <trace_id>",
                    value=p99, threshold=budget,
                    service=self.service, stage=stage))
        return out

    @staticmethod
    def nodes_series(view: ClusterView, node: str) -> dict:
        return view.nodes.get(node, {}).get("series", {}) or {}


class KvPressureRule(Rule):
    """Paged-KV pool pressure: a replica's admission headroom
    (``kv.free_blocks`` / ``kv.total_blocks``) sat below ``free_frac``
    for most of the window WHILE the pool was actively evicting
    (``kv.evictions.rate`` above ``evict_rate_floor``). Both gates
    matter: low headroom alone is a well-sized busy pool; evictions
    alone are a healthy LRU turning over — together they are the
    thrash signature (admission waits at the head, prefix blocks churn
    out before they can be reused) that precedes admit-timeout sheds.
    Majority-of-window, not last-point: the free-blocks gauge swings
    at every retire, and one momentary recovery must not mask (nor one
    momentary dip fake) sustained pressure."""

    name = "kv-pressure"
    severity = "page"

    def __init__(self, free_frac: float = 0.15,
                 evict_rate_floor: float = 0.2,
                 window_s: float = 120.0, min_points: int = 3):
        self.free_frac = float(free_frac)
        self.evict_rate_floor = float(evict_rate_floor)
        self.window_s = float(window_s)
        self.min_points = int(min_points)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            total = view.last(node, "kv.total_blocks")
            if total is None or total[1] <= 0:
                continue
            pts = [p for p in view.series(node, "kv.free_blocks")
                   if p[0] >= view.now - self.window_s]
            if len(pts) < self.min_points:
                continue
            low = [v for _, v in pts
                   if v / total[1] <= self.free_frac]
            if len(low) * 2 < len(pts):
                continue
            rate = max((v for t, v in
                        view.series(node, "kv.evictions.rate")
                        if t >= view.now - self.window_s),
                       default=0.0)
            if rate <= self.evict_rate_floor:
                continue
            frac = min(low) / total[1]
            out.append(self._alert(
                node,
                f"kv pool pressure: free blocks down to "
                f"{min(low):.0f}/{total[1]:.0f} "
                f"({100 * frac:.0f}%) with evictions at "
                f"{rate:.1f}/s — admission is about to shed",
                value=frac, threshold=self.free_frac,
                evictions_per_s=round(rate, 2)))
        return out


class PrefixHitCollapseRule(Rule):
    """Prefix-cache effectiveness collapse: a replica whose
    ``kv.prefix_hit_rate`` was healthy earlier in the window reads
    collapsed now — the signature of affinity routing breaking (fleet
    churn re-hashed the keys) or eviction pressure churning the shared
    prefix out between requests. Hit rate only moves with traffic
    (change-driven sampling), so a quiet replica never fires."""

    name = "prefix-hit-collapse"
    severity = "warn"

    def __init__(self, healthy_frac: float = 0.3,
                 collapsed_frac: float = 0.1,
                 window_s: float = 600.0, min_points: int = 4):
        self.healthy_frac = float(healthy_frac)
        self.collapsed_frac = float(collapsed_frac)
        self.window_s = float(window_s)
        self.min_points = int(min_points)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = [p for p in
                   view.series(node, "kv.prefix_hit_rate")
                   if p[0] >= view.now - self.window_s]
            if len(pts) < self.min_points:
                continue
            peak = max(v for _, v in pts[:-1])
            last = pts[-1][1]
            if peak >= self.healthy_frac \
                    and last <= self.collapsed_frac:
                out.append(self._alert(
                    node,
                    f"prefix hit rate collapsed "
                    f"{peak:.2f} → {last:.2f} — check affinity "
                    f"routing and pool eviction pressure",
                    value=last, threshold=self.collapsed_frac,
                    peak=round(peak, 4)))
        return out


class ServeStallRule(Rule):
    """Per-replica serving stall: the engine's iteration counter
    (``serve.steps``) stopped advancing while the admission queue
    (``serve.queue_depth``) is non-empty — a wedged engine thread, a
    hung device call, or an admission deadlock. The queue gate keeps
    an idle replica (no traffic, no steps — healthy) from paging; the
    threshold scales with the replica's own median iteration time with
    an absolute floor, like the training ``train-stall`` rule."""

    name = "serve-stall"
    severity = "page"

    def __init__(self, factor: float = 8.0, min_gap_s: float = 5.0,
                 min_steps: int = 3,
                 steps_series: str = "serve.steps",
                 step_ms_series: str = "serve.step_ms",
                 queue_series: str = "serve.queue_depth"):
        self.factor = float(factor)
        self.min_gap_s = float(min_gap_s)
        self.min_steps = int(min_steps)
        self.steps_series = steps_series
        self.step_ms_series = step_ms_series
        self.queue_series = queue_series

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = view.series(node, self.steps_series)
            if not pts or pts[-1][1] < self.min_steps:
                continue
            queued = view.last(node, self.queue_series)
            if queued is None or queued[1] <= 0:
                continue  # nothing waiting: an idle engine is healthy
            step_vals = [v for _, v in
                         view.series(node, self.step_ms_series)]
            med_s = (statistics.median(step_vals) / 1e3
                     if step_vals else 0.0)
            threshold = max(self.factor * med_s, self.min_gap_s)
            gap = view.now - pts[-1][0]
            if gap > threshold:
                out.append(self._alert(
                    node,
                    f"engine made no iteration for {gap:.1f}s with "
                    f"{queued[1]:.0f} queued (median iteration "
                    f"{med_s * 1e3:.0f}ms, threshold "
                    f"{threshold:.1f}s)",
                    value=gap, threshold=threshold))
        return out


class RecompileStormRule(Rule):
    """Dispatch-discipline breach at runtime: a node's
    ``jit.recompiles`` counter (the jitwatch seam — same-signature
    backend compiles the trace cache should have served) grew by
    ``threshold`` or more inside the window. A steady-state process
    compiles NOTHING; sustained recompiles mean a hot loop is paying
    trace+XLA-compile per iteration — the 0.77x class a green test
    suite never sees. The alert NAMES the worst-offending function
    from the per-function ``jit.fn.*`` books, which is what makes the
    page actionable (and lets the profile-capture hook grab the right
    node's timeline). Structural: the series only exists on
    jitwatch-armed processes, so a disarmed fleet never pays a false
    page."""

    name = "recompile-storm"
    severity = "page"

    def __init__(self, threshold: float = 3.0, window_s: float = 120.0,
                 series: str = "jit.recompiles"):
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.series = series

    def _worst_fn(self, view: ClusterView, node: str):
        """(fn, recompiles) with the highest per-function count, from
        the sampled ``jit.fn.*`` series (or the live gauges when the
        snapshot carries metrics)."""
        best: tuple[str, float] | None = None
        telem = view.nodes.get(node, {})
        candidates: dict[str, float] = {}
        for name, pts in (telem.get("series") or {}).items():
            if name.startswith("jit.fn.") and pts:
                candidates[name[len("jit.fn."):]] = pts[-1][1]
        for name, val in ((telem.get("metrics") or {})
                          .get("gauges", {}).items()):
            if name.startswith("jit.fn."):
                candidates.setdefault(name[len("jit.fn."):], val)
        for fn, val in candidates.items():
            if best is None or val > best[1]:
                best = (fn, val)
        return best

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = view.series(node, self.series)
            delta = counter_delta(pts, self.window_s, view.now)
            if delta < self.threshold:
                continue
            worst = self._worst_fn(view, node)
            who = (f"; worst offender: {worst[0]} "
                   f"({worst[1]:.0f} recompiles)" if worst else "")
            out.append(self._alert(
                node,
                f"{delta:.0f} steady-state recompiles in "
                f"{self.window_s:.0f}s — a hot program is re-tracing "
                f"per call{who}; read `obs jit` for the per-function "
                f"books",
                value=delta, threshold=self.threshold,
                fn=worst[0] if worst else None))
        return out


class MigrationStallRule(Rule):
    """A disaggregated migration wedged mid-transfer: a replica's
    ``serve.migrate_inflight`` gauge stayed above zero for the whole
    window while its ``serve.migrations`` completion counter did not
    advance — an export ticket parked on a prefill replica or an
    import reservation pinned on a decode replica whose gateway leg
    died without the abort landing. Pinned blocks are pool capacity
    the admission gate can't hand out, so a stall quietly becomes
    KV-pressure sheds on a fleet that looks idle. Structural: the
    gauge only exists on migration-armed engines (ISSUE 16), so a
    unified fleet never pays a false page. Start at ``obs serve`` —
    the migration counters and per-replica class column name the
    wedged side."""

    name = "migration-stall"
    severity = "page"

    def __init__(self, window_s: float = 60.0,
                 inflight_series: str = "serve.migrate_inflight",
                 done_series: str = "serve.migrations"):
        self.window_s = float(window_s)
        self.inflight_series = inflight_series
        self.done_series = done_series

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = [p for p in view.series(node, self.inflight_series)
                   if p[0] >= view.now - self.window_s]
            if len(pts) < 2 or min(v for _, v in pts) <= 0:
                continue  # empty, briefly sampled, or drained mid-window
            done = counter_delta(
                view.series(node, self.done_series),
                self.window_s, view.now)
            if done > 0:
                continue  # migrations ARE completing; just busy
            out.append(self._alert(
                node,
                f"{pts[-1][1]:.0f} migration(s) in flight for "
                f"{self.window_s:.0f}s with none completing — a "
                f"parked export or pinned import reservation is "
                f"holding KV blocks; read `obs serve` first (class "
                f"column + migration counters name the wedged side)",
                value=pts[-1][1], threshold=0.0))
        return out


class ReshardStallRule(Rule):
    """A live elastic reshard wedged mid-move: a node's
    ``train.reshard_inflight`` gauge stayed above zero for the whole
    window while its ``train.reshards`` completion counter did not
    advance. The trainer raises the gauge before the re-pad/re-place
    loop and only clears it after the atomic swap lands
    (``StoreDPTrainer.reshard``), so a stuck gauge means the move is
    stalled (a wedged bucket re-place, a retry loop that keeps losing)
    and training is NOT stepping — the survivor set is paid for but
    idle. Structural: the series only exists on trainers that armed a
    reshard, so steady-state fleets never page. Start at ``obs
    scale``/the trace plane first — the ``train.reshard`` span (and
    its per-bucket chaos trace, if a drill is armed) names the bucket
    the move died in."""

    name = "reshard-stall"
    severity = "page"

    def __init__(self, window_s: float = 60.0,
                 inflight_series: str = "train.reshard_inflight",
                 done_series: str = "train.reshards"):
        self.window_s = float(window_s)
        self.inflight_series = inflight_series
        self.done_series = done_series

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            pts = [p for p in view.series(node, self.inflight_series)
                   if p[0] >= view.now - self.window_s]
            if len(pts) < 2 or min(v for _, v in pts) <= 0:
                continue  # no reshard, briefly sampled, or completed
            done = counter_delta(
                view.series(node, self.done_series),
                self.window_s, view.now)
            if done > 0:
                continue  # reshards ARE landing; just churning
            out.append(self._alert(
                node,
                f"a live reshard has been in flight for "
                f"{self.window_s:.0f}s without completing — training "
                f"is parked on the survivor set; read `obs scale` and "
                f"the train.reshard trace span first (they name the "
                f"bucket the move stalled in), then the elastic "
                f"recover log for retry exhaustion",
                value=pts[-1][1], threshold=0.0))
        return out


class CapacityHeadroomRule(Rule):
    """Offered load sustained above the last-measured capacity knee:
    a node publishing traffic-plane series (the open-loop driver's
    ``loadgen.offered`` counter) is being asked for more than the
    frontier sweep measured the fleet good for (the ``loadgen
    .knee_rps`` gauge :func:`~ptype_tpu.loadgen.frontier.publish_knee`
    stamps). This is the *leading* capacity signal — it warns while
    goodput still holds, before the SLO burns and ``slo-burn-rate``
    pages. Structural: both series exist only where a frontier has
    been measured and traffic is being offered, so untraffic'd fleets
    never see it. Runbook: docs/OPERATIONS.md "Capacity planning"."""

    name = "capacity-headroom"
    severity = "warn"

    def __init__(self, window_s: float = 30.0,
                 headroom_frac: float = 0.9,
                 min_offered: float = 8.0):
        self.window_s = float(window_s)
        #: Warn at this fraction of the knee — at 1.0 the warning and
        #: the goodput collapse arrive together, which is too late.
        self.headroom_frac = float(headroom_frac)
        self.min_offered = float(min_offered)

    def evaluate(self, view: ClusterView) -> list[Alert]:
        out = []
        for node in view.node_keys():
            knee = view.last(node, "loadgen.knee_rps")
            if knee is None or knee[1] <= 0:
                continue  # no frontier measured on this node
            pts = view.series(node, "loadgen.offered")
            if not pts:
                continue
            offered = counter_delta(pts, self.window_s, view.now)
            if offered < self.min_offered:
                continue  # a handful of requests is not "sustained"
            span = min(self.window_s,
                       max(1e-9, pts[-1][0] - pts[0][0]))
            rate = offered / span
            bar = self.headroom_frac * knee[1]
            if rate >= bar:
                out.append(self._alert(
                    node,
                    f"offered load ~{rate:.0f} rps sustained at "
                    f">={self.headroom_frac:.0%} of the measured "
                    f"capacity knee ({knee[1]:.0f} rps) — grow the "
                    f"fleet or re-sweep the frontier before the SLO "
                    f"burns",
                    value=rate, threshold=bar))
        return out


def default_rules(service: str = "llm",
                  slo_p99_ms: float | None = None,
                  slo_ttft_ms: float | None = None) -> list[Rule]:
    """The stock watchdog set; ``slo_p99_ms`` adds the latency rule
    and ``slo_ttft_ms`` the serving TTFT rule — both are SLO targets
    nobody but the operator can pick, so like ``P99Rule`` the TTFT
    page is opt-in (a healthy prompt-heavy fleet over an arbitrary
    default would page, and auto-capture profiles, out of the box).
    The structural rules (kv-pressure / prefix-hit-collapse /
    serve-stall / migration-stall / reshard-stall /
    capacity-headroom) are always in the set — they key on
    ``serve.*`` / ``kv.*`` / reshard-armed ``train.*`` /
    frontier-armed ``loadgen.*`` series only the relevant plane emits
    and need no target, so other fleets never pay a false page for
    their presence."""
    rules: list[Rule] = [
        BurnRateRule(service=service),
        StallRule(),
        StragglerRule(),
        LossRule(),
        CoordFlapRule(),
        MemoryGrowthRule(),
        MfuGapRule(),
        KvPressureRule(),
        PrefixHitCollapseRule(),
        ServeStallRule(),
        RecompileStormRule(),
        MigrationStallRule(),
        ReshardStallRule(),
        CapacityHeadroomRule(),
    ]
    if slo_ttft_ms is not None:
        rules.append(TtftRule(slo_ttft_ms=slo_ttft_ms))
        # Same opt-in SLO target, finer verdict: the stage-budget rule
        # pages naming the culprit stage rather than the total.
        rules.append(StageBreachRule(service=service,
                                     slo_ttft_ms=slo_ttft_ms))
    if slo_p99_ms is not None:
        rules.insert(1, P99Rule(service=service, slo_p99_ms=slo_p99_ms))
    return rules


class AlertEngine:
    """Run a rule set over snapshots; fire, log, count, and dump.

    ``evaluate`` returns only NEWLY fired alerts — a (rule, node) pair
    re-firing within ``cooldown_s`` is suppressed, so a polling loop
    does not page once per poll for one ongoing condition. History
    stays in :attr:`alerts` (bounded) for the top view.

    ``capture`` takes an alert callable — in practice
    :class:`ptype_tpu.health.profiling.AlertCapture`, which turns a
    ``straggler``/``train-stall``/``slo-p99`` firing into a short
    device-profile capture on the NAMED node (its own rate limit, its
    own thread) so the page ships with its evidence. Any hook failure
    is logged, never raised: the watchdog outlives its attachments.
    """

    def __init__(self, rules: list[Rule] | None = None,
                 cooldown_s: float = 30.0, dump: bool = True,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 capture=None):
        self.rules = rules if rules is not None else default_rules()
        self.cooldown_s = float(cooldown_s)
        self.dump = dump
        self.capture = capture
        self.registry = (registry if registry is not None
                         else metrics_mod.metrics)
        self.alerts: collections.deque = collections.deque(maxlen=256)
        self._last_fired: dict[tuple[str, str], float] = {}
        self._lock = lockcheck.lock("health.alerts")

    def evaluate(self, snapshot: dict,
                 now: float | None = None) -> list[Alert]:
        view = ClusterView(snapshot, now)
        fired: list[Alert] = []
        for rule in self.rules:
            try:
                found = rule.evaluate(view)
            except Exception as e:  # noqa: BLE001 — one broken rule
                # must not kill the watchdog that hosts the others.
                log.warning("health rule failed",
                            kv={"rule": rule.name, "err": repr(e)})
                continue
            fired.extend(found)
        kept: list[Alert] = []
        with self._lock:
            for alert in fired:
                if not alert.ts:
                    alert.ts = view.now
                last = self._last_fired.get(alert.key())
                if last is not None and \
                        view.now - last < self.cooldown_s:
                    continue
                self._last_fired[alert.key()] = view.now
                self.alerts.append(alert)
                kept.append(alert)
        for alert in kept:
            self.registry.counter("health.alerts").add(1)
            self.registry.counter(f"health.alerts.{alert.rule}").add(1)
            log.warning("health alert", kv=alert.to_dict())
            if self.dump:
                trace.maybe_dump(f"alert:{alert.rule}:{alert.node}")
            if self.capture is not None:
                try:
                    self.capture(alert)
                except Exception as e:  # noqa: BLE001 — a broken
                    # capture hook must not kill the watchdog.
                    log.warning("alert capture hook failed",
                                kv={"rule": alert.rule,
                                    "node": alert.node, "err": repr(e)})
        return kept

    def recent(self, limit: int = 16) -> list[Alert]:
        with self._lock:
            out = list(self.alerts)
        return out[-limit:]
