"""Goodput ledger: per-step time attribution + straggler detection.

MFU used to live only in bench tail records and the step-time
breakdown only as spans a human loads into Perfetto. This module makes
both a live, always-on account:

- :class:`GoodputLedger` listens on the ``metrics.annotate`` seam
  (:func:`ptype_tpu.metrics.set_annotate_observer`) — the one hook
  train/store_dp.py, train/trainer.py, and parallel/tensorstore.py
  already run their regions through — and folds every finished region
  into a per-step record: ``data`` (``train.data``), ``collective``
  (``store.push*`` / ``store.pull*``), ``checkpoint``
  (``checkpoint.*``), ``optimizer`` (``train.opt*`` — the apply leg,
  split out so the ZeRO-1 sharded update's ~N× FLOP saving is a
  visible number), ``prefill`` (``serve.prefill`` — chunked-prefill
  admission on a serving node whose ledger steps on ``serve.step``;
  the paged engine's bounded-stall contract as a measured leg),
  ``compute`` (the step remainder), and ``stall`` (the wall-clock gap
  between consecutive steps). Each closed step
  publishes ``goodput.*`` gauges into the node's registry, which the
  health :class:`~ptype_tpu.health.series.Sampler` turns into the
  series every other node can pull.
- :func:`detect_stragglers` is the robust cross-node comparison
  (median + k·MAD with an absolute-excess and ratio floor — MAD alone
  explodes on a tight cluster) that names the slow node; the
  straggler alert rule feeds it per-node step/collective means from
  the stitched cluster snapshot.

Goodput here is the fraction of wall time spent in compute:
``100 * compute / (step + stall)`` — the number that drops when a
collective slows, a checkpoint blocks, the input pipeline starves the
step, or the scheduler steals the host.
"""

from __future__ import annotations

import collections
import statistics
import threading
import time

from ptype_tpu import metrics as metrics_mod

#: Steps of history a ledger keeps.
LEDGER_WINDOW = 512


def _component(name: str) -> str | None:
    """Region name → breakdown component (None: a region no step
    attributes)."""
    fam = name.split("/", 1)[0]
    if fam.startswith("store.push") or fam.startswith("store.pull"):
        return "collective"
    if fam.startswith("checkpoint"):
        return "checkpoint"
    if fam == "train.data":
        return "data"
    if fam == "train.opt":
        # The optimizer apply — its own leg since the ZeRO-1 sharded
        # update (train.opt/zero) exists precisely to shrink it ~N×;
        # the replicated apply paths ride the same region name so the
        # comparison is apples-to-apples in `obs top` and the bench.
        return "optimizer"
    if fam == "serve.prefill":
        # Chunked-prefill admission work between decode steps on a
        # SERVING node (ledger step_name="serve.step"): its own leg so
        # the paged engine's bounded-stall contract is a measured
        # number — max per-step prefill is capped by the chunk budget,
        # and what prefill doesn't account for shows up as stall.
        return "prefill"
    return None


class _Region:
    """Context manager timing one region straight into a ledger — the
    direct-drive path for simulated nodes (several ledgers in one
    process can't share the single annotate observer)."""

    __slots__ = ("_ledger", "_name", "_t0")

    def __init__(self, ledger: "GoodputLedger", name: str):
        self._ledger = ledger
        self._name = name

    def __enter__(self) -> "_Region":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._ledger.observe(self._name,
                             time.perf_counter() - self._t0)
        return False


class GoodputLedger:
    """Per-step goodput accounting over the annotate seam.

    ``tokens_per_step`` / ``flops_per_token`` / ``n_chips`` (all
    optional) turn the breakdown into live ``tokens_per_sec`` and MFU
    series; without them the ledger still attributes time.
    """

    def __init__(self,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 step_name: str = "train.step",
                 window: int = LEDGER_WINDOW,
                 tokens_per_step: int = 0,
                 flops_per_token: float = 0.0,
                 n_chips: int = 1,
                 peak_tflops: float | None = None):
        self.registry = (registry if registry is not None
                         else metrics_mod.metrics)
        self.step_name = step_name
        self.tokens_per_step = int(tokens_per_step)
        self.flops_per_token = float(flops_per_token)
        self.n_chips = int(n_chips)
        self.peak_tflops = peak_tflops
        #: FLOPs per step as XLA compiled them (health/profiling
        #: compiled-cost accounting) — 0.0 until set_compiled_flops.
        self.compiled_flops_per_step = 0.0
        self._lock = threading.Lock()
        #: (component, dur_s, monotonic end) for regions finished since
        #: the last step closed — the end stamp lets _close_step split
        #: them into inside-the-step (subtracted from compute) vs
        #: between-steps (a checkpoint save after the step: counted in
        #: its component AND deducted from stall, never from compute).
        #: Bounded: a process that emits component regions but never
        #: steps (a serving node pulling the store per request) must
        #: not leak — old events age out, steps see the recent window.
        self._events: collections.deque = collections.deque(
            maxlen=4096)
        self._records: collections.deque = collections.deque(
            maxlen=int(window))
        self._prev_end: float | None = None
        self._steps = 0

    # ----------------------------------------------------------- intake

    def observe(self, name: str, dur_s: float,
                end: float | None = None) -> None:
        """Fold one finished region (the annotate-observer signature,
        plus an injectable monotonic ``end`` for deterministic tests).
        """
        end = time.perf_counter() if end is None else end
        if name.split("/", 1)[0] == self.step_name:
            self._close_step(dur_s, end)
            return
        comp = _component(name)
        if comp is not None:
            with self._lock:
                self._events.append((comp, dur_s, end))

    def region(self, name: str) -> _Region:
        """Time a region directly into this ledger — the simulated-
        node path; real processes install() onto the annotate seam."""
        return _Region(self, name)

    def set_compiled_flops(self, flops_per_step: float) -> "GoodputLedger":
        """Arm the compiled-cost MFU: ``flops_per_step`` from XLA's
        ``cost_analysis`` over the step programs
        (:func:`ptype_tpu.health.profiling.compiled_cost`, e.g.
        ``StoreDPTrainer.compiled_cost()["flops"]``). Each closed step
        then records ``mfu_compiled`` next to the analytic ``mfu`` —
        and ``mfu_gap_pct`` when both exist, the disagreement the
        ``mfu-divergence`` alert rule watches (a silent remat or dtype
        change shifts real FLOPs; the formula never notices)."""
        with self._lock:
            self.compiled_flops_per_step = float(flops_per_step)
        return self

    def install(self) -> "GoodputLedger":
        """Become the process's annotate observer: every
        ``metrics.annotate`` region now feeds this ledger."""
        metrics_mod.set_annotate_observer(self.observe)
        return self

    def uninstall(self) -> None:
        metrics_mod.set_annotate_observer(None)

    # ------------------------------------------------------------ ledger

    def _close_step(self, step_s: float, end: float) -> None:
        with self._lock:
            events, self._events = self._events, collections.deque(
                maxlen=4096)
            # Split components at the step's start: inside regions are
            # step costs (subtracted from compute); regions that ended
            # BEFORE the step began ran between steps (a checkpoint
            # save after the previous step) — counted in their
            # component and deducted from stall, never from compute.
            step_start = end - step_s
            inside = {"data": 0.0, "collective": 0.0,
                      "checkpoint": 0.0, "optimizer": 0.0,
                      "prefill": 0.0}
            between = dict(inside)
            for comp, dur, t in events:
                (inside if t >= step_start else between)[comp] += dur
            wall = (step_s if self._prev_end is None
                    else max(step_s, end - self._prev_end))
            self._prev_end = end
            stall = max(0.0, (wall - step_s) - sum(between.values()))
            data = inside["data"] + between["data"]
            coll = inside["collective"] + between["collective"]
            ckpt = inside["checkpoint"] + between["checkpoint"]
            opt = inside["optimizer"] + between["optimizer"]
            prefill = inside["prefill"] + between["prefill"]
            # Clamp so a mis-nested caller can't drive compute negative.
            compute = max(0.0, step_s - min(step_s,
                                            sum(inside.values())))
            goodput = 100.0 * compute / wall if wall > 0 else 0.0
            self._steps += 1
            rec = {
                "step": self._steps,
                "t": round(time.time(), 3),
                # Full wall clock this step accounts for (step + stall
                # + between-step component time) — the share
                # denominator; step_ms + stall_ms alone EXCLUDES
                # between-step components that the component sums
                # include, which would let a share exceed 100%.
                "wall_ms": round(wall * 1e3, 3),
                "step_ms": round(step_s * 1e3, 3),
                "compute_ms": round(compute * 1e3, 3),
                "collective_ms": round(coll * 1e3, 3),
                "data_ms": round(data * 1e3, 3),
                "checkpoint_ms": round(ckpt * 1e3, 3),
                "optimizer_ms": round(opt * 1e3, 3),
                "prefill_ms": round(prefill * 1e3, 3),
                "stall_ms": round(stall * 1e3, 3),
                "goodput_pct": round(goodput, 2),
            }
            if self.tokens_per_step and wall > 0:
                tps = self.tokens_per_step / wall
                rec["tokens_per_sec"] = round(tps, 1)
                if self.flops_per_token:
                    rec["mfu"] = round(metrics_mod.mfu(
                        tps, self.flops_per_token, self.n_chips,
                        self.peak_tflops), 5)
            if self.compiled_flops_per_step and wall > 0:
                # tokens/sec × flops/token == flops/sec: feed the
                # shared mfu() with (1/wall, flops_per_step).
                rec["mfu_compiled"] = round(metrics_mod.mfu(
                    1.0 / wall, self.compiled_flops_per_step,
                    self.n_chips, self.peak_tflops), 5)
                if rec.get("mfu"):
                    rec["mfu_gap_pct"] = round(
                        100.0 * (rec["mfu_compiled"] - rec["mfu"])
                        / rec["mfu"], 2)
            self._records.append(rec)
        reg = self.registry
        for key in ("step_ms", "compute_ms", "collective_ms", "data_ms",
                    "checkpoint_ms", "optimizer_ms", "prefill_ms",
                    "stall_ms", "goodput_pct", "tokens_per_sec", "mfu",
                    "mfu_compiled", "mfu_gap_pct"):
            if key in rec:
                name = "goodput.pct" if key == "goodput_pct" \
                    else f"goodput.{key}"
                reg.gauge(name).set(rec[key])
        reg.counter("goodput.steps").add(1)

    # ---------------------------------------------------------- readouts

    def records(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._records)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def summary(self, limit: int | None = None) -> dict:
        """Window means: ``goodput_pct``, a ``step_breakdown`` dict
        (the bench tail's shape), and throughput when configured."""
        recs = self.records(limit)
        if not recs:
            return {"steps": 0, "goodput_pct": 0.0, "step_breakdown": {}}
        n = len(recs)

        def mean(key: str) -> float:
            return round(sum(r.get(key, 0.0) for r in recs) / n, 3)

        breakdown = {
            k: mean(k) for k in
            ("step_ms", "compute_ms", "collective_ms", "data_ms",
             "checkpoint_ms", "optimizer_ms", "prefill_ms",
             "stall_ms")}
        # Share denominator: mean wall over the records that carry it
        # (averaging absent keys as 0 would deflate the wall and push
        # the share past 100% — the bound this metric promises).
        walls = [r["wall_ms"] for r in recs if "wall_ms" in r]
        wall = (sum(walls) / len(walls) if walls
                else breakdown["step_ms"] + breakdown["stall_ms"])
        out = {
            "steps": recs[-1]["step"],
            "goodput_pct": round(mean("goodput_pct"), 2),
            "step_breakdown": breakdown,
            # The ISSUE 6 acceptance metric: how much of the step the
            # collective leg owns — what quantized wires + fine-grained
            # overlap (store_dp overlap=True) exist to shrink.
            "collective_share_pct": round(
                100.0 * breakdown["collective_ms"] / wall, 2)
            if wall else 0.0,
        }
        if "tokens_per_sec" in recs[-1]:
            out["tokens_per_sec"] = mean("tokens_per_sec")
        if "mfu" in recs[-1]:
            out["mfu"] = round(mean("mfu"), 5)
        if "mfu_compiled" in recs[-1]:
            out["mfu_compiled"] = round(mean("mfu_compiled"), 5)
        if "mfu_gap_pct" in recs[-1]:
            out["mfu_gap_pct"] = round(mean("mfu_gap_pct"), 2)
        return out


# ------------------------------------------------- process-wide default

_default: GoodputLedger | None = None
_default_lock = threading.Lock()


def install(**kwargs) -> GoodputLedger:
    """Create + install the process-wide default ledger on the
    annotate seam (idempotent; new kwargs replace the old ledger)."""
    global _default
    with _default_lock:
        led = GoodputLedger(**kwargs).install()
        _default = led
        return led


def uninstall() -> None:
    global _default
    with _default_lock:
        led, _default = _default, None
    if led is not None:
        led.uninstall()


def default() -> GoodputLedger | None:
    return _default


# ------------------------------------------------- straggler detection


def detect_stragglers(per_node: dict[str, float], k: float = 4.0,
                      min_nodes: int = 3, min_excess: float = 0.0,
                      min_ratio: float = 1.25) -> list[dict]:
    """Name the slow nodes: value > median + max(k·MAD, min_excess)
    AND value > min_ratio·median.

    Median + MAD is the robust core (one straggler cannot drag the
    mean it is judged against), but a tight healthy cluster has MAD≈0,
    so an absolute excess floor (``min_excess``, caller's units) and a
    ratio floor keep scheduler noise from paging. Returns
    ``[{"node", "value", "median", "threshold"}, ...]``."""
    if len(per_node) < min_nodes:
        return []
    vals = list(per_node.values())
    med = statistics.median(vals)
    mad = statistics.median([abs(v - med) for v in vals])
    threshold = med + max(k * mad, min_excess)
    return [{"node": node, "value": round(v, 3),
             "median": round(med, 3), "threshold": round(threshold, 3)}
            for node, v in sorted(per_node.items())
            if v > threshold and v > min_ratio * med]


def _dedup_aliases(snapshot: dict):
    """Yield each distinct PROCESS-level node once: several registry
    service names can alias one process (same pid + same reported
    service → same registry/sampler), and a duplicated series must not
    skew the straggler median or double-fire the alert. Simulated
    nodes sharing a pid stay distinct — they report distinct service
    names over their own telemetry endpoints."""
    seen: set = set()
    for key, telem in snapshot.get("nodes", {}).items():
        pid = telem.get("pid")
        if pid is not None:
            ident = (pid, telem.get("service", ""))
            if ident in seen:
                continue
            seen.add(ident)
        yield key, telem


def node_series_means(snapshot: dict, name: str,
                      window_s: float | None = None,
                      now: float | None = None) -> dict[str, float]:
    """Per-node mean of a named series from a cluster snapshot —
    the straggler rule's input. Nodes without the series are absent
    (a serving node has no step series; it must not skew training
    stragglers)."""
    now = time.time() if now is None else now
    out: dict[str, float] = {}
    for key, telem in _dedup_aliases(snapshot):
        pts = telem.get("series", {}).get(name) or []
        if window_s is not None:
            pts = [p for p in pts if p[0] >= now - window_s]
        if pts:
            out[key] = sum(p[1] for p in pts) / len(pts)
    return out


def node_span_means(snapshot: dict, prefix: str,
                    window_s: float | None = None,
                    now: float | None = None) -> dict[str, float]:
    """Per-node mean duration (ms) of spans whose name starts with
    ``prefix`` — the fallback comparison when a fleet runs the trace
    plane but not the sampler: per-node ``store.push_tree``/step span
    durations straight from the stitched snapshot."""
    now = time.time() if now is None else now
    sums: dict[str, float] = {}
    counts: dict[str, int] = {}
    for key, telem in _dedup_aliases(snapshot):
        for sp in telem.get("spans", ()):
            if not sp.get("name", "").startswith(prefix):
                continue
            if window_s is not None and \
                    sp.get("start_s", 0.0) < now - window_s:
                continue
            sums[key] = sums.get(key, 0.0) + sp.get("dur_s", 0.0) * 1e3
            counts[key] = counts.get(key, 0) + 1
    return {k: sums[k] / counts[k] for k in sums}
