"""Distributed tracing — spans, wire context, and the flight recorder.

The reference's entire observability API was one counter
(``Client.ConnectionErrs``, SURVEY.md §5); the repo since grew
per-process metrics (metrics.py) and KV logs (logs.py), but a request
crossing gateway → actor RPC → coordinator → TensorStore left no
connected record — every soak failure was debugged by grepping five
processes' logs. This module is the missing trace plane:

- **Spans** carry W3C-style context (``trace_id`` / ``span_id`` /
  parent) through a per-thread contextvar; :func:`span` opens a child
  of whatever is current, so nesting needs no plumbing.
- **Wire propagation**: the active span's ``traceparent`` rides actor
  RPC frames (rpc.py injects ``tp``, actor.py re-attaches it around
  dispatch) and coord wire frames (coord/wire.py injects ``_tp``,
  coord/service.py re-attaches) — one request is ONE trace across
  every process it touches.
- **Flight recorder**: each process keeps finished spans in a bounded
  ring (:class:`FlightRecorder`), dumpable on demand
  (:meth:`FlightRecorder.dump_jsonl`) or on unhandled error/shed
  (:func:`maybe_dump`, armed by ``PTYPE_TRACE_DUMP_DIR`` or
  ``enable(dump_dir=...)``).
- **Chaos correlation**: fault firings and recovery beacons
  (:mod:`ptype_tpu.chaos`) land as events on the span they hit, so a
  soak failure shows *which request* a fault landed in.

Zero-cost contract (same shape as chaos.py): with no recorder armed,
:func:`span` / :func:`span_from` / :func:`attach` return a module
singleton no-op context manager — one global load + ``None`` check,
no allocation; :func:`traceparent` returns ``None`` before touching
the contextvar. Tracing is enabled per process with :func:`enable`
(tests, the obs demo, bench probes) or the ``PTYPE_TRACE`` env var.

This module imports only the stdlib plus :mod:`ptype_tpu.chaos`
(itself stdlib-only) — it sits under logs/metrics/rpc and must never
create an import cycle.
"""

from __future__ import annotations

import collections
import contextvars
import json
import os
import random
import threading
import time

from ptype_tpu import chaos

__all__ = [
    "Span", "FlightRecorder",
    "enable", "disable", "enabled", "recorder",
    "span", "span_from", "attach", "current", "traceparent",
    "parse_traceparent", "add_event", "maybe_dump", "telemetry",
]

#: Env var: truthy value arms tracing at import (multiprocess workers
#: join a traced run without code changes, like PTYPE_CHAOS_PLAN).
TRACE_ENV = "PTYPE_TRACE"
#: Env var: directory for on-error flight-recorder dumps.
DUMP_ENV = "PTYPE_TRACE_DUMP_DIR"

_ids = random.Random()


def _new_trace_id() -> str:
    return f"{_ids.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_ids.getrandbits(64):016x}"


class Span:
    """One timed operation. Created only while tracing is enabled;
    finished spans are frozen into the process flight recorder."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_s",
                 "dur_s", "attrs", "events", "status", "tid", "remote")

    def __init__(self, name: str, trace_id: str, parent_id: str | None,
                 remote: bool = False):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        #: Wall clock, NOT monotonic: cross-process spans must land on
        #: one shared timeline for the stitched Perfetto view.
        self.start_s = time.time()
        self.dur_s = 0.0
        self.attrs: dict = {}
        self.events: list[dict] = []
        self.status = "ok"
        self.tid = threading.get_ident()
        #: True for the placeholder parent re-created from a wire
        #: traceparent by :func:`attach` — context only, never recorded.
        self.remote = remote

    def set_attr(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def set_status(self, status: str) -> "Span":
        """Mark the span's outcome explicitly — for failures the code
        CATCHES (a retried attempt, an absorbed transport error) that
        the context-manager exit therefore never sees."""
        self.status = status
        return self

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name,
                            "t": round(time.time() - self.start_s, 6),
                            **({"attrs": attrs} if attrs else {})})

    def to_dict(self) -> dict:
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "start_s": round(self.start_s, 6),
             "dur_s": round(self.dur_s, 6), "status": self.status,
             "tid": self.tid}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = self.events
        return d

    def __repr__(self) -> str:
        return (f"Span({self.name!r} trace={self.trace_id[:8]} "
                f"span={self.span_id[:8]} {self.status})")


class FlightRecorder:
    """Bounded ring of finished spans — the per-process black box.

    A ring, not a file: tracing must be cheap enough to leave on in a
    soak, and the interesting spans are always the most recent ones.
    Pull the ring over RPC (:func:`telemetry` via ``ptype.Telemetry``)
    or dump it to JSONL when something goes wrong.
    """

    def __init__(self, service: str = "", capacity: int = 4096):
        self.service = service or f"pid-{os.getpid()}"
        self.pid = os.getpid()
        self.capacity = int(capacity)
        self._ring: "collections.deque[Span]" = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._finished = 0

    def record(self, sp: Span) -> None:
        with self._lock:
            self._ring.append(sp)
            self._finished += 1

    @property
    def finished(self) -> int:
        with self._lock:
            return self._finished

    def spans(self, trace_id: str | None = None,
              limit: int | None = None) -> list[Span]:
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def to_dicts(self, limit: int | None = None,
                 trace_id: str | None = None) -> list[dict]:
        return [s.to_dict() for s in self.spans(trace_id, limit)]

    def trace_ids(self) -> list[str]:
        """Distinct trace ids in the ring, oldest first."""
        seen: dict[str, None] = {}
        for s in self.spans():
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def dump_jsonl(self, path: str) -> int:
        """Write the ring (one span dict per line); returns the count."""
        spans = self.to_dicts()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for s in spans:
                f.write(json.dumps(s, separators=(",", ":")) + "\n")
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# -------------------------------------------------------------- module API

_recorder: FlightRecorder | None = None
_current: "contextvars.ContextVar[Span | None]" = contextvars.ContextVar(
    "ptype_trace_span", default=None)
_dump_dir: str | None = None
_dump_last = 0.0
_dump_lock = threading.Lock()
#: Minimum seconds between on-error dumps — an error storm must not
#: turn the flight recorder into a disk-filling loop.
DUMP_MIN_INTERVAL_S = 5.0


def enable(service: str = "", capacity: int = 4096,
           dump_dir: str | None = None) -> FlightRecorder:
    """Arm tracing process-wide; returns the fresh flight recorder.
    Also registers the chaos observer so fault firings / recovery
    beacons land as events on the span they hit."""
    global _recorder, _dump_dir
    rec = FlightRecorder(service, capacity)
    _recorder = rec
    if dump_dir is not None:
        _dump_dir = dump_dir
    chaos.set_observer(_chaos_observer)
    return rec


def disable() -> None:
    global _recorder, _dump_dir
    _recorder = None
    _dump_dir = None
    chaos.set_observer(None)


def _restore(rec: FlightRecorder | None, dump_dir: str | None) -> None:
    """Re-arm a previously captured (recorder, dump_dir) pair — how the
    bench overhead probe hands back the host process's tracing state
    (ring, service name, dump config) after toggling around its own
    measurement."""
    global _recorder, _dump_dir
    _recorder = rec
    _dump_dir = dump_dir
    chaos.set_observer(_chaos_observer if rec is not None else None)


def enabled() -> bool:
    return _recorder is not None


def recorder() -> FlightRecorder | None:
    return _recorder


def dump_dir() -> str | None:
    """The on-error dump directory, if armed (``enable(dump_dir=...)``
    or ``PTYPE_TRACE_DUMP_DIR``) — where :func:`maybe_dump` writes,
    and where the health plane's alert-triggered profile captures
    land so a page's span ring and device timeline sit side by side."""
    return _dump_dir or os.environ.get(DUMP_ENV) or None


def current() -> Span | None:
    """The active span on this thread, or None (always None when
    tracing is disabled — stale contextvars from a disable() mid-span
    must not leak ids into logs)."""
    if _recorder is None:
        return None
    return _current.get()


def traceparent() -> str | None:
    """W3C-style ``00-<trace_id>-<span_id>-01`` for the active span —
    what the rpc/coord transports inject into outbound frames."""
    if _recorder is None:
        return None
    sp = _current.get()
    if sp is None:
        return None
    return f"00-{sp.trace_id}-{sp.span_id}-01"


def current_trace_id() -> str | None:
    """The active trace id on this thread, or None — the exemplar
    seam (:meth:`ptype_tpu.metrics.Histogram.observe` attaches it to
    tail observations). One global load when tracing is disabled."""
    if _recorder is None:
        return None
    sp = _current.get()
    return sp.trace_id if sp is not None else None


def parse_traceparent(tp) -> tuple[str, str] | None:
    """(trace_id, span_id) from a traceparent, or None if malformed —
    a peer's garbage must degrade to 'start a fresh trace', not raise."""
    if not isinstance(tp, str):
        return None
    parts = tp.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


class _Noop:
    """The disabled-path singleton: a context manager that allocates
    nothing and absorbs the whole Span surface."""

    __slots__ = ()

    def __enter__(self) -> "_Noop":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attr(self, key: str, value) -> "_Noop":
        return self

    def set_status(self, status: str) -> "_Noop":
        return self

    def add_event(self, name: str, **attrs) -> None:
        pass


_NOOP = _Noop()


class _SpanCtx:
    """Context manager that opens a span as a child of the current (or
    an explicit remote) context, makes it current for the scope, and
    freezes it into the recorder on exit."""

    __slots__ = ("_rec", "_name", "_attrs", "_parent", "_span", "_token")

    def __init__(self, rec: FlightRecorder, name: str,
                 parent: tuple[str, str] | None, attrs: dict):
        self._rec = rec
        self._name = name
        self._attrs = attrs
        self._parent = parent  # (trace_id, span_id) | None
        self._span: Span | None = None
        self._token = None

    def __enter__(self) -> Span:
        if self._parent is not None:
            trace_id, parent_id = self._parent
        else:
            cur = _current.get()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = _new_trace_id(), None
        sp = Span(self._name, trace_id, parent_id)
        if self._attrs:
            sp.attrs.update(self._attrs)
        self._span = sp
        self._token = _current.set(sp)
        # Monotonic duration clock alongside the wall-clock start.
        sp.attrs["_t0"] = time.perf_counter()
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        sp.dur_s = time.perf_counter() - sp.attrs.pop("_t0")
        if exc is not None:
            # ShedError is a typed refusal, not a failure — checked by
            # name so this module stays import-light.
            sp.status = ("shed" if type(exc).__name__ == "ShedError"
                         else "error")
            sp.add_event("exception", type=type(exc).__name__,
                         message=str(exc)[:200])
        _current.reset(self._token)
        self._rec.record(sp)
        return False


def span(name: str, **attrs):
    """Open a span (child of the current one) for a ``with`` scope.
    The no-op singleton when tracing is disabled — no allocation."""
    rec = _recorder
    if rec is None:
        return _NOOP
    return _SpanCtx(rec, name, None, attrs)


def span_from(tp, name: str, **attrs):
    """Open a span whose parent is a wire ``traceparent`` (the server
    side of a propagated call). Falls back to :func:`span` semantics
    when ``tp`` is absent/malformed; no-op when disabled."""
    rec = _recorder
    if rec is None:
        return _NOOP
    return _SpanCtx(rec, name, parse_traceparent(tp), attrs)


class _AttachCtx:
    """Make a remote traceparent the current context WITHOUT opening a
    recorded span — the seam for dispatch paths that already open
    their own span (ActorServer.dispatch) one frame below."""

    __slots__ = ("_parent", "_token")

    def __init__(self, parent: tuple[str, str]):
        self._parent = parent
        self._token = None

    def __enter__(self):
        trace_id, span_id = self._parent
        ph = Span("", trace_id, None, remote=True)
        ph.span_id = span_id  # impersonate the remote caller's span
        self._token = _current.set(ph)
        return ph

    def __exit__(self, *exc) -> bool:
        _current.reset(self._token)
        return False


def attach(tp):
    """Context manager adopting a wire traceparent as the current
    context (no span recorded). No-op when disabled or ``tp`` is
    absent/malformed."""
    if _recorder is None:
        return _NOOP
    parent = parse_traceparent(tp)
    if parent is None:
        return _NOOP
    return _AttachCtx(parent)


def add_event(name: str, **attrs) -> None:
    """Attach an event to the active span; free no-op otherwise."""
    if _recorder is None:
        return
    sp = _current.get()
    if sp is not None and not sp.remote:
        sp.add_event(name, **attrs)


def _chaos_observer(kind: str, site: str, action: str, key: str) -> None:
    """chaos.py observer: fault firings and recovery beacons become
    events on whatever span the afflicted thread is inside."""
    if _recorder is None:
        return
    sp = _current.get()
    if sp is not None and not sp.remote:
        sp.add_event(f"chaos.{kind}", site=site, action=action, key=key)


# ------------------------------------------------------- on-error dumping


def maybe_dump(reason: str = "") -> str | None:
    """Dump the flight recorder to ``<dump_dir>/flight-<pid>-<ns>.jsonl``
    if a dump dir is configured (``enable(dump_dir=...)`` or
    ``PTYPE_TRACE_DUMP_DIR``), rate-limited to one dump per
    :data:`DUMP_MIN_INTERVAL_S`. Returns the path or None.

    Called from the unhandled-error path of actor dispatch and the
    gateway's shed path — the moments a post-mortem wants the ring."""
    global _dump_last
    rec = _recorder
    d = _dump_dir or os.environ.get(DUMP_ENV)
    if rec is None or not d:
        return None
    now = time.monotonic()
    with _dump_lock:
        if now - _dump_last < DUMP_MIN_INTERVAL_S:
            return None
        _dump_last = now
    path = os.path.join(
        d, f"flight-{rec.pid}-{time.monotonic_ns()}.jsonl")
    try:
        rec.dump_jsonl(path)
    except OSError:
        return None
    if reason:
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps({"flight_dump_reason": reason}) + "\n")
        except OSError:
            pass
    return path


# ------------------------------------------------------ telemetry surface


def telemetry(span_limit: int = 256) -> dict:
    """One node's observability snapshot — what the built-in
    ``ptype.Telemetry`` actor endpoint serves and
    :func:`ptype_tpu.telemetry.cluster_snapshot` aggregates: process
    identity, the metrics registry snapshot (memory watermark gauges
    refreshed per pull), recent series when the health sampler is
    armed (:func:`ptype_tpu.health.series.start` — the history the
    alert rules evaluate), and the most recent spans from the flight
    recorder."""
    from ptype_tpu import metrics as metrics_mod  # lazy: jax import
    from ptype_tpu.health import series as series_mod

    metrics_mod.record_memory_gauges()
    rec = _recorder
    return {
        "pid": os.getpid(),
        "service": rec.service if rec is not None else "",
        "tracing": rec is not None,
        "ts": round(time.time(), 3),
        "metrics": metrics_mod.metrics.snapshot(),
        "series": series_mod.default_snapshot(),
        "spans": rec.to_dicts(limit=span_limit) if rec is not None else [],
        "spans_finished": rec.finished if rec is not None else 0,
    }


def _maybe_enable_from_env() -> None:
    raw = os.environ.get(TRACE_ENV, "")
    if raw and raw not in ("0", "false", "off") and _recorder is None:
        enable(service=raw if raw not in ("1", "true", "on") else "")


_maybe_enable_from_env()
