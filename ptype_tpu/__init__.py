"""ptype_tpu — a TPU-native actor-cluster framework.

Capability surface of edegens/ptype (see /root/reference and SURVEY.md),
re-designed TPU-first:

- ``join(config)``       -> Cluster membership over a coordination service
                            (the JAX-style single-coordinator model rather
                            than embedded raft; ref: cluster/cluster.go:28-84).
- ``Cluster.registry``   -> lease-backed service discovery with watch streams
                            (ref: cluster/registry.go:17-21), where nodes carry
                            TPU device ordinals so the cluster topology *is*
                            the pod mesh.
- ``Cluster.store``      -> replicated KV metadata tier (ref: cluster/store.go)
                            plus a tensor tier (``ptype_tpu.parallel``) whose
                            push/pull lowers to XLA collectives over ICI.
- ``Cluster.new_client`` -> load-balanced sync/async actor RPC with bounded
                            retries and a watch-driven connection balancer
                            (ref: cluster/rpc.go).

The compute path is JAX/XLA/pjit/shard_map/Pallas; the host-side runtime is
pure-Python threads + sockets (the reference's runtime was pure Go + TCP).
"""

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Honor an explicit JAX_PLATFORMS even on hosts whose site hooks
    # override jax_platforms at interpreter startup (env vars lose to
    # config there). No-op once a backend is initialized.
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # noqa: BLE001 — never block import on this
        pass

from ptype_tpu.config import (
    Config,
    ConfigError,
    PlatformConfig,
    config_from_env,
    config_from_file,
)
from ptype_tpu.errors import (
    ClusterError,
    ErrNoClientAvailable,
    ErrNoKey,
    NoClientAvailableError,
    NoKeyError,
    RPCError,
)
from ptype_tpu.registry import Node, Registry
from ptype_tpu.store import KVStore
from ptype_tpu.rpc import Client, ConnConfig, DEFAULT_CONN_CONFIG
from ptype_tpu.actor import ActorServer
from ptype_tpu.cluster import Cluster, join

__version__ = "0.1.0"

__all__ = [
    "ActorServer",
    "Client",
    "Cluster",
    "ClusterError",
    "Config",
    "ConfigError",
    "ConnConfig",
    "DEFAULT_CONN_CONFIG",
    "ErrNoClientAvailable",
    "ErrNoKey",
    "KVStore",
    "Node",
    "NoClientAvailableError",
    "NoKeyError",
    "PlatformConfig",
    "RPCError",
    "Registry",
    "join",
    "config_from_env",
    "config_from_file",
]
