"""Model serving over the actor RPC plane.

The reference's serving story was "register a handler object, join,
serve" (example/calculator/server.go:15-41). This module packages the
generation path the same way: a :class:`GeneratorActor` whose
``Generate`` endpoint runs the compiled KV-cache decode loop, dropping
into an ActorServer next to any other handler. Prompts/outputs ride the
tensor codec as device buffers; callers use the balanced client
(``cluster.new_client("llm").call("Generator.Generate", toks, 16)``).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from ptype_tpu import logs
from ptype_tpu.models import generate as gen
from ptype_tpu.models import transformer as tfm

log = logs.get_logger("serve")


class GeneratorActor:
    """Generation endpoint over a params pytree.

    Serializes requests (one decode loop at a time per actor — the
    single-chip serving model; scale out by registering more actors
    under the same service and letting the balancer spread callers).
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None):
        self.cfg = cfg
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.params = (params if params is not None
                       else jax.jit(lambda r: tfm.init_params(r, cfg))(rng))
        self._lock = threading.Lock()
        self._calls = 0
        self._forward = jax.jit(
            lambda p, t: tfm.forward(p, t, self.cfg))

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0):
        """prompt: (B, S) int32 tokens → (B, max_new_tokens) int32."""
        prompt = jnp.asarray(prompt, jnp.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        with self._lock:
            self._calls += 1
            out = gen.generate(
                self.params, self.cfg, prompt, int(max_new_tokens),
                float(temperature), jax.random.PRNGKey(int(seed)),
            )
        return out

    def Logits(self, tokens):
        """Full-sequence logits (B, S, V) — the eval endpoint."""
        tokens = jnp.asarray(tokens, jnp.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        with self._lock:
            return self._forward(self.params, tokens)

    def Info(self) -> dict:
        return {
            "n_params": tfm.count_params(self.params),
            "d_model": self.cfg.d_model,
            "n_layers": self.cfg.n_layers,
            "vocab_size": self.cfg.vocab_size,
            "max_seq": self.cfg.max_seq,
            "calls": self._calls,
        }
