"""Model serving over the actor RPC plane.

The reference's serving story was "register a handler object, join,
serve" (example/calculator/server.go:15-41). This module packages the
generation path the same way: a :class:`GeneratorActor` whose
``Generate`` endpoint runs the compiled KV-cache decode loop, dropping
into an ActorServer next to any other handler. Prompts/outputs ride the
tensor codec as device buffers; callers use the balanced client
(``cluster.new_client("llm").call("Generator.Generate", toks, 16)``).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from ptype_tpu import logs
from ptype_tpu import metrics as metrics_mod
from ptype_tpu.models import generate as gen
from ptype_tpu.models import transformer as tfm

log = logs.get_logger("serve")


def _norm_prompt(prompt) -> jnp.ndarray:
    """Tokens from the wire → (B, S) int32 (a bare (S,) gets a batch
    dim) — one normalization for every endpoint."""
    prompt = jnp.asarray(prompt, jnp.int32)
    return prompt[None] if prompt.ndim == 1 else prompt


class GeneratorActor:
    """Generation endpoint over a params pytree.

    Serializes requests (one decode loop at a time per actor — the
    single-chip serving model; scale out by registering more actors
    under the same service and letting the balancer spread callers).
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None):
        self.cfg = cfg
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.params = (params if params is not None
                       else jax.jit(lambda r: tfm.init_params(r, cfg))(rng))
        self._lock = threading.Lock()
        self._calls = 0
        #: Load telemetry for the gateway's replica pool: requests that
        #: have entered Generate/Logits and not yet returned. Kept
        #: under its own lock — _lock is HELD for a whole decode loop,
        #: and Info() must answer while one is in flight.
        self._load_lock = threading.Lock()
        self._in_flight = 0
        self._forward = jax.jit(
            lambda p, t: tfm.forward(p, t, self.cfg))

    def _enter_request(self) -> None:
        with self._load_lock:
            self._in_flight += 1

    def _exit_request(self) -> None:
        with self._load_lock:
            self._in_flight -= 1

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        """prompt: (B, S) int32 tokens → (B, max_new_tokens) int32."""
        prompt = _norm_prompt(prompt)
        self._enter_request()
        try:
            with self._lock:
                self._calls += 1
                out = gen.generate(
                    self.params, self.cfg, prompt, int(max_new_tokens),
                    float(temperature), jax.random.PRNGKey(int(seed)),
                    top_k=int(top_k), top_p=float(top_p),
                    stop_token=int(stop_token), pad_token=int(pad_token),
                    repetition_penalty=float(repetition_penalty),
                )
            return out
        finally:
            self._exit_request()

    def Logits(self, tokens):
        """Full-sequence logits (B, S, V) — the eval endpoint."""
        tokens = _norm_prompt(tokens)
        self._enter_request()
        try:
            with self._lock:
                return self._forward(self.params, tokens)
        finally:
            self._exit_request()

    def Info(self) -> dict:
        with self._load_lock:
            in_flight = self._in_flight
        return {
            "n_params": tfm.count_params(self.params),
            "d_model": self.cfg.d_model,
            "n_layers": self.cfg.n_layers,
            "vocab_size": self.cfg.vocab_size,
            "max_seq": self.cfg.max_seq,
            "calls": self._calls,
            # Load telemetry (the gateway's least-loaded signal): the
            # serialized actor's backlog is everyone parked on _lock.
            "in_flight": in_flight,
            "queue_depth": max(0, in_flight - 1),
            # Device HBM watermarks (RSS fallback) — refreshed into the
            # mem.* gauges as a side effect, so the health plane's
            # sampler/alerts see the same numbers the probe reads.
            "memory": metrics_mod.record_memory_gauges(),
        }


def _pow2(n: int) -> int:
    """Smallest power of two >= n (compile-cache bucketing)."""
    return 1 << max(n - 1, 0).bit_length()


class _Pending:
    __slots__ = ("prompt", "max_new", "done", "out", "err")

    def __init__(self, prompt, max_new):
        self.prompt = prompt          # (b_i, S) int32
        self.max_new = max_new
        self.done = threading.Event()
        self.out = None
        self.err = None


class BatchingGeneratorActor(GeneratorActor):
    """GeneratorActor with dynamic request batching.

    Concurrent GREEDY requests that share ``max_new_tokens`` coalesce
    into one decode loop — MIXED prompt lengths included: the batcher
    thread takes the first queued request, drains more for up to
    ``window_ms``, left-pads ragged groups (``generate``'s
    ``prompt_lens`` path — exact greedy parity with solo), and buckets
    both rows and padded length to powers of two so the compile cache
    stays bounded (one program per (B_bucket, S_bucket, max_new)).
    Greedy rows are independent (no cross-row ops in the model), so
    batched results match solo results. Sampled requests (``temperature > 0``) keep
    their exact per-request RNG semantics by running through the solo
    path — batching them would change which fold_in stream each row
    sees.

    This is dynamic batching (triton-style), not continuous batching:
    requests join at loop boundaries, not mid-decode — the right
    cost/benefit at the framework's actor granularity; scale out by
    registering more actors and letting the balancer spread callers.
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None, window_ms: float = 5.0,
                 max_batch: int = 32):
        super().__init__(cfg, params, rng)
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self._queue: list[_Pending] = []
        self._cond = threading.Condition()
        self._closed = False
        self._batches = 0
        self._batched_requests = 0
        self._thread = threading.Thread(
            target=self._worker, name="generate-batcher", daemon=True)
        self._thread.start()

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        if (float(temperature) != 0.0
                or float(repetition_penalty) != 1.0
                or int(stop_token) >= 0):
            # Sampling params / stop masking are per-request semantics:
            # solo path (greedy same-shape requests still batch).
            return super().Generate(prompt, max_new_tokens, temperature,
                                    seed, top_k, top_p, stop_token,
                                    pad_token, repetition_penalty)
        req = _Pending(_norm_prompt(prompt), int(max_new_tokens))
        self._enter_request()
        try:
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                self._queue.append(req)
                self._cond.notify()
            req.done.wait()
            if req.err is not None:
                raise req.err
            return req.out
        finally:
            self._exit_request()

    # ------------------------------------------------------------ worker

    def _worker(self) -> None:
        import time

        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                # Coalesce: first request opens a window; late arrivals
                # within it join this round.
                deadline = time.monotonic() + self.window_s
                rows = sum(p.prompt.shape[0] for p in self._queue)
                while rows < self.max_batch and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    got = self._cond.wait(timeout=remaining)
                    rows = sum(p.prompt.shape[0] for p in self._queue)
                    if not got:
                        break
                # Take only up to max_batch rows — the window loop
                # stops WAITING at the cap, but a burst (or a fat
                # request queued behind others) could have overshot it;
                # decoding past the cap would pad to a bigger bucket
                # and blow the configured device footprint. A single
                # request larger than max_batch runs alone, uncapped —
                # it can't be split without changing its result shape.
                batch, rows = [], 0
                while self._queue:
                    nxt_rows = self._queue[0].prompt.shape[0]
                    if batch and rows + nxt_rows > self.max_batch:
                        break
                    batch.append(self._queue.pop(0))
                    rows += nxt_rows
            self._run_round(batch)

    def _run_round(self, batch: list[_Pending]) -> None:
        """Group by max_new only: MIXED prompt lengths coalesce via the
        ragged left-padded path (exact greedy parity with solo). Rows
        AND padded lengths bucket to powers of two so the compile cache
        stays bounded; lengths themselves are traced, not compiled."""
        import numpy as np

        groups: dict[int, list[_Pending]] = {}
        for p in batch:
            groups.setdefault(p.max_new, []).append(p)
        for max_new, reqs in groups.items():
            try:
                rows = [np.asarray(p.prompt[i])
                        for p in reqs for i in range(p.prompt.shape[0])]
                n = len(rows)
                # Row-pad to the next power of two: one compiled
                # program per bucket instead of per request count.
                # Never capped below n — a clamp would hand XLA the raw
                # request count again (one compile per distinct n, the
                # unbounded cache this padding exists to avoid).
                bucket = _pow2(n)
                rows += [rows[0]] * (bucket - n)
                # One path for uniform AND mixed lengths: always the
                # ragged lens route, so the compile cache is bounded
                # by (B_bucket, S_bucket, max_new) — a uniform fast
                # path would compile one program per distinct length.
                prompts, lens = gen.pad_prompts(rows)
                # Bucket the PADDED length too (further left-pad; lens
                # stay exact, so results are unchanged) — capped so
                # bucketing can never push a group past max_seq that
                # its members individually fit in.
                S = prompts.shape[1]
                S_b = max(S, min(_pow2(S), self.cfg.max_seq - max_new))
                if S_b > S:
                    prompts = jnp.pad(prompts, ((0, 0), (S_b - S, 0)))
                with self._lock:
                    self._calls += len(reqs)
                    self._batches += 1
                    self._batched_requests += len(reqs)
                    out = gen.generate(self.params, self.cfg, prompts,
                                       max_new, 0.0,
                                       jax.random.PRNGKey(0),
                                       prompt_lens=lens)
                row = 0
                for p in reqs:
                    b = p.prompt.shape[0]
                    p.out = out[row:row + b]
                    row += b
                    p.done.set()
            except Exception as e:  # noqa: BLE001 — deliver to callers
                for p in reqs:
                    if not p.done.is_set():
                        p.err = e
                        p.done.set()

    def Info(self) -> dict:
        info = super().Info()
        info["batches"] = self._batches
        info["batched_requests"] = self._batched_requests
        with self._cond:
            # Requests queued for a batching round, not lock-waiters.
            info["queue_depth"] = len(self._queue)
        return info

    def close(self) -> None:
        # Lowercase on purpose: register() exposes only Uppercase
        # methods, so this lifecycle call is NOT remotely reachable.
        with self._cond:
            self._closed = True
            # Claim not-yet-taken requests under the lock: whatever the
            # worker already took it will finish serving (a mid-decode
            # round can outlive any join timeout — don't fail requests
            # a live worker is about to complete).
            stragglers, self._queue = self._queue, []
            self._cond.notify_all()
        for p in stragglers:
            if not p.done.is_set():
                p.err = RuntimeError("generator actor closed")
                p.done.set()
        self._thread.join(timeout=5)


class _RowPending:
    """One prompt ROW in the continuous engine (a (B, S) request is
    split into B independent rows; they re-assemble at the end)."""

    __slots__ = ("prompt", "max_new", "stop_token", "emitted", "done",
                 "err")

    def __init__(self, prompt, max_new, stop_token):
        self.prompt = prompt          # 1-D int32 np array
        self.max_new = max_new
        self.stop_token = stop_token
        self.emitted: list[int] = []
        self.done = threading.Event()
        self.err = None


class ContinuousGeneratorActor(GeneratorActor):
    """TRUE continuous batching: a fixed bank of ``n_slots`` KV-cache
    slots and ONE running decode loop. Requests join a free slot at
    any step boundary (their prompt prefills into the slot while the
    other slots are mid-decode) and leave the moment they finish
    (max_new reached or stop token hit) — no request ever waits for a
    co-batched stranger to finish, the standard TPU serving win over
    the lock-serialized actor (and over BatchingGeneratorActor's
    coalesce-at-start dynamic batching).

    Engine layout (all static shapes — one compiled step program for
    the life of the actor):

    - cache bank ``(L, n_slots, reach, Kh, Dh)``; slots are
      RIGHT-aligned (prompt at columns [0, L), decode grows from L) so
      cache slot == token position,
    - per-slot ``pos``/``token``/``active`` vectors drive
      ``generate.decode_step_ragged`` — every slot attends to its own
      prefix depth,
    - admission prefills via a per-S-bucket compiled program that
      writes K/V straight into the slot (``prefill(last_index=L-1)``:
      right-pad garbage beyond L is never attended and is overwritten
      by decode writes before it could be).

    Greedy requests only (sampling keeps per-request RNG semantics on
    the solo path, same contract as BatchingGeneratorActor); greedy
    rows are independent, so every row matches its solo decode
    exactly. Stop tokens retire a slot EARLY — freed capacity is
    reused by the next queued request mid-flight.
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None, n_slots: int = 8,
                 max_len: int | None = None):
        super().__init__(cfg, params, rng)
        import numpy as np

        from ptype_tpu.models import generate as g

        self.n_slots = int(n_slots)
        reach = min(int(max_len) if max_len else cfg.max_seq,
                    cfg.max_seq)
        self.reach = -(-reach // 128) * 128  # lane-aligned
        bank = g.init_cache(cfg, self.n_slots, max_seq=self.reach)
        self._k, self._v = bank.k, bank.v
        self._tok = np.zeros(self.n_slots, np.int32)
        self._pos = np.zeros(self.n_slots, np.int32)
        self._active = np.zeros(self.n_slots, bool)
        self._slot_state: dict[int, _RowPending] = {}
        self._queue: list[_RowPending] = []
        self._cond = threading.Condition()
        self._closed = False
        self._steps = 0
        self._max_live = 0

        def engine_step(params, k, v, tok, pos, active):
            logits, cache = g.decode_step_ragged(
                params, tok, pos, self.cfg, g.KVCache(k, v))
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, 0)
            return cache.k, cache.v, nxt

        # Donate the bank: the engine must not copy n_slots full-reach
        # caches every step.
        self._engine_step = jax.jit(engine_step, donate_argnums=(1, 2))
        self._prefill_progs: dict[int, object] = {}
        self._thread = threading.Thread(
            target=self._engine, name="generate-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ public

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        import numpy as np

        if (float(temperature) != 0.0
                or float(repetition_penalty) != 1.0):
            # Per-request RNG / penalty state: solo path.
            return super().Generate(prompt, max_new_tokens, temperature,
                                    seed, top_k, top_p, stop_token,
                                    pad_token, repetition_penalty)
        prompt = _norm_prompt(prompt)
        max_new = int(max_new_tokens)
        if max_new <= 0:
            # Nothing to generate: don't occupy a slot (and don't let
            # the engine emit into a zero-width output).
            return jnp.zeros((prompt.shape[0], 0), jnp.int32)
        if prompt.shape[1] + max_new > self.reach:
            raise ValueError(
                f"prompt {prompt.shape[1]} + max_new {max_new} exceeds "
                f"slot reach {self.reach}")
        rows = [_RowPending(np.asarray(prompt[i]), max_new,
                            int(stop_token))
                for i in range(prompt.shape[0])]
        self._enter_request()
        try:
            with self._lock:
                self._calls += 1
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                self._queue.extend(rows)
                self._cond.notify()
            out = np.full((len(rows), max_new), int(pad_token), np.int32)
            for i, r in enumerate(rows):
                r.done.wait()
                if r.err is not None:
                    raise r.err
                out[i, :len(r.emitted)] = r.emitted
            return jnp.asarray(out)
        finally:
            self._exit_request()

    # ------------------------------------------------------------ engine

    def _prefill_prog(self, s_bucket: int):
        """Per-S-bucket compiled slot prefill: fills the slot's K/V
        columns [0, s_bucket) in the bank and returns the first greedy
        token (logits taken at column L-1)."""
        prog = self._prefill_progs.get(s_bucket)
        if prog is not None:
            return prog
        from ptype_tpu.models import generate as g

        def run(params, k, v, prompt, length, slot):
            small = g.init_cache(self.cfg, 1, max_seq=s_bucket)
            logits, kv = g.prefill(params, prompt, self.cfg, small,
                                   last_index=length[None] - 1)
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            k = jax.lax.dynamic_update_slice(k, kv.k,
                                             (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(v, kv.v,
                                             (0, slot, 0, 0, 0))
            return k, v, first

        prog = jax.jit(run, donate_argnums=(1, 2))
        self._prefill_progs[s_bucket] = prog
        return prog

    def _admit(self, slot: int, row: _RowPending) -> None:
        import numpy as np

        L = len(row.prompt)
        s_b = min(max(_pow2(L), 16), self.reach)
        padded = np.zeros((1, s_b), np.int32)
        padded[0, :L] = row.prompt  # RIGHT-aligned slot layout
        self._k, self._v, first = self._prefill_prog(s_b)(
            self.params, self._k, self._v, jnp.asarray(padded),
            jnp.int32(L), jnp.int32(slot))
        first = int(first)
        row.emitted.append(first)
        if (row.max_new == 1
                or (row.stop_token >= 0 and first == row.stop_token)):
            row.done.set()  # done at prefill; slot never activates
            return
        self._slot_state[slot] = row
        self._tok[slot] = first
        self._pos[slot] = L
        self._active[slot] = True

    def _retire(self, slot: int) -> None:
        self._active[slot] = False
        self._slot_state.pop(slot).done.set()

    def _engine(self) -> None:
        """Engine thread wrapper: ANY escape from the loop — clean
        close or an unexpected error (compile failure in a new prefill
        bucket, device OOM) — must fail every pending row, or callers
        blocked in ``done.wait()`` hang forever while the dead actor
        keeps accepting requests."""
        err: Exception | None = None
        try:
            self._engine_loop()
        except Exception as e:  # noqa: BLE001 — delivered to callers
            err = e
            log.warning("generation engine died",
                        kv={"err": repr(e)})
        with self._cond:
            self._closed = True
            stragglers, self._queue = self._queue, []
        for slot in list(self._slot_state):
            stragglers.append(self._slot_state.pop(slot))
        for r in stragglers:
            if not r.done.is_set():
                r.err = err or RuntimeError("generator actor closed")
                r.done.set()

    def _engine_loop(self) -> None:
        import numpy as np

        while True:
            with self._cond:
                while (not self._queue and not self._active.any()
                       and not self._closed):
                    self._cond.wait()
                if self._closed:
                    return
                # Admission: fill free slots at this step boundary —
                # co-batched requests may be mid-decode right now.
                free = [s for s in range(self.n_slots)
                        if not self._active[s]]
                while self._queue and free:
                    self._admit(free.pop(0), self._queue.pop(0))
            if not self._active.any():
                continue
            with self._lock:
                self._steps += 1
                self._max_live = max(self._max_live,
                                     int(self._active.sum()))
                self._k, self._v, nxt = self._engine_step(
                    self.params, self._k, self._v,
                    jnp.asarray(self._tok), jnp.asarray(self._pos),
                    jnp.asarray(self._active))
            nxt_host = np.array(nxt)  # writable copy: _admit writes slots
            self._pos[self._active] += 1
            self._tok = nxt_host
            for slot in list(self._slot_state):
                if not self._active[slot]:
                    continue
                row = self._slot_state[slot]
                t = int(nxt_host[slot])
                row.emitted.append(t)
                if (len(row.emitted) >= row.max_new
                        or (row.stop_token >= 0
                            and t == row.stop_token)):
                    self._retire(slot)  # leaves mid-loop: capacity
                    # freed here is reused at the NEXT step boundary.

    def Info(self) -> dict:
        info = super().Info()
        info["n_slots"] = self.n_slots
        info["engine_steps"] = self._steps
        info["max_live_slots"] = self._max_live
        with self._cond:
            # Rows waiting for a slot — the continuous engine's real
            # backlog (admitted rows are being decoded, not queued).
            info["queue_depth"] = len(self._queue)
        info["live_slots"] = int(self._active.sum())
        return info

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
