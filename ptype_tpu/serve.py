"""Model serving over the actor RPC plane.

The reference's serving story was "register a handler object, join,
serve" (example/calculator/server.go:15-41). This module packages the
generation path the same way: a :class:`GeneratorActor` whose
``Generate`` endpoint runs the compiled KV-cache decode loop, dropping
into an ActorServer next to any other handler. Prompts/outputs ride the
tensor codec as device buffers; callers use the balanced client
(``cluster.new_client("llm").call("Generator.Generate", toks, 16)``).
"""

from __future__ import annotations

import threading

from ptype_tpu import lockcheck

import jax
import jax.numpy as jnp

from ptype_tpu import logs
from ptype_tpu import metrics as metrics_mod
from ptype_tpu.errors import ShedError
from ptype_tpu.models import generate as gen
from ptype_tpu.models import transformer as tfm

log = logs.get_logger("serve")

#: Replica lifecycle states (ISSUE 13): the reconciler's state machine,
#: reported through ``Info()`` so the gateway pool's snapshots and
#: ``obs serve``/``obs scale`` render the same view the reconciler
#: acts on. Numeric codes back the ``serve.lifecycle`` gauge (metric
#: series carry floats; the views map them back).
LIFECYCLES = ("spawning", "warm", "active", "draining", "drained")
LIFECYCLE_CODES = {name: i for i, name in enumerate(LIFECYCLES)}


def _norm_prompt(prompt) -> jnp.ndarray:
    """Tokens from the wire → (B, S) int32 (a bare (S,) gets a batch
    dim) — one normalization for every endpoint."""
    prompt = jnp.asarray(prompt, jnp.int32)
    return prompt[None] if prompt.ndim == 1 else prompt


class GeneratorActor:
    """Generation endpoint over a params pytree.

    Serializes requests (one decode loop at a time per actor — the
    single-chip serving model; scale out by registering more actors
    under the same service and letting the balancer spread callers).
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None):
        self.cfg = cfg
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.params = (params if params is not None
                       else jax.jit(lambda r: tfm.init_params(r, cfg))(rng))
        self._lock = lockcheck.lock("serve.actor.decode")
        self._calls = 0
        #: Load telemetry for the gateway's replica pool: requests that
        #: have entered Generate/Logits and not yet returned. Kept
        #: under its own lock — _lock is HELD for a whole decode loop,
        #: and Info() must answer while one is in flight.
        self._load_lock = lockcheck.lock("serve.actor.load")
        self._in_flight = 0
        #: Replica lifecycle (ISSUE 13): "active" for a bare actor;
        #: the reconciler's ReplicaHost moves it through spawning →
        #: warm → active, and :meth:`begin_drain` to "draining".
        self.lifecycle = "active"
        self._draining = False
        self._forward = jax.jit(
            lambda p, t: tfm.forward(p, t, self.cfg))

    def _enter_request(self) -> None:
        with self._load_lock:
            self._in_flight += 1

    def _exit_request(self) -> None:
        with self._load_lock:
            self._in_flight -= 1

    # ------------------------------------------------------------- drain

    def _check_draining(self) -> None:
        """The drain gate: a draining replica refuses NEW work with a
        typed shed (the gateway's frontdoor re-routes it to a sibling
        — no eviction, no lost request) while already-admitted work
        runs to completion. MUST be called AFTER ``_enter_request``
        (inside its try/finally): a request checked before it is
        counted could pass the gate, get preempted, and be invisible
        to ``drained()`` — the replica would deregister and exit with
        the request still executing, exactly the lost request the
        drain contract forbids."""
        with self._load_lock:
            draining = self._draining
        if draining:
            raise ShedError("replica draining (scale-down in "
                            "progress); route elsewhere",
                            retry_after_s=0.05)

    def begin_drain(self) -> None:
        """Stop admitting; in-flight requests finish normally. The
        reconciler (or operator) polls :meth:`drained` and
        deregisters/exits the replica once it reports True."""
        with self._load_lock:
            self._draining = True
            in_flight = self._in_flight
        self.lifecycle = "draining"
        log.info("replica draining", kv={"in_flight": in_flight})

    def drained(self) -> bool:
        """True once a drain was requested AND no request is in
        flight — the point where deregister-and-exit loses nothing."""
        with self._load_lock:
            return self._draining and self._in_flight == 0

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        """prompt: (B, S) int32 tokens → (B, max_new_tokens) int32."""
        prompt = _norm_prompt(prompt)
        self._enter_request()
        try:
            self._check_draining()
            with self._load_lock:
                self._calls += 1
            with self._lock:
                out = gen.generate(
                    self.params, self.cfg, prompt, int(max_new_tokens),
                    float(temperature), jax.random.PRNGKey(int(seed)),
                    top_k=int(top_k), top_p=float(top_p),
                    stop_token=int(stop_token), pad_token=int(pad_token),
                    repetition_penalty=float(repetition_penalty),
                )
            return out
        finally:
            self._exit_request()

    def Logits(self, tokens):
        """Full-sequence logits (B, S, V) — the eval endpoint."""
        tokens = _norm_prompt(tokens)
        self._enter_request()
        try:
            self._check_draining()
            with self._lock:
                return self._forward(self.params, tokens)
        finally:
            self._exit_request()

    def Info(self) -> dict:
        with self._load_lock:
            in_flight = self._in_flight
            calls = self._calls
        return {
            "n_params": tfm.count_params(self.params),
            "d_model": self.cfg.d_model,
            "n_layers": self.cfg.n_layers,
            "vocab_size": self.cfg.vocab_size,
            "max_seq": self.cfg.max_seq,
            "calls": calls,
            # Lifecycle (ISSUE 13): the reconciler's state machine,
            # surfaced so the gateway pool's snapshots (and `obs
            # serve`) render the same fleet view the reconciler acts
            # on — routing sorts draining replicas last.
            "lifecycle": self.lifecycle,
            # Load telemetry (the gateway's least-loaded signal): the
            # serialized actor's backlog is everyone parked on _lock.
            "in_flight": in_flight,
            "queue_depth": max(0, in_flight - 1),
            # Device HBM watermarks (RSS fallback) — refreshed into the
            # mem.* gauges as a side effect, so the health plane's
            # sampler/alerts see the same numbers the probe reads.
            "memory": metrics_mod.record_memory_gauges(),
        }


def _pow2(n: int) -> int:
    """Smallest power of two >= n (compile-cache bucketing)."""
    return 1 << max(n - 1, 0).bit_length()


class _Pending:
    __slots__ = ("prompt", "max_new", "done", "out", "err")

    def __init__(self, prompt, max_new):
        self.prompt = prompt          # (b_i, S) int32
        self.max_new = max_new
        self.done = threading.Event()
        self.out = None
        self.err = None


class BatchingGeneratorActor(GeneratorActor):
    """GeneratorActor with dynamic request batching.

    Concurrent GREEDY requests that share ``max_new_tokens`` coalesce
    into one decode loop — MIXED prompt lengths included: the batcher
    thread takes the first queued request, drains more for up to
    ``window_ms``, left-pads ragged groups (``generate``'s
    ``prompt_lens`` path — exact greedy parity with solo), and buckets
    both rows and padded length to powers of two so the compile cache
    stays bounded (one program per (B_bucket, S_bucket, max_new)).
    Greedy rows are independent (no cross-row ops in the model), so
    batched results match solo results. Sampled requests (``temperature > 0``) keep
    their exact per-request RNG semantics by running through the solo
    path — batching them would change which fold_in stream each row
    sees.

    This is dynamic batching (triton-style), not continuous batching:
    requests join at loop boundaries, not mid-decode — the right
    cost/benefit at the framework's actor granularity; scale out by
    registering more actors and letting the balancer spread callers.
    """

    def __init__(self, cfg: tfm.TransformerConfig, params=None,
                 rng: jax.Array | None = None, window_ms: float = 5.0,
                 max_batch: int = 32):
        super().__init__(cfg, params, rng)
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self._queue: list[_Pending] = []
        self._cond = lockcheck.condition("serve.batcher")
        self._closed = False
        self._batches = 0
        self._batched_requests = 0
        self._thread = threading.Thread(
            target=self._worker, name="generate-batcher", daemon=True)
        self._thread.start()

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0):
        if (float(temperature) != 0.0
                or float(repetition_penalty) != 1.0
                or int(stop_token) >= 0):
            # Sampling params / stop masking are per-request semantics:
            # solo path (greedy same-shape requests still batch).
            return super().Generate(prompt, max_new_tokens, temperature,
                                    seed, top_k, top_p, stop_token,
                                    pad_token, repetition_penalty)
        req = _Pending(_norm_prompt(prompt), int(max_new_tokens))
        self._enter_request()
        try:
            self._check_draining()
            with self._cond:
                if self._closed:
                    raise RuntimeError("generator actor is closed")
                self._queue.append(req)
                self._cond.notify()
            req.done.wait()
            if req.err is not None:
                raise req.err
            return req.out
        finally:
            self._exit_request()

    # ------------------------------------------------------------ worker

    def _worker(self) -> None:
        import time

        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                # Coalesce: first request opens a window; late arrivals
                # within it join this round.
                deadline = time.monotonic() + self.window_s
                rows = sum(p.prompt.shape[0] for p in self._queue)
                while rows < self.max_batch and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    got = self._cond.wait(timeout=remaining)
                    rows = sum(p.prompt.shape[0] for p in self._queue)
                    if not got:
                        break
                # Take only up to max_batch rows — the window loop
                # stops WAITING at the cap, but a burst (or a fat
                # request queued behind others) could have overshot it;
                # decoding past the cap would pad to a bigger bucket
                # and blow the configured device footprint. A single
                # request larger than max_batch runs alone, uncapped —
                # it can't be split without changing its result shape.
                batch, rows = [], 0
                while self._queue:
                    nxt_rows = self._queue[0].prompt.shape[0]
                    if batch and rows + nxt_rows > self.max_batch:
                        break
                    batch.append(self._queue.pop(0))
                    rows += nxt_rows
            self._run_round(batch)

    def _run_round(self, batch: list[_Pending]) -> None:
        """Group by max_new only: MIXED prompt lengths coalesce via the
        ragged left-padded path (exact greedy parity with solo). Rows
        AND padded lengths bucket to powers of two so the compile cache
        stays bounded; lengths themselves are traced, not compiled."""
        import numpy as np

        groups: dict[int, list[_Pending]] = {}
        for p in batch:
            groups.setdefault(p.max_new, []).append(p)
        for max_new, reqs in groups.items():
            try:
                rows = [np.asarray(p.prompt[i])
                        for p in reqs for i in range(p.prompt.shape[0])]
                n = len(rows)
                # Row-pad to the next power of two: one compiled
                # program per bucket instead of per request count.
                # Never capped below n — a clamp would hand XLA the raw
                # request count again (one compile per distinct n, the
                # unbounded cache this padding exists to avoid).
                bucket = _pow2(n)
                rows += [rows[0]] * (bucket - n)
                # One path for uniform AND mixed lengths: always the
                # ragged lens route, so the compile cache is bounded
                # by (B_bucket, S_bucket, max_new) — a uniform fast
                # path would compile one program per distinct length.
                prompts, lens = gen.pad_prompts(rows)
                # Bucket the PADDED length too (further left-pad; lens
                # stay exact, so results are unchanged) — capped so
                # bucketing can never push a group past max_seq that
                # its members individually fit in.
                S = prompts.shape[1]
                S_b = max(S, min(_pow2(S), self.cfg.max_seq - max_new))
                if S_b > S:
                    prompts = jnp.pad(prompts, ((0, 0), (S_b - S, 0)))
                with self._load_lock:
                    self._calls += len(reqs)
                    self._batches += 1
                    self._batched_requests += len(reqs)
                with self._lock:
                    out = gen.generate(self.params, self.cfg, prompts,
                                       max_new, 0.0,
                                       jax.random.PRNGKey(0),
                                       prompt_lens=lens)
                row = 0
                for p in reqs:
                    b = p.prompt.shape[0]
                    p.out = out[row:row + b]
                    row += b
                    p.done.set()
            except Exception as e:  # noqa: BLE001 — deliver to callers
                for p in reqs:
                    if not p.done.is_set():
                        p.err = e
                        p.done.set()

    def Info(self) -> dict:
        info = super().Info()
        with self._load_lock:
            info["batches"] = self._batches
            info["batched_requests"] = self._batched_requests
        with self._cond:
            # Requests queued for a batching round, not lock-waiters.
            info["queue_depth"] = len(self._queue)
        return info

    def close(self) -> None:
        # Lowercase on purpose: register() exposes only Uppercase
        # methods, so this lifecycle call is NOT remotely reachable.
        with self._cond:
            self._closed = True
            # Claim not-yet-taken requests under the lock: whatever the
            # worker already took it will finish serving (a mid-decode
            # round can outlive any join timeout — don't fail requests
            # a live worker is about to complete).
            stragglers, self._queue = self._queue, []
            self._cond.notify_all()
        for p in stragglers:
            if not p.done.is_set():
                p.err = RuntimeError("generator actor closed")
                p.done.set()
        self._thread.join(timeout=5)


def __getattr__(name: str):
    """Lazy re-exports (PEP 562): the continuous engine now lives in
    :mod:`ptype_tpu.serve_engine` — the paged KV-cache rebase (block
    pool + prefix reuse + chunked prefill; ISSUE 9). Importing it here
    eagerly would cycle (serve_engine subclasses GeneratorActor), and
    serve.py itself must never allocate a full-reach contiguous bank
    again (lint PT009) — ``ContinuousGeneratorActor`` IS the paged
    engine now, same ctor surface (``n_slots``/``max_len``) plus the
    pool knobs (``block_tokens``/``n_blocks``/``prefill_chunk``/
    ``max_queue``/``attn``)."""
    if name in ("ContinuousGeneratorActor", "PagedGeneratorActor"):
        from ptype_tpu.serve_engine.engine import PagedGeneratorActor

        return PagedGeneratorActor
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")
