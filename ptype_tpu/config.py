"""Two-level YAML configuration.

Mirrors the reference contract (cluster/config.go:23-46): a *framework*
config names the service/node and points at a second, platform-level config
file that is resolved **relative to the framework config's directory** and
validated eagerly. In the reference the platform file was an etcd embed
config; here it is a TPU platform config (coordination endpoint + mesh
topology + durability dir), consumed by ``ptype_tpu.cluster.join`` the way
``Join`` consumed ``embed.Config``.

Binaries choose their config via the ``CONFIG`` env var
(ref: example/*/server.go:22 etc.) — see ``config_from_env``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any

import yaml

from ptype_tpu.errors import ConfigError

#: Env var every binary reads its config path from (ref: server.go:22).
CONFIG_ENV_VAR = "CONFIG"


@dataclass
class PlatformConfig:
    """TPU platform topology — the etcd-embed-config equivalent.

    Validated eagerly at load time (ref: config.go:41-43 called
    ``etcdConfig.Validate()``).
    """

    #: Name of this coordination member (ref etcd yaml ``name``).
    name: str = "node"
    #: host:port the coordination service listens on / is reached at.
    #: The first address is the seed (coordinator); the reference kept a
    #: list of client URLs (config.go:17-18).
    coordinator_address: str = "127.0.0.1:7070"
    #: True if this node should host the coordination service (the seed).
    #: Equivalent of bootstrapping the first etcd member vs joining.
    is_coordinator: bool = False
    #: Logical mesh axes, ordered, name -> size. The product must equal the
    #: number of participating devices. e.g. {"data": 8} or
    #: {"data": 2, "fsdp": 2, "model": 2}.
    mesh_axes: dict[str, int] = field(default_factory=dict)
    #: Number of processes (hosts) in the cluster; 1 = single-host.
    num_processes: int = 1
    #: This process's index in [0, num_processes).
    process_id: int = 0
    #: Durability dir for Store snapshots + checkpoints (ref etcd
    #: ``data-dir``): Store contents survive restarts.
    data_dir: str = ""
    #: Lease TTL seconds for registry liveness (ref hardcoded 2s,
    #: registry.go:58-59 — here it is configurable, default preserved).
    lease_ttl: float = 2.0
    #: Dial timeout to the coordination service (ref: 5s, registry.go:37).
    dial_timeout: float = 5.0
    #: fsync the coordination WAL per record. Default off: flush-only
    #: survives coordinator PROCESS death (the elastic story's failure
    #: mode) at microsecond append cost. On = full etcd-raft-log parity
    #: (survives host power loss) at ~ms/append on typical disks.
    wal_fsync: bool = False
    #: host:port of the quorum witness (coord/witness.py). Set on the
    #: seed and every standby to get real partition tolerance: the
    #: primary self-fences when it can reach neither the witness nor a
    #: live WAL follower (the minority side of a partition must refuse
    #: clients rather than serve possibly-superseded state — raft
    #: parity, ref cluster_test.go:47-167), and a standby can only
    #: promote by taking the witness lease. Empty = crash-failover
    #: only (the pre-witness behavior).
    witness_address: str = ""
    #: Witness lease TTL seconds: failover detection floor and the
    #: window a minority primary may serve after the partition starts.
    witness_ttl: float = 3.0
    #: host:port of the JAX distributed coordination service for
    #: multi-controller runs (``num_processes > 1``). Empty = derive
    #: from ``coordinator_address`` host with port+1. ``join`` calls
    #: ``jax.distributed.initialize`` with this (SURVEY §3.1: "Join ≈
    #: jax.distributed.initialize + mesh construction").
    jax_coordinator_address: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ConfigError("platform config: name must be non-empty")
        host, sep, port = self.coordinator_address.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ConfigError(
                f"platform config: coordinator_address must be host:port, "
                f"got {self.coordinator_address!r}"
            )
        if not (0 < int(port) < 65536):
            raise ConfigError(
                f"platform config: coordinator port out of range: {port}"
            )
        for axis, size in self.mesh_axes.items():
            if not isinstance(size, int) or size < 1:
                raise ConfigError(
                    f"platform config: mesh axis {axis!r} must have a "
                    f"positive integer size, got {size!r}"
                )
        if self.num_processes < 1:
            raise ConfigError("platform config: num_processes must be >= 1")
        if not (0 <= self.process_id < self.num_processes):
            raise ConfigError(
                f"platform config: process_id {self.process_id} out of range "
                f"[0, {self.num_processes})"
            )
        if self.lease_ttl <= 0:
            raise ConfigError("platform config: lease_ttl must be > 0")
        if self.dial_timeout <= 0:
            raise ConfigError("platform config: dial_timeout must be > 0")


@dataclass
class Config:
    """Framework config (ref: cluster/config.go:12-21)."""

    service_name: str = ""
    node_name: str = ""
    port: int = 0
    #: Path to the platform YAML, relative to this config's directory
    #: (ref field ``etcd_config_file``, resolution config.go:35-37).
    platform_config_file: str = ""
    #: Seed coordination endpoints for joining an existing cluster
    #: (ref field ``initial_cluster_client_urls``).
    initial_cluster_client_urls: list[str] = field(default_factory=list)
    debug: bool = False

    #: Loaded + validated platform config (ref unexported ``etcdConfig``).
    platform: PlatformConfig = field(default_factory=PlatformConfig)

    def validate(self) -> None:
        if not self.service_name:
            raise ConfigError("config: service_name must be non-empty")
        if not self.node_name:
            raise ConfigError("config: node_name must be non-empty")
        if not (0 <= self.port < 65536):
            raise ConfigError(f"config: port out of range: {self.port}")
        self.platform.validate()


_CONFIG_FIELDS = {
    "service_name", "node_name", "port", "platform_config_file",
    "initial_cluster_client_urls", "debug",
}
_PLATFORM_FIELDS = {
    "name", "coordinator_address", "is_coordinator", "mesh_axes",
    "num_processes", "process_id", "data_dir", "lease_ttl", "dial_timeout",
    "jax_coordinator_address", "wal_fsync", "witness_address",
    "witness_ttl",
}


def _load_yaml(path: str, what: str) -> dict[str, Any]:
    try:
        with open(path, "r") as f:
            raw = yaml.safe_load(f)
    except FileNotFoundError as e:
        raise ConfigError(f"failed to read {what} at {path}: {e}") from e
    except yaml.YAMLError as e:
        raise ConfigError(f"failed to read yaml of {what}: {e}") from e
    if raw is None:
        raw = {}
    if not isinstance(raw, dict):
        raise ConfigError(f"{what} at {path} must be a YAML mapping")
    return raw


def platform_config_from_file(path: str) -> PlatformConfig:
    """Load + validate a platform config (ref: embed.ConfigFromFile)."""
    raw = _load_yaml(path, "platform config")
    unknown = set(raw) - _PLATFORM_FIELDS
    if unknown:
        raise ConfigError(
            f"platform config {path}: unknown fields {sorted(unknown)}"
        )
    try:
        cfg = PlatformConfig(**raw)
    except TypeError as e:
        raise ConfigError(f"platform config {path}: {e}") from e
    cfg.validate()
    return cfg


def config_from_file(path: str) -> Config:
    """Load a framework config and its referenced platform config.

    Contract from the reference (config.go:23-46): missing file, bad YAML,
    missing/invalid platform config each raise a distinct, wrapped error;
    the platform path resolves relative to the framework config's dir.
    """
    raw = _load_yaml(path, "cluster config")
    unknown = set(raw) - _CONFIG_FIELDS
    if unknown:
        raise ConfigError(f"cluster config {path}: unknown fields {sorted(unknown)}")
    try:
        cfg = Config(**raw)
    except TypeError as e:
        raise ConfigError(f"failed to parse cluster config {path}: {e}") from e

    if cfg.platform_config_file:
        platform_path = os.path.join(
            os.path.dirname(path), cfg.platform_config_file
        )
        try:
            cfg.platform = platform_config_from_file(platform_path)
        except ConfigError as e:
            raise ConfigError(
                f"failed to read platform config from "
                f"{cfg.platform_config_file}: {e}"
            ) from e

    cfg.validate()
    return cfg


def config_from_env() -> Config:
    """Load the config named by ``$CONFIG`` (ref: server.go:22)."""
    path = os.environ.get(CONFIG_ENV_VAR, "")
    if not path:
        raise ConfigError(
            f"{CONFIG_ENV_VAR} env var not set; point it at a cluster YAML"
        )
    return config_from_file(path)
