"""Shared jittered exponential backoff for retry/poll loops.

Every retry loop in ``ptype_tpu/`` rides :class:`Backoff` instead of a
bare ``time.sleep`` (lint rule PT002, tools/ptlint): an immediate or
fixed-interval re-fire sends a whole fleet back into a dying node set
in lockstep, which is exactly the thundering herd the reference's
round-robin retry was built to avoid. Jitter decorrelates the herd;
the cap bounds the worst-case reaction time once the peer is back.

The delay sequence is ``min(cap, base * factor**n)``, scaled by a
uniform jitter in ``[1 - jitter, 1]`` — "full jitter below the
ceiling", so the configured cap is also the hard upper bound of any
single sleep.
"""

from __future__ import annotations

import random
import time


class Backoff:
    """Iterative jittered exponential backoff.

    ``base=cap`` degenerates to a constant-with-jitter poll interval —
    the right shape for bounded-deadline barrier polls (checkpoint.py).
    A seeded ``rng`` makes the delay sequence reproducible (chaos
    drills); the default draws from the module-level PRNG.
    """

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: random.Random | None = None):
        if base <= 0 or cap < base:
            raise ValueError(f"Backoff: need 0 < base <= cap, "
                             f"got base={base} cap={cap}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"Backoff: jitter must be in [0, 1], "
                             f"got {jitter}")
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rng = rng
        self._n = 0

    def next_delay(self) -> float:
        """The next delay in the sequence (advances the attempt count)."""
        raw = min(self.cap, self.base * (self.factor ** self._n))
        self._n += 1
        if not self.jitter:
            return raw
        rnd = self._rng.random() if self._rng is not None else random.random()
        return raw * (1.0 - self.jitter * rnd)

    def sleep(self, delay: float | None = None) -> float:
        """Sleep for ``delay`` (default: the next delay in the
        sequence); returns the time slept."""
        d = self.next_delay() if delay is None else delay
        time.sleep(d)
        return d

    def wait(self, event, delay: float | None = None) -> bool:
        """Backoff-shaped ``event.wait``: park for the next delay (or
        ``delay``) unless the event fires first; returns its state —
        the close-aware variant of :meth:`sleep` for monitor loops."""
        d = self.next_delay() if delay is None else delay
        return event.wait(d)

    def reset(self) -> None:
        """Back to the base delay (call after a success so the next
        failure burst starts fast again)."""
        self._n = 0
