"""Paged attention — Pallas TPU decode kernel over a block table.

The paged engine's default decode path is an XLA gather
(models/generate._paged_attention_gather): it materializes the whole
gathered (B, nb·bt, Kh, Dh) K/V per layer per step in HBM before the
einsum reads it. This kernel skips the materialization: the block
table rides **scalar prefetch** (``pltpu.PrefetchScalarGridSpec``), so
each grid step's BlockSpec index map dials the bank block the table
names and Mosaic DMAs exactly that (block_tokens, Dh) tile into VMEM —
online softmax across the table dimension, flash-style, with
per-sequence position masking from the prefetched ``pos``.

Layout contract (the (8, 128) Mosaic tiling rule, same machinery as
ops/flash_attention):

- the bank layer is transposed to head-major ``(Kh, n_blocks,
  block_tokens, Dh)`` before the call so the K/V block tile is
  ``(block_tokens, Dh)`` — the NAIVE untransposed layout would put a
  squeezed size-1 head dim second-to-last in the block, the exact
  BENCH_r02 failure class the flash LSE output hit;
- queries are grouped ``(B, Kh, G, Dh)`` (GQA-native: the kernel never
  repeats K/V heads) and the G dim rides whole in the block;
- :func:`check_tpu_lowering` validates every declared BlockSpec
  against the rule AND the kernel's own alignment requirements
  (``block_tokens % 8``, ``Dh % 128``) WITHOUT a TPU — the serving
  engine only enables ``attn="kernel"`` on a real TPU backend when
  this returns clean; CPU tests run ``interpret=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
#: f32 Mosaic tile: (sublanes, lanes).
SUBLANES = 8
LANES = 128


def _paged_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, bt: int, scale: float):
    """Grid (B, Kh, nb): one (sequence, kv head, table slot) tile per
    step; the innermost table dim streams blocks through the online-
    softmax scratch. ``tables_ref``/``pos_ref`` are scalar-prefetched:
    the k/v index maps already consumed ``tables`` to pick the bank
    block, the body reads ``pos`` for masking."""
    b, i = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)
    G = q_ref.shape[2]
    SG = m_scr.shape[0]  # sublane-padded query-group rows

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    limit = pos_ref[b] + 1        # attend positions < limit
    base = i * bt                 # table slot i holds these positions

    @pl.when(base < limit)
    def _compute():
        q = q_ref[0, 0]           # (G, Dh)
        if SG > G:                # pad rows to the f32 sublane tile;
            #                       pad rows accumulate garbage that
            #                       _finalize never reads back.
            q = jnp.concatenate(
                [q, jnp.zeros((SG - G, q.shape[1]), q.dtype)], axis=0)
        k = k_ref[0, 0]           # (bt, Dh)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (SG, bt)
        col = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < limit, s, NEG_INF)
        m_prev = m_scr[...][:, :1]
        l_prev = l_scr[...][:, :1]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nb - 1)
    def _finalize():
        l = l_scr[...][:G, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...][:G] / l_safe).astype(o_ref.dtype)


def paged_attention(q, kc, vc, tables, pos,
                    interpret: bool | None = None) -> jax.Array:
    """Decode attention through block tables, one bank layer at a time.

    q: (B, 1, H, Dh) this step's queries; kc/vc: (n_blocks,
    block_tokens, Kh, Dh) bank layer; tables: (B, nb) int32 position-
    ordered block ids; pos: (B,) current token position (attend
    ``<= pos``). Returns (B, 1, H, Dh), matching the gather path.
    ``interpret`` defaults to True on CPU backends (the test tier)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    B, _, H, Dh = q.shape
    n_blocks, bt, Kh, _ = kc.shape
    nb = tables.shape[1]
    if H % Kh:
        raise ValueError(f"paged_attention: n_heads {H} must divide "
                         f"by kv_heads {Kh}")
    if not interpret:
        bad = check_tpu_lowering(B, H, Kh, Dh, n_blocks, bt, nb)
        if bad:
            raise ValueError(
                "paged_attention: config does not meet the TPU "
                "lowering contract: " + "; ".join(bad))
    G = H // Kh
    SG = max(G, SUBLANES)
    scale = 1.0 / (Dh ** 0.5)
    qh = q[:, 0].reshape(B, Kh, G, Dh)     # head h -> (h // G, h % G)
    kt = jnp.transpose(kc, (2, 0, 1, 3))   # (Kh, n_blocks, bt, Dh)
    vt = jnp.transpose(vc, (2, 0, 1, 3))

    qmap = lambda b, kh, i, tr, pr: (b, kh, 0, 0)             # noqa: E731,E501
    kvmap = lambda b, kh, i, tr, pr: (kh, tr[b, i], 0, 0)     # noqa: E731,E501
    shp = _spec_shapes(G, bt, Dh)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Kh, nb),
        in_specs=[
            pl.BlockSpec(shp["q"], qmap),
            pl.BlockSpec(shp["kv"], kvmap),
            pl.BlockSpec(shp["kv"], kvmap),
        ],
        out_specs=pl.BlockSpec(shp["q"], qmap),
        scratch_shapes=[
            pltpu.VMEM((SG, LANES), jnp.float32),  # m (lane-repl)
            pltpu.VMEM((SG, LANES), jnp.float32),  # l (lane-repl)
            pltpu.VMEM((SG, Dh), jnp.float32),     # acc
        ],
    )
    o = pl.pallas_call(
        functools.partial(_paged_kernel, bt=bt, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kh, G, Dh), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), pos.astype(jnp.int32), qh, kt, vt)
    return o.reshape(B, 1, H, Dh)


def _spec_shapes(G: int, bt: int, Dh: int) -> dict:
    """The BlockSpec block shapes the pallas_call declares — the ONE
    source the call and :func:`lowering_block_shapes` share (the
    flash-kernel pattern: a layout change can't pass the CPU-tier
    check while failing on Mosaic)."""
    return {"q": (1, 1, G, Dh), "kv": (1, 1, bt, Dh)}


def lowering_block_shapes(B: int, H: int, Kh: int, Dh: int,
                          n_blocks: int, bt: int, nb: int
                          ) -> list[tuple[str, tuple, tuple]]:
    """Every (operand, block shape, array shape) the kernel declares
    at these dimensions — the Mosaic tiling contract as data,
    checkable WITHOUT a TPU (see ops/flash_attention for the failure
    class this guards against)."""
    G = H // Kh
    shp = _spec_shapes(G, bt, Dh)
    q4 = (B, Kh, G, Dh)
    kv4 = (Kh, n_blocks, bt, Dh)
    return [("q", shp["q"], q4), ("k", shp["kv"], kv4),
            ("v", shp["kv"], kv4), ("o", shp["q"], q4)]


def check_tpu_lowering(B: int, H: int, Kh: int, Dh: int,
                       n_blocks: int, bt: int, nb: int) -> list[str]:
    """Violations of the Mosaic (8, 128) divisibility rule across
    :func:`lowering_block_shapes`, plus the kernel's own alignment
    requirements — empty when the kernel lowers. The serving engine
    consults this before enabling ``attn="kernel"`` on a TPU backend;
    tests assert it over the bench/serving configs on CPU."""
    bad = []
    for name, block, array in lowering_block_shapes(
            B, H, Kh, Dh, n_blocks, bt, nb):
        for dim, want in ((-2, SUBLANES), (-1, LANES)):
            if block[dim] % want and block[dim] != array[dim]:
                bad.append(
                    f"{name}: block {block} dim {dim} = {block[dim]} "
                    f"not divisible by {want} nor equal to array "
                    f"{array}")
    # The kernel's VMEM tiles must be NATIVELY aligned — block == array
    # on a misaligned dim satisfies the BlockSpec rule but leaves the
    # (bt, Dh) compute tile unfillable on the MXU/VPU grid.
    if bt % SUBLANES:
        bad.append(f"block_tokens {bt} not divisible by {SUBLANES} "
                   f"(sublane tile)")
    if Dh % LANES:
        bad.append(f"head_dim {Dh} not divisible by {LANES} "
                   f"(lane tile)")
    if H % Kh:
        bad.append(f"n_heads {H} not divisible by kv_heads {Kh}")
    return bad
