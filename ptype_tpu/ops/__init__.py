"""Pallas TPU kernels for the hot ops (SURVEY.md §7: "performance-
critical kernels go to Pallas")."""

from ptype_tpu.ops.flash_attention import (check_tpu_lowering,
                                           flash_attention,
                                           lowering_block_shapes,
                                           make_flash_attn_fn)

__all__ = ["check_tpu_lowering", "flash_attention",
           "lowering_block_shapes", "make_flash_attn_fn"]
