"""Pallas TPU kernels for the hot ops (SURVEY.md §7: "performance-
critical kernels go to Pallas")."""

from ptype_tpu.ops.flash_attention import flash_attention, make_flash_attn_fn

__all__ = ["flash_attention", "make_flash_attn_fn"]
