"""Flash attention — Pallas TPU kernel, forward + backward.

The MFU target (≥30% at 125M on a v5e-8, BASELINE.json) dies on a
materialized S×S score matrix: at S=1024 the dense path writes
B·H·S² f32 to HBM each direction. This kernel keeps scores in VMEM
block-by-block (online softmax forward; recomputed-block backward), so
attention is HBM-linear in S — the standard flash decomposition, written
for the MXU:

- block_q × block_k score tiles (one MXU pass each), bf16 matmuls with
  f32 accumulators (``preferred_element_type``);
- **K/V streamed through the grid** — the kv-block index is the
  innermost grid dim and online-softmax state lives in VMEM scratch
  that persists across it, so VMEM use is O(block), independent of S
  (the llama preset's S=8192 fits);
- **native GQA**: K/V keep their ``n_kv_heads`` heads; the kernel index
  maps route query head h to kv head h // group — no ``jnp.repeat``
  materializing the H-head tensors GQA exists to avoid;
- causal masking at block granularity; blocks strictly above the
  diagonal are skipped (``pl.when`` — fetched but never computed);
- forward emits the log-sum-exp rows as a residual; backward is two
  kernels (dq; dk/dv accumulated over query heads of the group) using
  the delta = rowsum(dO∘O) trick, wired as a ``jax.custom_vjp``;
- ``interpret=True`` on CPU so the numerics tier of the test suite
  (SURVEY.md §4) validates the kernel without a TPU.

Layout: public API takes (B, S, H, Dh) like models/transformer._attention
and transposes to (B, H, S, Dh) internally (head-major keeps each
(b, h) program's K/V stream contiguous in HBM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_cpu() -> bool:
    return jax.default_backend() == "cpu"


# ------------------------------------------------------------------ forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                scale: float, causal: bool, want_lse: bool):
    """Grid (B, H, num_q, num_k): one (q block, k block) tile per step.

    ``rest`` is ``(lse_ref if want_lse, m_scr, l_scr, acc_scr)`` — the
    LSE output exists only when the caller wants the residual (the
    primal path declares just ``o``, skipping ~B·H·S·LANES f32 of
    discarded HBM writes). Scratch (m, l, acc) carries the online
    softmax across the innermost kv dim; m/l are lane-replicated
    (block_q, block_k) f32 so every op stays 2-D and tile-aligned.
    """
    if want_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref, (m_scr, l_scr, acc_scr) = None, rest
    qi, kb = pl.program_id(2), pl.program_id(3)
    num_k = pl.num_programs(3)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: K blocks strictly above this Q block's diagonal contribute
    # nothing — skip the MXU work entirely.
    live = (kb * block_k < (qi + 1) * block_q) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[...][:, :1]  # row stats live in lane 0
        l_prev = l_scr[...][:, :1]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)  # (block_q, 1)
        p = jnp.exp(s - m_next)
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == num_k - 1)
    def _finalize():
        m = m_scr[...][:, :1]  # (block_q, 1) — stay 2-D for Mosaic
        l = l_scr[...][:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            # LSE rows are lane-replicated to the 128-lane tile (the row
            # layout (B, H, S) puts a squeezed size-1 head dim second-to-
            # last in the block, violating Mosaic's (8, 128) tiling rule
            # — the round-2 TPU lowering failure).
            lse_ref[...] = jnp.broadcast_to(m + jnp.log(l_safe),
                                            lse_ref.shape)


#: Lane width of the f32 Mosaic tile. Row residuals (LSE, delta) are
#: stored lane-replicated at this width so their block's last two dims
#: are (block_q, 128)-aligned.
LANES = 128


def _spec_shapes(block_q: int, block_k: int, Dh: int) -> dict:
    """The three BlockSpec block shapes every kernel in this module
    declares — the ONE source both the pallas_calls and the lowering
    checker (:func:`lowering_block_shapes`) consume, so a layout
    change can't pass the CPU-tier check while failing on Mosaic."""
    return {"q": (None, None, block_q, Dh),
            "kv": (None, None, block_k, Dh),
            "row": (None, None, block_q, LANES)}


def _fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
         interpret: bool, want_lse: bool = True):
    """q: (B, H, S, Dh); k, v: (B, K, S, Dh) → (o like q, lse
    (B, H, S, LANES) lane-replicated | None when ``not want_lse``)."""
    B, H, S, Dh = q.shape
    K = k.shape[1]
    group = H // K
    scale = 1.0 / (Dh ** 0.5)
    grid = (B, H, S // block_q, S // block_k)

    qmap = lambda b, h, qi, kb: (b, h, qi, 0)           # noqa: E731
    kvmap = lambda b, h, qi, kb: (b, h // group, kb, 0)  # noqa: E731
    shp = _spec_shapes(block_q, block_k, Dh)

    out_specs = [pl.BlockSpec(shp["q"], qmap)]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if want_lse:
        out_specs.append(pl.BlockSpec(shp["row"], qmap))
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, S, LANES), jnp.float32))

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          want_lse=want_lse),
        grid=grid,
        in_specs=[
            pl.BlockSpec(shp["q"], qmap),
            pl.BlockSpec(shp["kv"], kvmap),
            pl.BlockSpec(shp["kv"], kvmap),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # m (lane-repl)
            pltpu.VMEM((block_q, LANES), jnp.float32),  # l (lane-repl)
            pltpu.VMEM((block_q, Dh), jnp.float32),     # acc
        ],
        interpret=interpret,
    )(q, k, v)
    return (out[0], out[1]) if want_lse else (out[0], None)


# ----------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale: float, causal: bool):
    """dq for one q block, streaming k/v blocks through the grid."""
    qi, kb = pl.program_id(2), pl.program_id(3)
    num_k = pl.num_programs(3)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (kb * block_k < (qi + 1) * block_q) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, :1]      # lane-replicated → (block_q, 1)
        delta = delta_ref[...][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # normalized probs via lse
        dp = jax.lax.dot_general(
            do, v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_k - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool):
    """dk/dv for one kv block of one KV HEAD: grid (B, K, num_k, G,
    num_q) streams every query block of every query head in the GQA
    group through scratch accumulators — the group-sum GQA's backward
    needs, without materializing repeated K/V."""
    ki = pl.program_id(2)
    g, qb = pl.program_id(3), pl.program_id(4)
    num_g, num_q = pl.num_programs(3), pl.num_programs(4)
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]

    @pl.when((g == 0) & (qb == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = ((qb + 1) * block_q > ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        do = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, :1]      # lane-replicated → (block_q, 1)
        delta = delta_ref[...][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # (block_q, block_k)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((g == num_g - 1) & (qb == num_q - 1))
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


# ------------------------------------------------------------- custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, block_q, block_k, causal, interpret):
    o, _ = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal,
                interpret=interpret, want_lse=False)
    return o


def _flash_fwd(q, k, v, block_q, block_k, causal, interpret):
    o, lse = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal,
                  interpret=interpret)
    # Save one lane of the replicated LSE: the (B, H, S, LANES) layout is
    # a kernel-I/O constraint, not information — holding all 128 lanes
    # from forward to backward would inflate saved-activation HBM 128×.
    return o, (q, k, v, o, lse[..., :1])


def _flash_bwd(block_q, block_k, causal, interpret, res, do):
    q, k, v, o, lse1 = res
    B, H, S, Dh = q.shape
    K = k.shape[1]
    group = H // K
    scale = 1.0 / (Dh ** 0.5)
    # Row residuals ride the same lane-replicated (B, H, S, LANES)
    # layout the forward emits for LSE (Mosaic (8, 128) tiling rule);
    # both are broadcast transiently here, inside the backward.
    lse = jnp.broadcast_to(lse1, (B, H, S, LANES))
    delta = jnp.broadcast_to(
        jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                axis=-1, keepdims=True),
        (B, H, S, LANES))

    qmap = lambda b, h, qi, kb: (b, h, qi, 0)            # noqa: E731
    kvmap = lambda b, h, qi, kb: (b, h // group, kb, 0)  # noqa: E731
    shp = _spec_shapes(block_q, block_k, Dh)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid=(B, H, S // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec(shp["q"], qmap),
            pl.BlockSpec(shp["kv"], kvmap),
            pl.BlockSpec(shp["kv"], kvmap),
            pl.BlockSpec(shp["q"], qmap),
            pl.BlockSpec(shp["row"], qmap),
            pl.BlockSpec(shp["row"], qmap),
        ],
        out_specs=pl.BlockSpec(shp["q"], qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, Dh), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid walks (kv head, k block) then the group's query heads
    # and q blocks innermost, accumulating the GQA group-sum in scratch.
    bmap_q = lambda b, kk, ki, g, qb: (b, kk * group + g, qb, 0)  # noqa: E731,E501
    bmap_kv = lambda b, kk, ki, g, qb: (b, kk, ki, 0)             # noqa: E731,E501

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal),
        grid=(B, K, S // block_k, group, S // block_q),
        in_specs=[
            pl.BlockSpec(shp["q"], bmap_q),
            pl.BlockSpec(shp["kv"], bmap_kv),
            pl.BlockSpec(shp["kv"], bmap_kv),
            pl.BlockSpec(shp["q"], bmap_q),
            pl.BlockSpec(shp["row"], bmap_q),
            pl.BlockSpec(shp["row"], bmap_q),
        ],
        out_specs=[
            pl.BlockSpec(shp["kv"], bmap_kv),
            pl.BlockSpec(shp["kv"], bmap_kv),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, Dh), jnp.float32),
            pltpu.VMEM((block_k, Dh), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------------------- public API


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: bool | None = None) -> jax.Array:
    """Flash attention over (B, S, H, Dh) tensors (transformer layout).

    GQA-native: K/V may carry fewer heads (``H % K == 0``); query head h
    reads kv head ``h // (H/K)`` inside the kernel — no repeat. Sequence
    length must divide by the (clamped) block sizes; pad upstream —
    presets use power-of-two seq. ``interpret`` defaults to True on CPU
    backends so tests validate the kernel without a TPU.

    Default blocks are large (1024×1024): the grid-step count, not
    VMEM, bounds throughput at these shapes — measured on v5e at the
    125M train config (B=16/S=1024, dots-remat): 1024×1024 0.457 MFU,
    512×1024 0.442, 512×512 0.422, 256×512 0.402, and 128×128 blocks
    3.3× slower than 512+ (per-step overhead dominates the tiny
    (128, Dh) MXU tiles). VMEM stays O(block): ~2.5 MB/program at
    Dh=128 even at S=8192.
    """
    if interpret is None:
        interpret = _on_cpu()
    B, S, H, Dh = q.shape
    K = k.shape[2]
    if H % K:
        raise ValueError(f"flash_attention: n_heads {H} must divide by "
                         f"n_kv_heads {K}")
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(
            f"flash_attention: seq {S} must divide by blocks "
            f"({block_q}, {block_k})"
        )
    to_hmajor = lambda x: jnp.swapaxes(x, 1, 2)  # noqa: E731
    o = _flash(to_hmajor(q), to_hmajor(k), to_hmajor(v),
               block_q, block_k, causal, interpret)
    return jnp.swapaxes(o, 1, 2)


def lowering_block_shapes(B: int, H: int, S: int, Dh: int,
                          K: int | None = None,
                          block_q: int = 1024, block_k: int = 1024
                          ) -> list[tuple[str, tuple, tuple]]:
    """Every (operand name, block shape, array shape) the three
    pallas_calls declare at these dimensions — the Mosaic tiling
    contract as data, checkable WITHOUT a TPU.

    The TPU lowering requires the last two dims of every block shape
    to divide by (8, 128) or equal the array's. BENCH_r02 recorded the
    violation this guards against: the LSE output was once declared
    (B, H, S) with a squeezed size-1 dim second-to-last in the block —
    the fix stores row residuals lane-replicated at (block_q, LANES).
    ``tests/test_flash_lowering.py`` asserts the rule over every entry
    here for the bench/train configs, so a spec regression fails tier-1
    on CPU instead of the next TPU session."""
    K = K or H
    block_q, block_k = min(block_q, S), min(block_k, S)
    q4 = (B, H, S, Dh)
    kv4 = (B, K, S, Dh)
    lse4 = (B, H, S, LANES)
    # The block shapes come from the SAME _spec_shapes the
    # pallas_calls consume (None = squeezed dim → size 1 here).
    shp = {k: tuple(1 if d is None else d for d in v)
           for k, v in _spec_shapes(block_q, block_k, Dh).items()}
    qb, kvb, lseb = shp["q"], shp["kv"], shp["row"]
    out = []
    # forward: q, k, v → o (+ lse when the residual is wanted)
    out += [("fwd/q", qb, q4), ("fwd/k", kvb, kv4), ("fwd/v", kvb, kv4),
            ("fwd/o", qb, q4), ("fwd/lse", lseb, lse4)]
    # backward dq: q, k, v, do, lse, delta → dq
    out += [("dq/q", qb, q4), ("dq/k", kvb, kv4), ("dq/v", kvb, kv4),
            ("dq/do", qb, q4), ("dq/lse", lseb, lse4),
            ("dq/delta", lseb, lse4), ("dq/dq", qb, q4)]
    # backward dk/dv: same operands → dk, dv
    out += [("dkv/q", qb, q4), ("dkv/k", kvb, kv4), ("dkv/v", kvb, kv4),
            ("dkv/do", qb, q4), ("dkv/lse", lseb, lse4),
            ("dkv/delta", lseb, lse4), ("dkv/dk", kvb, kv4),
            ("dkv/dv", kvb, kv4)]
    return out


def check_tpu_lowering(B: int, H: int, S: int, Dh: int,
                       K: int | None = None,
                       block_q: int = 1024, block_k: int = 1024
                       ) -> list[str]:
    """Violations of the Mosaic (8, 128) divisibility rule across
    :func:`lowering_block_shapes` — empty when the kernels lower."""
    bad = []
    for name, block, array in lowering_block_shapes(
            B, H, S, Dh, K, block_q, block_k):
        for dim, want in ((-2, 8), (-1, 128)):
            if block[dim] % want and block[dim] != array[dim]:
                bad.append(
                    f"{name}: block {block} dim {dim} = {block[dim]} "
                    f"not divisible by {want} nor equal to array "
                    f"{array}")
    return bad


def make_flash_attn_fn(block_q: int = 1024, block_k: int = 1024):
    """attn_fn(q, k, v, cfg) for models/transformer.forward — the
    ``attn_impl="flash"`` lowering. Shapes the kernel can't tile
    (seq not divisible by the clamped block sizes — e.g. odd decode
    lengths) fall back to the dense XLA path so "flash" is always safe
    to set globally."""

    def attn_fn(q, k, v, cfg):
        S = q.shape[1]
        bq, bk = min(block_q, S), min(block_k, S)
        if S % bq or S % bk:
            from ptype_tpu.models.transformer import _attention

            return _attention(q, k, v, cfg)
        return flash_attention(q, k, v, causal=cfg.causal,
                               block_q=bq, block_k=bk)

    return attn_fn
