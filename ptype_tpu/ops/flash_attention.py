"""Flash attention — Pallas TPU kernel, forward + backward.

The MFU target (≥30% at 125M on a v5e-8, BASELINE.json) dies on a
materialized S×S score matrix: at S=1024 the dense path writes
B·H·S² f32 to HBM each direction. This kernel keeps scores in VMEM
block-by-block (online softmax forward; recomputed-block backward), so
attention is HBM-linear in S — the standard flash decomposition, written
for the MXU:

- block_q × block_k = 128×128 score tiles (one MXU pass each),
  bf16 matmuls with f32 accumulators (``preferred_element_type``);
- causal masking at block granularity: K-blocks strictly above the
  diagonal are skipped by loop bounds (not masked — never computed);
- backward = two kernels (dq, and dk/dv) over recomputed score blocks
  plus the delta = rowsum(dO∘O) trick, wired as a ``jax.custom_vjp``;
- ``interpret=True`` on CPU so the numerics tier of the test suite
  (SURVEY.md §4) validates the kernel without a TPU.

Layout: public API takes (B, S, H, Dh) like models/transformer._attention
and transposes to (B, H, S, Dh) internally (head-major keeps each
(b, h) program's K/V contiguous in HBM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _on_cpu() -> bool:
    return jax.default_backend() == "cpu"


# ------------------------------------------------------------------ forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
                causal: bool):
    """One (b·h, q_block) program: online softmax over K blocks."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[0]
    seq_k = k_ref.shape[0]

    q = q_ref[...]  # (block_q, Dh)
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_k = seq_k // block_k
    if causal:
        # K blocks past this Q block's diagonal are never computed.
        hi = jnp.minimum((qi + 1) * block_q + block_k - 1, seq_k) // block_k
    else:
        hi = num_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, hi, body, (m, l, acc))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def _fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
         interpret: bool):
    """q,k,v: (B, H, S, Dh) → o same shape."""
    B, H, S, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    grid = (B * H, S // block_q)

    def qmap(bh, qi):
        return (bh // H, bh % H, qi, 0)

    def kvmap(bh, qi):
        return (bh // H, bh % H, 0, 0)

    return pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, Dh), qmap),
            pl.BlockSpec((None, None, S, Dh), kvmap),
            pl.BlockSpec((None, None, S, Dh), kvmap),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, Dh), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


# ----------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, dq_ref, *,
                   block_k: int, scale: float, causal: bool):
    """Recompute score blocks; dq for one (b·h, q_block)."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[0]
    seq_k = k_ref.shape[0]

    q = q_ref[...]
    o = o_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    delta = jnp.sum(o * do, axis=1)  # (block_q,)

    # Recover the softmax normalizer: flash stores only o, so we redo the
    # m/l pass (cheap relative to the matmuls, keeps HBM linear).
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    num_k = seq_k // block_k
    hi = (jnp.minimum((qi + 1) * block_q + block_k - 1, seq_k) // block_k
          if causal else num_k)

    def stats(kb, carry):
        m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new[:, None]),
                                             axis=1)
        return m_new, l

    m, l = jax.lax.fori_loop(0, hi, stats, (m, l))

    def body(kb, dq):
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - m[:, None]) / l[:, None]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, hi, body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _fwd_stats_kernel(q_ref, k_ref, m_ref, l_ref, *, block_k: int,
                      scale: float, causal: bool):
    """Row max/normalizer per q block (forward replay, stats only)."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[0]
    seq_k = k_ref.shape[0]
    q = q_ref[...]
    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    num_k = seq_k // block_k
    hi = (jnp.minimum((qi + 1) * block_q + block_k - 1, seq_k) // block_k
          if causal else num_k)

    def body(kb, carry):
        m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new[:, None]),
                                             axis=1)
        return m_new, l

    m, l = jax.lax.fori_loop(0, hi, body, (m, l))
    m_ref[...] = m[None, :]
    l_ref[...] = l[None, :]


def _bwd_dkv_kernel_v2(m_ref, l_ref, q_ref, k_ref, v_ref, do_ref, delta_ref,
                       dk_ref, dv_ref, *, block_q: int, scale: float,
                       causal: bool):
    """dk/dv for one (b·h, k_block), given per-row m/l/delta."""
    ki = pl.program_id(1)
    block_k = k_ref.shape[0]
    seq_q = q_ref.shape[0]
    k = k_ref[...]
    v = v_ref[...]
    num_q = seq_q // block_q
    lo = (ki * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qb * block_q, block_q), :]
        do = do_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        m = m_ref[0, pl.ds(qb * block_q, block_q)]
        l = l_ref[0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q)]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - m[:, None]) / l[:, None]  # (block_q, block_k)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        lo, num_q, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)),
    )
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------- custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, block_q, block_k, causal, interpret):
    return _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal,
                interpret=interpret)


def _flash_fwd(q, k, v, block_q, block_k, causal, interpret):
    o = _fwd(q, k, v, block_q=block_q, block_k=block_k, causal=causal,
             interpret=interpret)
    return o, (q, k, v, o)


def _flash_bwd(block_q, block_k, causal, interpret, res, do):
    q, k, v, o = res
    B, H, S, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    grid = (B * H, S // block_q)

    def qmap(bh, qi):
        return (bh // H, bh % H, qi, 0)

    def fullmap(bh, qi):
        return (bh // H, bh % H, 0, 0)

    # Row stats (m, l) via a stats-only forward replay.
    m, l = pl.pallas_call(
        functools.partial(_fwd_stats_kernel, block_k=block_k, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, Dh), qmap),
            pl.BlockSpec((None, None, S, Dh), fullmap),
        ],
        out_specs=[
            pl.BlockSpec((None, None, 1, block_q), lambda bh, qi: (bh // H, bh % H, 0, qi)),
            pl.BlockSpec((None, None, 1, block_q), lambda bh, qi: (bh // H, bh % H, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, 1, S), jnp.float32),
            jax.ShapeDtypeStruct((B, H, 1, S), jnp.float32),
        ],
        interpret=interpret,
    )(q, k)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)[:, :, None, :]  # (B, H, 1, S)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, Dh), qmap),
            pl.BlockSpec((None, None, S, Dh), fullmap),
            pl.BlockSpec((None, None, S, Dh), fullmap),
            pl.BlockSpec((None, None, block_q, Dh), qmap),
            pl.BlockSpec((None, None, block_q, Dh), qmap),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, Dh), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, o, do)

    grid_k = (B * H, S // block_k)

    def kmap(bh, ki):
        return (bh // H, bh % H, ki, 0)

    def full_rowmap(bh, ki):
        return (bh // H, bh % H, 0, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_v2, block_q=block_q, scale=scale,
                          causal=causal),
        grid=grid_k,
        in_specs=[
            pl.BlockSpec((None, None, 1, S), full_rowmap),  # m
            pl.BlockSpec((None, None, 1, S), full_rowmap),  # l
            pl.BlockSpec((None, None, S, Dh), full_rowmap),  # q (full)
            pl.BlockSpec((None, None, block_k, Dh), kmap),
            pl.BlockSpec((None, None, block_k, Dh), kmap),
            pl.BlockSpec((None, None, S, Dh), full_rowmap),  # do (full)
            pl.BlockSpec((None, None, 1, S), full_rowmap),  # delta
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, Dh), kmap),
            pl.BlockSpec((None, None, block_k, Dh), kmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(m, l, q, k, v, do, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------------------- public API


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Flash attention over (B, S, H, Dh) tensors (transformer layout).

    GQA-aware: K/V may carry fewer heads (repeated up to H). Sequence
    length must divide by the block sizes (pad upstream — presets use
    power-of-two seq). ``interpret`` defaults to True on CPU backends so
    tests validate the kernel without a TPU.
    """
    if interpret is None:
        interpret = _on_cpu()
    B, S, H, Dh = q.shape
    K = k.shape[2]
    if K != H:
        k = jnp.repeat(k, H // K, axis=2)
        v = jnp.repeat(v, H // K, axis=2)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(
            f"flash_attention: seq {S} must divide by blocks "
            f"({block_q}, {block_k})"
        )
    to_hmajor = lambda x: jnp.swapaxes(x, 1, 2)  # noqa: E731
    o = _flash(to_hmajor(q), to_hmajor(k), to_hmajor(v),
               block_q, block_k, causal, interpret)
    return jnp.swapaxes(o, 1, 2)


def make_flash_attn_fn(block_q: int = 128, block_k: int = 128):
    """attn_fn(q, k, v, cfg) for models/transformer.forward — the
    ``attn_impl="flash"`` lowering."""

    def attn_fn(q, k, v, cfg):
        return flash_attention(q, k, v, causal=cfg.causal,
                               block_q=block_q, block_k=block_k)

    return attn_fn
