"""ctypes loader for the native wire library (native/ptype_wire.cpp).

The reference's whole runtime was compiled (Go); here the Python host
runtime gets a native transport tier: writev frame sends (no
concatenation copy) and GIL-free exact reads. Loading is best-effort —
``available()`` is False and callers fall back to pure Python when the
.so is absent and cannot be built (no compiler, read-only tree).

Build explicitly with ``make native``; ``load()`` also attempts a
one-time on-demand g++ build the first time it runs from a writable
checkout.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ptype_tpu import logs

log = logs.get_logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "..", "native",
                    "ptype_wire.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_ptype_wire.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-o", _SO, src],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed", kv={"err": str(e)})
        return False


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use if possible.

    Lock-free fast path after the first call: every wire send/recv goes
    through here, so the steady state must not serialize all connection
    threads on a module lock (the one-time build inside the lock is
    acceptable: callers fall back to Python until it finishes)."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.debug("native load failed", kv={"err": str(e)})
            return None
        lib.ptype_send_frame.restype = ctypes.c_int
        lib.ptype_send_frame.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
        ]
        lib.ptype_recv_exact.restype = ctypes.c_int64
        lib.ptype_recv_exact.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.ptype_crc32c.restype = ctypes.c_uint32
        lib.ptype_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        _lib = lib
        log.debug("native wire library loaded", kv={"path": _SO})
        return _lib


def available() -> bool:
    return load() is not None


def send_frame(sock, header: bytes, blobs: list[bytes]) -> bool:
    """writev the frame [len][header][blobs...]; False → caller falls
    back to Python sends. Socket must be blocking."""
    lib = load()
    if lib is None:
        return False
    n = len(blobs)
    if n > 1000:
        # The C side caps its iovec array; very-many-leaf payloads take
        # the Python sendall fallback rather than erroring.
        return False
    blob_arr = (ctypes.c_char_p * n)(*blobs) if n else None
    len_arr = (ctypes.c_uint64 * n)(*[len(b) for b in blobs]) if n else None
    rc = lib.ptype_send_frame(
        sock.fileno(), header, len(header),
        ctypes.cast(blob_arr, ctypes.POINTER(ctypes.c_char_p)),
        ctypes.cast(len_arr, ctypes.POINTER(ctypes.c_uint64)),
        n,
    )
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc))
    return True


def recv_exact_into(sock, buf: memoryview) -> int:
    """Read exactly len(buf) bytes into a writable buffer without the
    GIL. Returns bytes read (== len(buf)), 0 on clean EOF; raises
    ConnectionError on mid-frame EOF, OSError on socket error. Falls
    back by raising NotImplementedError when the library is absent."""
    lib = load()
    if lib is None:
        raise NotImplementedError("native wire library unavailable")
    addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
    rc = lib.ptype_recv_exact(sock.fileno(), addr, len(buf))
    if rc == -1000000:
        raise ConnectionError("EOF mid-frame")
    if rc < 0:
        raise OSError(int(-rc), os.strerror(int(-rc)))
    return int(rc)


def crc32c(data: bytes) -> int:
    lib = load()
    if lib is None:
        raise NotImplementedError("native wire library unavailable")
    return int(lib.ptype_crc32c(data, len(data)))
