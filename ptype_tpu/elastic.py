"""Failure detection + elastic recovery: stop → reshard → resume.

The reference's elasticity was per-call: lease expiry (2 s TTL,
registry.go:58-83) dropped dead nodes from the balancer, and round-robin
retries routed around them (SURVEY.md §5 "Failure detection"). XLA
collectives cannot fail over per call — the device set is baked into the
compiled program — so the TPU-native contract is the one SURVEY.md §7
names the hardest: separate "membership event" from "mesh rebuild", and
on member loss run checkpoint → rebuild mesh over the survivors →
restore (resharded) → resume.

- :class:`FailureDetector` — watches a service's registry stream
  (snapshot-then-deltas) and reports joins/losses. Liveness is lease
  expiry, exactly the reference mechanism.
- :class:`ElasticTrainer` — wraps the GSPMD trainer: ``step`` raises
  :class:`MembershipChanged` when the detector saw churn; ``recover()``
  checkpoints the current state, rebuilds the mesh from the surviving
  workers' device ordinals, restores into the new shardings, and
  recompiles the step. The Checkpointer's reshard-on-restore does the
  heavy lifting (checkpoint.py).
- :class:`ElasticZeroTrainer` — the LIVE half of the story (ISSUE 17):
  wraps the store-DP ZeRO trainer, and on churn ``recover()`` reshards
  the resident sharded state in memory
  (``StoreDPTrainer.reshard`` → ``ZeroState.reshard`` — strip old tail
  pads, re-pad, re-place, moments bit-preserved) instead of the
  checkpoint round trip. A reconciler-ordered trainer scale event
  (``ProcessLauncher(kind="custom")`` launching/stopping trainer
  replicas) reaches the same path: the scaled replica set changes the
  registry membership, the detector reports it, and the next ``step``
  raises :class:`MembershipChanged`.
- Fault injection for tests/drills: ``inject_loss`` revokes a
  registration the way a SIGKILL would (lease revoke ⇒ immediate
  expiry), so the whole path is exercisable in-process.
"""

from __future__ import annotations

import threading

from ptype_tpu import lockcheck
from typing import Callable

import jax

from ptype_tpu import chaos, logs
from ptype_tpu.errors import ClusterError
from ptype_tpu.parallel.topology import DATA_AXIS

log = logs.get_logger("elastic")


class MembershipChanged(Exception):
    """Raised by ElasticTrainer.step when the worker set changed; call
    ``recover()`` and retry the step."""

    def __init__(self, lost: list[str], joined: list[str]):
        super().__init__(f"lost={lost} joined={joined}")
        self.lost = lost
        self.joined = joined


class FailureDetector:
    """Watch a service; track node churn (lease-expiry liveness)."""

    def __init__(self, registry, service_name: str,
                 on_change: Callable | None = None):
        self.service_name = service_name
        self._watch = registry.watch_service(service_name)
        self._on_change = on_change
        self._lock = lockcheck.lock("elastic.fd")
        self._current: dict[str, object] = {}
        self._lost: list[str] = []
        self._joined: list[str] = []
        self._seeded = threading.Event()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"fd-{service_name}", daemon=True)
        self._thread.start()

    @staticmethod
    def _key(node) -> str:
        return f"{node.address}:{node.port}"

    def _run(self) -> None:
        for nodes in self._watch:
            if self._closed.is_set():
                break
            new = {self._key(n): n for n in nodes}
            with self._lock:
                if self._seeded.is_set():
                    lost = sorted(set(self._current) - set(new))
                    joined = sorted(set(new) - set(self._current))
                    self._lost.extend(lost)
                    self._joined.extend(joined)
                else:
                    lost, joined = [], []
                self._current = new
            self._seeded.set()
            if (lost or joined) and self._on_change is not None:
                self._on_change(lost, joined)
            if lost or joined:
                log.info("membership change",
                         kv={"service": self.service_name,
                             "lost": lost, "joined": joined})

    def wait_seeded(self, timeout: float = 5.0) -> None:
        if not self._seeded.wait(timeout):
            raise ClusterError(
                f"FailureDetector: no initial snapshot for "
                f"{self.service_name!r} within {timeout}s")

    def current(self) -> list:
        with self._lock:
            return sorted(self._current.values(),
                          key=lambda n: (n.process_id, n.address, n.port))

    def drain_changes(self) -> tuple[list[str], list[str]]:
        """(lost, joined) since the last drain; empties the buffers."""
        with self._lock:
            lost, self._lost = self._lost, []
            joined, self._joined = self._joined, []
        return lost, joined

    @property
    def changed(self) -> bool:
        with self._lock:
            return bool(self._lost or self._joined)

    def close(self, timeout: float = 5.0) -> None:
        """Stop watching and JOIN the watch thread (bounded): a test
        tearing a detector down must not leak a thread that wakes
        later against a dead registry."""
        self._closed.set()
        self._watch.cancel()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            log.warning("failure detector thread did not exit in time",
                        kv={"service": self.service_name,
                            "timeout": timeout})


def inject_loss(registration) -> None:
    """Fault injection: kill a member the lease way (revoke ⇒ expiry ⇒
    watch event), the in-process stand-in for SIGKILLing its host."""
    registration.close(revoke=True)


def devices_from_nodes(detector: FailureDetector) -> list:
    """The survivor device set: every ordinal the registered workers
    advertise, resolved against this process's visible devices."""
    nodes = detector.current()
    ordinals: list[int] = []
    for n in nodes:
        ordinals.extend(n.device_ordinals)
    if not ordinals:
        raise ClusterError(
            "elastic: surviving workers advertise no devices")
    by_id = {d.id: d for d in jax.devices()}
    missing = [o for o in ordinals if o not in by_id]
    if missing:
        raise ClusterError(
            f"elastic: registry devices {missing} not visible")
    return [by_id[o] for o in sorted(set(ordinals))]


class ElasticTrainer:
    """GSPMD trainer + failure detector + checkpoint-reshard-resume."""

    def __init__(self, cfg, registry, service_name: str, ckpt_dir: str,
                 mesh_axis: str = DATA_AXIS, optimizer=None,
                 rng: jax.Array | None = None):
        from ptype_tpu.checkpoint import Checkpointer
        from ptype_tpu.train.trainer import default_optimizer

        self.cfg = cfg
        self.mesh_axis = mesh_axis
        self.optimizer = optimizer or default_optimizer()
        self.detector = FailureDetector(registry, service_name)
        self.detector.wait_seeded()
        self.ckpt = Checkpointer(ckpt_dir)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._build(fresh=True)

    # ------------------------------------------------------------ build

    def _devices_from_nodes(self) -> list:
        return devices_from_nodes(self.detector)

    def _build(self, fresh: bool) -> None:
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.train import trainer as tr

        devices = self._devices_from_nodes()
        self.mesh = build_mesh({self.mesh_axis: len(devices)},
                               devices=devices)
        self._step_fn = tr.make_train_step(self.cfg, self.mesh,
                                           self.optimizer)
        if fresh:
            self.state, self.state_shardings = tr.init_state(
                self._rng, self.cfg, self.mesh, self.optimizer)
        else:
            # Shardings for the NEW mesh; state restored by recover().
            self.state_shardings = tr._state_shardings(
                self.mesh, self.cfg, self.optimizer)
        log.info("elastic mesh built",
                 kv={"devices": len(devices), "fresh": fresh})

    # ------------------------------------------------------------- step

    def step(self, batch: dict):
        if self.detector.changed:
            lost, joined = self.detector.drain_changes()
            raise MembershipChanged(lost, joined)
        from jax.sharding import NamedSharding

        from ptype_tpu.models import transformer as tfm

        axis_sizes = {n: int(self.mesh.shape[n])
                      for n in self.mesh.axis_names}
        sh = NamedSharding(self.mesh, tfm.batch_spec(axis_sizes))
        batch = {k: jax.device_put(v, sh) for k, v in batch.items()}
        self.state, out = self._step_fn(self.state, batch)
        return out

    def checkpoint(self) -> int:
        step = int(self.state.step)
        self.ckpt.save(step, self.state)
        return step

    def recover(self) -> dict:
        """Checkpoint-restore-reshard after MembershipChanged.

        The state in memory is still valid (single-controller: the
        controller survived; what died is worker capacity), so we save
        it, rebuild the mesh over the survivors, and restore into the
        new shardings.

        Churn does not stop arriving just because a recover is in
        flight: a second ``MembershipChanged``'s worth of events
        landing mid-rebuild re-runs the drain-and-rebuild loop over
        the LATEST survivor set instead of crashing out of (or
        resuming onto) a half-current mesh."""
        saved = self.checkpoint()
        old = self.mesh.devices.size
        for _ in range(5):
            self.detector.drain_changes()
            self._build(fresh=False)
            self.state = self.ckpt.restore(
                self.state, step=saved, shardings=self.state_shardings)
            if not self.detector.changed:
                break
            log.info("membership changed again mid-recover; rebuilding",
                     kv={"step": saved})
        # Still churning after the bounded drain: return with the
        # latest consistent build — the next step() raises
        # MembershipChanged and the caller recovers again.
        chaos.note_ok("elastic.recover", str(saved))
        log.info("elastic recovery complete",
                 kv={"step": saved, "old_devices": old,
                     "new_devices": self.mesh.devices.size})
        return {"restored_step": saved, "devices": self.mesh.devices.size}


class ElasticZeroTrainer:
    """Store-DP ZeRO trainer + failure detector + LIVE reshard-resume.

    The elastic story WITHOUT the restore round trip: the resident
    state is already sharded over the flat bucket space
    (parallel/zero.py), so a survivor-set change is a re-pad +
    re-place (``StoreDPTrainer.reshard``), not a checkpoint cycle.
    ``step`` raises :class:`MembershipChanged` on churn; ``recover``
    reshards onto the survivor mesh and the caller simply retries the
    step — the step budget lost to a replica kill is the ONE step that
    raised, nothing more.
    """

    def __init__(self, cfg, registry, service_name: str,
                 mesh_axis: str = DATA_AXIS, zero=2,
                 rng: jax.Array | None = None, wire=None,
                 zero_hparams=None):
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.tensorstore import TensorStore
        from ptype_tpu.train.store_dp import StoreDPTrainer

        self.cfg = cfg
        self.mesh_axis = mesh_axis
        self.detector = FailureDetector(registry, service_name)
        self.detector.wait_seeded()
        devices = devices_from_nodes(self.detector)
        mesh = build_mesh({mesh_axis: len(devices)}, devices=devices)
        store = TensorStore(mesh, axis=mesh_axis, wire=wire)
        self.trainer = StoreDPTrainer(cfg, store, rng=rng, zero=zero,
                                      zero_hparams=zero_hparams)
        log.info("elastic zero trainer up",
                 kv={"devices": len(devices),
                     "zero_stage": self.trainer.zero_stage})

    # ------------------------------------------------------------- step

    def step(self, batch: dict) -> dict:
        if self.detector.changed:
            lost, joined = self.detector.drain_changes()
            raise MembershipChanged(lost, joined)
        return self.trainer.step(batch)

    def params(self) -> dict:
        return self.trainer.params()

    # ---------------------------------------------------------- recover

    def recover(self, reshard_retries: int = 3) -> dict:
        """Live reshard after :class:`MembershipChanged`.

        Same bounded drain-and-rebuild loop as
        :meth:`ElasticTrainer.recover` (churn keeps arriving mid-
        recover), but the rebuild is ``trainer.reshard`` — in memory,
        atomic, moments bit-preserved. The reshard itself retries
        ``reshard_retries`` times: a mid-reshard fault (the
        ``train.reshard`` chaos seam's drop) raises with the OLD
        plan/mesh/arrays fully intact, so the retry runs against
        consistent state."""
        old = int(self.trainer.n_workers)
        from ptype_tpu.parallel.mesh import build_mesh

        info: dict = {}
        for _ in range(5):
            self.detector.drain_changes()
            devices = devices_from_nodes(self.detector)
            mesh = build_mesh({self.mesh_axis: len(devices)},
                              devices=devices)
            last: Exception | None = None
            for attempt in range(reshard_retries):
                try:
                    info = self.trainer.reshard(mesh, self.mesh_axis)
                    last = None
                    break
                except ClusterError as e:
                    last = e
                    log.warning("live reshard attempt failed; retrying",
                                kv={"attempt": attempt,
                                    "error": str(e)})
            if last is not None:
                raise last
            if not self.detector.changed:
                break
        chaos.note_ok("elastic.recover",
                      f"{old}->{self.trainer.n_workers}")
        log.info("elastic live reshard complete",
                 kv={"old_devices": old,
                     "new_devices": self.trainer.n_workers,
                     "reshard_ms": info.get("reshard_ms")})
        return {"old_devices": old,
                "new_devices": self.trainer.n_workers, **info}
