"""Load-balanced actor RPC client.

Capability parity with the reference's L4 (cluster/rpc.go): sync ``call``,
async ``go``, a watch-driven connection balancer with debounced rebalancing,
deterministic hash-based node selection, atomic round-robin, bounded
retries, mesh mode (``max_connections=0``), and a connection-error stream.

Documented reference bugs are **fixed, not replicated** (SURVEY.md §2):
- ``withRetry`` looped forever / never retried (rpc.go:107-116) — here a
  call makes exactly ``retries + 1`` attempts, each on the next
  round-robin connection so retries land on different nodes when possible;
- ``Client.Go`` delivered the first completion without retrying
  (rpc.go:90-95) — here the async path shares the sync retry loop;
- membership changes re-dialed every node (rpc.go:226-244) — here healthy
  connections to surviving nodes are reused;
- ``selectNodes`` could pick duplicates (rpc.go:252-264) — here collisions
  linear-probe to distinct nodes.
"""

from __future__ import annotations

import contextvars
import json
import queue
import struct
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass

from ptype_tpu import actor as actor_mod
from ptype_tpu import chaos, codec, logs, retry, trace
from ptype_tpu.coord import wire
from ptype_tpu.errors import (NoClientAvailableError, RemoteError, RPCError,
                              ShedError)
from ptype_tpu.registry import Node, NodeWatch, Registry

log = logs.get_logger("rpc")

_LEN = struct.Struct(">I")


@dataclass
class ConnConfig:
    """Ref: rpc.go:19-38, defaults preserved."""

    #: Max connections to unique nodes; 0 = full mesh.
    max_connections: int = 3
    #: Timeout for the initial node set to appear.
    initial_node_timeout: float = 5.0
    #: Quiet window for batching membership churn.
    debounce_time: float = 3.0
    #: Extra attempts after the first (total attempts = retries + 1),
    #: possibly on different nodes.
    retries: int = 2
    #: Per-attempt call timeout (the reference relied on TCP semantics;
    #: an explicit bound is strictly safer). None = no timeout.
    call_timeout: float | None = 60.0
    #: TCP connect timeout per dial (was hard-coded in ``_Conn``).
    dial_timeout: float = 5.0
    #: Jittered exponential backoff between retry attempts: an
    #: immediate re-fire lands the whole retry budget inside the same
    #: dying node set before the balancer can notice. First retry
    #: waits ~``retry_backoff_base``, growing to ``retry_backoff_cap``.
    retry_backoff_base: float = 0.05
    retry_backoff_cap: float = 1.0
    #: Pluggable connection picker: ``picker(healthy_conns) -> conn``
    #: replaces blind round-robin in the balancer's ``get()`` — the
    #: seam the inference gateway uses to inject its load-aware choice
    #: (``gateway.least_loaded_picker``). Returning None (or anything
    #: not in the list, or raising) falls back to round-robin, so a
    #: picker can never strand a caller.
    picker: object = None


DEFAULT_CONN_CONFIG = ConnConfig()


def fnv32a(data: str) -> int:
    """FNV-1a 32-bit (ref: rpc.go:266-270 used hash/fnv New32a)."""
    h = 0x811C9DC5
    for byte in data.encode("utf-8"):
        h ^= byte
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


# ---------------------------------------------------------------- transport


class _Conn:
    """One multiplexed connection to an actor server."""

    def __init__(self, node: Node, dial_timeout: float = 5.0):
        self.node = node
        import socket

        self._sock = socket.create_connection(
            (node.address, node.port), timeout=dial_timeout
        )
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 1
        self._id_lock = threading.Lock()
        self._closed = threading.Event()
        threading.Thread(
            target=self._read_loop,
            name=f"rpc-conn-{node.address}:{node.port}",
            daemon=True,
        ).start()

    @property
    def healthy(self) -> bool:
        return not self._closed.is_set()

    def _read_loop(self) -> None:
        while not self._closed.is_set():
            try:
                msg = wire.recv_msg(self._sock)
                blob = b""
                if msg.get("result_len"):
                    blob = wire._recv_exact(self._sock, msg["result_len"])
            except (wire.WireError, OSError):
                break
            f = chaos.hit("rpc.recv")
            if f is not None and f.action == "delay":
                f.sleep()  # slow reply: the caller's timeout clock runs
            with self._pending_lock:
                fut = self._pending.pop(msg.get("id"), None)
            if fut is None:
                continue
            if msg.get("ok"):
                try:
                    fut.set_result(codec.decode(blob))
                except Exception as e:  # noqa: BLE001
                    fut.set_exception(RPCError(f"decode failed: {e}"))
            elif msg.get("shed"):
                # Typed admission refusal (gateway overload): keep the
                # retry hint and the ShedError type across the wire —
                # callers back off, the retry loop must NOT re-fire.
                fut.set_exception(ShedError(
                    msg.get("error", "request shed"),
                    retry_after_s=msg.get("retry_after_s", 1.0)))
            else:
                fut.set_exception(
                    RemoteError(msg.get("error", "remote error"),
                                msg.get("traceback", ""))
                )
        self.close()

    def call_async(self, method: str, args) -> Future:
        if self._closed.is_set():
            fut: Future = Future()
            fut.set_exception(RPCError(f"connection to {self.node.address}:"
                                       f"{self.node.port} closed"))
            return fut
        f = chaos.hit("rpc.send", method)
        if f is not None:
            injected = self._inject_send_fault(f)
            if injected is not None:
                return injected
        parts = codec.encode_parts(args)
        args_len = sum(len(p) for p in parts)
        with self._id_lock:
            req_id = self._next_id
            self._next_id += 1
        fut = Future()
        fut.req_id = req_id  # lets the caller forget() a timed-out call
        with self._pending_lock:
            self._pending[req_id] = fut
        frame = {"id": req_id, "method": method, "args_len": args_len}
        tp = trace.traceparent()
        if tp is not None:
            # Trace context rides the request frame: the server attaches
            # it around dispatch so the handler's spans join this trace.
            frame["tp"] = tp
        header = json.dumps(frame, separators=(",", ":")).encode("utf-8")
        try:
            with self._send_lock:
                # One writev (native) / one sendall: the header frame and
                # every tensor blob go out without a concatenation copy.
                from ptype_tpu import native

                if not native.send_frame(self._sock, header, parts):
                    self._sock.sendall(
                        _LEN.pack(len(header)) + header + b"".join(parts)
                    )
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            self.close()
            fut.set_exception(RPCError(f"send failed: {e}"))
        return fut

    def _inject_send_fault(self, f) -> Future | None:
        """Apply an armed ``rpc.send`` fault. ``delay`` returns None
        (the real send proceeds afterwards); ``drop`` and ``truncate``
        kill the connection and return a failed Future — the retry
        path's next attempt lands on another node."""
        if f.action == "delay":
            f.sleep()
            return None
        if f.action == "truncate":
            # A length header promising more bytes than ever arrive:
            # the server reader blocks on the remainder until the close
            # lands, then surfaces the standard truncated-frame
            # WireError — the same failure a mid-send crash produces.
            try:
                with self._send_lock:
                    self._sock.sendall(_LEN.pack(1 << 20) + b"chaos")
            except OSError:
                pass
        self.close()
        fut: Future = Future()
        fut.set_exception(RPCError(
            f"chaos: {f.action} on send to "
            f"{self.node.address}:{self.node.port}"))
        return fut

    def forget(self, fut: Future) -> None:
        """Drop a timed-out call's pending entry so abandoned futures are
        not resolved by late replies and _pending cannot grow unboundedly."""
        req_id = getattr(fut, "req_id", None)
        if req_id is not None:
            with self._pending_lock:
                self._pending.pop(req_id, None)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        import socket

        try:
            # shutdown() wakes the read loop parked in recv(2); close()
            # alone leaves it wedged until process exit.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._pending_lock:
            pending, self._pending = list(self._pending.values()), {}
        for fut in pending:
            if not fut.done():
                fut.set_exception(RPCError("connection closed"))


class _LocalConn:
    """Zero-copy same-process dispatch — no socket, no serialization.

    This is the TPU-native fast path: device-resident ``jax.Array`` args
    pass by reference, avoiding the device→host→device round-trip the
    north star calls out.
    """

    def __init__(self, node: Node, server: actor_mod.ActorServer):
        self.node = node
        self._server = server

    @property
    def healthy(self) -> bool:
        return self._server.serving

    def call_async(self, method: str, args) -> Future:
        fut: Future = Future()
        # Carry the caller's trace context into the dispatch thread —
        # contextvars do not flow into new threads on their own, and
        # the local fast path must stitch like the wire path does.
        ctx = contextvars.copy_context()

        def run():
            try:
                fut.set_result(
                    ctx.run(self._server.dispatch, method, args))
            except ShedError as e:
                fut.set_exception(e)  # typed: parity with the wire path
            except Exception as e:  # noqa: BLE001
                import traceback

                fut.set_exception(RemoteError(f"{type(e).__name__}: {e}",
                                              traceback.format_exc()))

        threading.Thread(target=run, daemon=True).start()
        return fut

    def forget(self, fut: Future) -> None:
        pass

    def close(self) -> None:
        pass


def _dial(node: Node, dial_timeout: float = 5.0):
    f = chaos.hit("rpc.dial", f"{node.address}:{node.port}")
    if f is not None:
        if f.action == "delay":
            f.sleep()
        elif f.action in ("drop", "timeout"):
            raise OSError(
                f"chaos: dial {f.action} to {node.address}:{node.port}")
    local = actor_mod.lookup_local(node.address, node.port)
    if local is not None:
        return _LocalConn(node, local)
    return _Conn(node, dial_timeout)


# ---------------------------------------------------------------- balancer


class _ConnectionBalancer:
    """Watches the registry and maintains <= max_connections dialed peers
    (ref: rpc.go:126-297, with the §2 fixes)."""

    def __init__(self, local_addr: str, service_name: str, registry: Registry,
                 cfg: ConnConfig):
        self.cfg = cfg
        self.local_addr = local_addr
        self.service_name = service_name
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._lock = threading.RLock()
        self._conns: list = []
        #: Latest node snapshot, kept so ``get()`` can kick a redial of
        #: dead connections without waiting for membership churn (a
        #: single-node service whose one connection drops would
        #: otherwise stay dead until the next watch event).
        self._last_nodes: list[Node] = []
        self._redialing = threading.Event()
        self._closed = threading.Event()
        self.err_queue: "queue.Queue[Exception]" = queue.Queue(maxsize=1024)
        self.conns_updated = threading.Event()

        self._watch: NodeWatch = registry.watch_service(service_name)
        # The registry pushes an immediate initial snapshot which may be
        # empty (service not registered yet — a normal startup race); keep
        # absorbing snapshots until one has nodes or the timeout passes
        # (ref contract: InitialNodeTimeout, rpc.go:155-160).
        deadline = time.monotonic() + cfg.initial_node_timeout
        initial: list[Node] | None = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            got = self._watch.get(timeout=remaining)
            if got:
                initial = got
                break
        if not initial:
            self._watch.cancel()
            raise NoClientAvailableError(
                f"no nodes for service {service_name!r} within "
                f"{cfg.initial_node_timeout}s"
            )
        self._handle_new_nodes(initial)
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name=f"balancer-{service_name}",
            daemon=True,
        )
        self._watch_thread.start()

    # -- selection ---------------------------------------------------------

    def _select_nodes(self, nodes: list[Node]) -> list[Node]:
        """Deterministic hash-based subset (ref: rpc.go:252-270), with
        linear probing instead of the reference's duplicate-prone rehash."""
        n = len(nodes)
        want = n if self.cfg.max_connections == 0 else min(
            self.cfg.max_connections, n
        )
        nodes = sorted(nodes, key=lambda nd: (nd.address, nd.port))
        chosen: list[Node] = []
        taken: set[int] = set()
        for i in range(want):
            idx = fnv32a(self.local_addr + str(i)) % n
            while idx in taken:
                idx = (idx + 1) % n
            taken.add(idx)
            chosen.append(nodes[idx])
        return chosen

    def _handle_new_nodes(self, nodes: list[Node]) -> None:
        selected = self._select_nodes(nodes) if nodes else []
        with self._lock:
            self._last_nodes = list(nodes)
            existing = {
                (c.node.address, c.node.port): c
                for c in self._conns
            }
        # Dial OUTSIDE the lock: a blackholed peer costs a full
        # dial_timeout, and holding the balancer lock across it would
        # stall every concurrent get() even though healthy connections
        # exist.
        new_conns = []
        dialed = []
        for node in selected:
            key = (node.address, node.port)
            cur = existing.get(key)
            if cur is not None and cur.healthy:
                new_conns.append(cur)  # reuse, don't re-dial (§2 fix)
                continue
            try:
                conn = _dial(node, self.cfg.dial_timeout)
            except OSError as e:
                self._report(RPCError(
                    f"dial {node.address}:{node.port} failed: {e}"
                ))
                continue
            dialed.append(conn)
            new_conns.append(conn)
        with self._lock:
            if self._closed.is_set():
                # close() raced the dials: never install into a closed
                # balancer (leaked sockets + reader threads).
                for c in dialed:
                    c.close()
                return
            keep = {id(c) for c in new_conns}
            for c in self._conns:
                if id(c) not in keep:
                    c.close()
            self._conns = new_conns
        self.conns_updated.set()
        log.debug("rebalanced connections",
                  kv={"service": self.service_name, "conns": len(selected)})

    def _watch_loop(self) -> None:
        """Debounce churn: after a change arrives, keep absorbing updates
        until the quiet window passes, then apply the latest snapshot
        (ref: rpc.go:197-224; coalescing contract rpc_test.go:371-387)."""
        while not self._closed.is_set():
            latest = self._watch.get(timeout=0.5)
            if latest is None:
                if self._watch.closed:
                    return
                continue
            deadline = time.monotonic() + self.cfg.debounce_time
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                more = self._watch.get(timeout=remaining)
                if more is not None:
                    latest = more
            if self._closed.is_set():
                return
            self._handle_new_nodes(latest)

    # -- access ------------------------------------------------------------

    def get(self):
        """Round-robin connection (ref: rpc.go:176-183); wraps at 2**64
        like the reference's uint64 counter (rpc_test.go:390-425). A
        configured ``picker`` sees the healthy set first and may
        override the choice (load-aware routing); any misbehavior —
        None, a stale conn, an exception — falls back to round-robin."""
        with self._seq_lock:
            seq = self._seq
            self._seq = (self._seq + 1) & 0xFFFFFFFFFFFFFFFF
        with self._lock:
            conns = [c for c in self._conns if c.healthy]
            if len(conns) < len(self._conns) or not conns:
                # Dead connections with no membership churn to evict
                # them: kick a background re-dial of the last snapshot
                # so the client heals instead of waiting for a watch
                # event that may never come.
                self._kick_redial()
            if not conns:
                return None
            if self.cfg.picker is not None:
                try:
                    chosen = self.cfg.picker(list(conns))
                except Exception:  # noqa: BLE001 — picker is advisory
                    chosen = None
                if chosen is not None and any(chosen is c for c in conns):
                    return chosen
            return conns[seq % len(conns)]

    def _kick_redial(self) -> None:
        # No extra cooldown: _redialing already serializes bursts (an
        # unreachable peer holds it for its whole dial_timeout), and a
        # fixed cooldown would race the retry backoff — a caller's last
        # attempt must not find the redial still embargoed.
        if self._closed.is_set() or self._redialing.is_set():
            return
        self._redialing.set()

        def run():
            try:
                with self._lock:
                    nodes = list(self._last_nodes)
                if nodes and not self._closed.is_set():
                    self._handle_new_nodes(nodes)
            finally:
                self._redialing.clear()

        threading.Thread(target=run, name=f"redial-{self.service_name}",
                         daemon=True).start()

    def _report(self, err: Exception) -> None:
        try:
            self.err_queue.put_nowait(err)
        except queue.Full:
            pass

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        self._watch.cancel()
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.close()


# ------------------------------------------------------------------ client


class Client:
    """Sync/async actor calls with bounded retries (ref: rpc.go:40-124)."""

    def __init__(self, local_addr: str, service_name: str, registry: Registry,
                 cfg: ConnConfig | None = None):
        self.cfg = cfg or DEFAULT_CONN_CONFIG
        self._conns = _ConnectionBalancer(
            local_addr, service_name, registry, self.cfg
        )

    def call(self, method: str, *args):
        """Synchronous call; up to ``retries + 1`` attempts, each on the
        next round-robin connection (correct version of rpc.go:59-67)."""
        return self._with_retry(method, args)

    def go(self, method: str, *args, done=None) -> Future:
        """Asynchronous call returning a Future (ref Client.Go's done
        channel, rpc.go:69-105 — with retries that actually happen).

        ``done``: optional callable invoked with the Future on completion,
        or a ``queue.Queue`` the Future is put on (the done-channel shape).
        """
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self._with_retry(method, args))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        if done is not None:
            if isinstance(done, queue.Queue):
                fut.add_done_callback(done.put)
            elif callable(done):
                fut.add_done_callback(done)
        return fut

    def _with_retry(self, method: str, args):
        attempts = self.cfg.retries + 1
        last_err: Exception | None = None
        bo = retry.Backoff(base=self.cfg.retry_backoff_base,
                           cap=self.cfg.retry_backoff_cap)
        for attempt in range(attempts):
            if attempt:
                # Jittered exponential backoff between attempts: give
                # the balancer (and the peer) a beat to recover instead
                # of re-firing immediately into the same dying node set.
                bo.sleep()
            conn = self._conns.get()
            if conn is None:
                last_err = NoClientAvailableError("no client nodes available")
                continue
            # One span per attempt: the traceparent injected by
            # call_async is THIS span, so the server-side handler span
            # parents under the attempt that actually carried it.
            with trace.span("rpc.call", method=method,
                            node=f"{conn.node.address}:{conn.node.port}",
                            attempt=attempt) as sp:
                fut = conn.call_async(method, args)
                try:
                    result = fut.result(timeout=self.cfg.call_timeout)
                    chaos.note_ok("rpc.call", method)
                    return result
                except FuturesTimeoutError:
                    conn.forget(fut)
                    last_err = RPCError(
                        f"call {method!r} timed out after "
                        f"{self.cfg.call_timeout}s"
                    )
                    # The failure is absorbed for retry, so the span
                    # exit never sees it — record it explicitly or the
                    # flight recorder shows a failed attempt as ok.
                    sp.set_status("error")
                    sp.add_event("exception", type="TimeoutError",
                                 message=str(last_err)[:200])
                    self._conns._report(last_err)
                    continue
                except ShedError:
                    # Typed overload refusal: terminal by contract —
                    # every retry would land back in the same
                    # overloaded admission queue and amplify the
                    # overload the shed exists to relieve. The caller
                    # owns the backoff (retry_after_s rides the
                    # exception).
                    raise
                except Exception as e:  # noqa: BLE001
                    # Both transport errors and remote handler errors
                    # retry — "retries are possibly done on different
                    # nodes" (rpc.go:28-30; retry-until-healthy-handler
                    # contract rpc_test.go:55-77).
                    last_err = e
                    sp.set_status("error")
                    sp.add_event("exception", type=type(e).__name__,
                                 message=str(e)[:200])
                    if not isinstance(e, RemoteError):
                        self._conns._report(e if isinstance(e, RPCError)
                                            else RPCError(str(e)))
        raise last_err if last_err is not None else NoClientAvailableError(
            "no client nodes available"
        )

    def connection_errs(self) -> "queue.Queue[Exception]":
        """Stream of balancer/transport errors (ref: rpc.go:122-124)."""
        return self._conns.err_queue

    def close(self) -> None:
        self._conns.close()
