"""JAX version compatibility shims.

The repo targets the modern ``jax.shard_map`` surface (``check_vma``);
older jax (< 1.0, e.g. the 0.4.x line baked into some images) ships the
same primitive as ``jax.experimental.shard_map.shard_map`` — and some
intermediate releases export top-level ``jax.shard_map`` while still
spelling the replication check ``check_rep``. Selection is therefore by
FEATURE (does the signature accept ``check_vma``), not by import
success. Everything that shard_maps imports from here so the whole mesh
data plane runs on all of them.
"""

from __future__ import annotations

import inspect


def _resolve_shard_map():
    legacy = None
    try:
        from jax import shard_map as sm  # type: ignore[attr-defined]

        try:
            if "check_vma" in inspect.signature(sm).parameters:
                return sm  # modern surface, pass through untouched
        except (TypeError, ValueError):
            pass  # unintrospectable wrapper: treat as legacy
        legacy = sm  # top-level export but pre-check_vma (check_rep era)
    except ImportError:
        pass
    if legacy is None:
        from jax.experimental.shard_map import shard_map as legacy

    def shim(f, *, mesh=None, in_specs=None, out_specs=None,
             check_vma: bool | None = None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return legacy(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)

    return shim


shard_map = _resolve_shard_map()

try:  # modern surface
    from jax.lax import axis_size  # type: ignore[attr-defined]
except ImportError:
    from jax import lax as _lax

    def axis_size(axis_name) -> int:
        # psum of a concrete constant over a named axis folds statically
        # to the axis size — the long-standing pre-axis_size idiom.
        return _lax.psum(1, axis_name)

__all__ = ["shard_map", "axis_size"]
