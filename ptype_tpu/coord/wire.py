"""Length-prefixed JSON framing for the coordination protocol.

The control plane is low-volume metadata (service records, small KV state,
lease heartbeats) — JSON over TCP is the honest choice; tensors NEVER travel
through here (they ride the actor RPC tensor codec or XLA collectives).

Frame: 4-byte big-endian length, then UTF-8 JSON payload.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

MAX_FRAME = 64 * 1024 * 1024

_LEN = struct.Struct(">I")


class WireError(ConnectionError):
    pass


def send_msg(sock: socket.socket, lock: threading.Lock, msg: dict) -> None:
    payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame too large: {len(payload)} bytes")
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> dict:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame too large: {length} bytes")
    payload = _recv_exact(sock, length)
    return json.loads(payload.decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(remaining)
        if not chunk:
            raise WireError("connection closed")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)
