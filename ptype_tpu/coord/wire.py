"""Length-prefixed JSON framing for the coordination protocol.

The control plane is low-volume metadata (service records, small KV state,
lease heartbeats) — JSON over TCP is the honest choice; tensors NEVER travel
through here (they ride the actor RPC tensor codec or XLA collectives).

Frame: 4-byte big-endian length, then UTF-8 JSON payload.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

from ptype_tpu import chaos, trace

MAX_FRAME = 64 * 1024 * 1024

_LEN = struct.Struct(">I")


class WireError(ConnectionError):
    pass


def _chaos_kill(sock: socket.socket) -> None:
    """Sever a connection the chaos way: shutdown() first so a reader
    parked in recv(2) on the same socket wakes immediately (close()
    alone does not — same reason as RemoteCoord._bounce_endpoint)."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def send_msg(sock: socket.socket, lock: threading.Lock, msg: dict) -> None:
    tp = trace.traceparent()
    if tp is not None and "_tp" not in msg:
        # Trace context rides the frame (the coord-plane analog of the
        # actor frame's "tp"): CoordServer attaches it around op
        # dispatch so coordinator work joins the caller's trace.
        # Replies/pushes sent from untraced threads carry nothing.
        msg = {**msg, "_tp": tp}
    payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame too large: {len(payload)} bytes")
    f = chaos.hit("coord.wire_send", str(msg.get("op", "")))
    if f is not None:
        if f.action == "delay":
            f.sleep()
        elif f.action == "drop":
            _chaos_kill(sock)
            raise WireError("chaos: connection dropped before send")
        elif f.action == "truncate":
            with lock:
                try:
                    sock.sendall(_LEN.pack(len(payload))
                                 + payload[: len(payload) // 2])
                except OSError:
                    pass
            _chaos_kill(sock)
            raise WireError("chaos: frame truncated mid-send")
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> dict:
    f = chaos.hit("coord.wire_recv")
    if f is not None:
        if f.action == "delay":
            f.sleep()
        elif f.action == "drop":
            _chaos_kill(sock)
            raise WireError("chaos: connection dropped before recv")
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame too large: {length} bytes")
    payload = _recv_exact(sock, length)
    try:
        msg = json.loads(bytes(payload).decode("utf-8"))
    except (ValueError, UnicodeDecodeError, RecursionError) as e:
        # RecursionError: ~2000 nested brackets blows json's recursive
        # parser well under MAX_FRAME — same peer-garbage class.
        # Garbage from a confused/malicious peer must surface as the
        # connection-level error every reader already handles — a raw
        # JSONDecodeError would escape the (WireError, OSError) nets.
        raise WireError(f"malformed frame: {e}") from e
    if not isinstance(msg, dict):
        raise WireError(f"malformed frame: expected object, "
                        f"got {type(msg).__name__}")
    return msg


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    """Read exactly n bytes into one preallocated buffer (no chunk list
    + join). Uses the native GIL-free reader when built (ptype_tpu.native,
    the compiled-runtime tier); recv_into otherwise.

    The native path requires a BLOCKING socket: ``settimeout()`` flips
    the fd to non-blocking and raw ``recv(2)`` then returns EAGAIN
    immediately (observed as spurious probe failures in the standby) —
    Python's own recv hides this behind a selector wait, so timed
    sockets take the Python path."""
    buf = bytearray(n)
    view = memoryview(buf)
    try:
        from ptype_tpu import native

        if native.available() and sock.gettimeout() is None:
            got = native.recv_exact_into(sock, view)
            if got < n:
                raise WireError("connection closed")
            return view
    except NotImplementedError:
        pass
    except ImportError:
        pass
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise WireError("connection closed")
        got += r
    return view
