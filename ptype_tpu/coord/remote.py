"""TCP client for the coordination service."""

from __future__ import annotations

import atexit
import socket
import threading
import time

from ptype_tpu import lockcheck
import weakref

from ptype_tpu import chaos, logs, retry
from ptype_tpu.coord import wire
from ptype_tpu.coord.api import CoordBackend
from ptype_tpu.coord.core import (
    Event,
    EventType,
    KVItem,
    Member,
    RangeOptions,
    RangeResult,
    Watch,
)
from ptype_tpu.errors import CoordinationError

log = logs.get_logger("coord.remote")

#: Live clients, quiesced at interpreter exit: reconnect/rewatch/
#: discovery threads that outlive logging teardown die loudly. Weak so
#: the set never pins a client.
_live_clients: "weakref.WeakSet[RemoteCoord]" = weakref.WeakSet()


@atexit.register
def _quiesce_clients() -> None:
    for c in list(_live_clients):
        c._closed.set()


class _Pending:
    __slots__ = ("event", "reply", "sock")

    def __init__(self, sock):
        self.event = threading.Event()
        self.reply: dict | None = None
        #: The socket this request was sent on. After a reconnect, any
        #: pending still tagged with an OLD socket was sent into the
        #: void (a half-closed socket accepts exactly one post-FIN
        #: write) — its reply can never come and it must be failed
        #: rather than left to burn the full request timeout.
        self.sock = sock


class _StaleCoordinator(CoordinationError):
    """The endpoint answered but is a SUPERSEDED primary (its fencing
    term is behind this client's). The request was refused before
    execution, so retrying against another endpoint is always safe.
    Carries the endpoint that refused, so concurrent callers bounce
    it exactly once."""

    def __init__(self, msg: str, endpoint: str | None = None):
        super().__init__(msg)
        self.endpoint = endpoint


class _SendFailed(CoordinationError):
    """The request never left this client (send error, or the bytes
    went into a socket the reader had already replaced). The server
    cannot have executed it, so the fence-bounce loop may re-send;
    a timeout or lost-mid-request is NOT this — the op may have
    executed, and only the caller knows whether a retry is safe."""


class RemoteCoord(CoordBackend):
    """Client over one persistent connection; safe for concurrent use.

    ``address`` may be a list of endpoints: the client dials the first
    reachable one and, on connection loss, cycles through ALL of them —
    so a warm standby (coord.standby) that takes over on a different
    address picks up the clientele without any client-side action.

    Fencing: every reply carries the server's promotion ``term``; the
    client remembers the highest it has seen and stamps it on every
    request (``min_term``). A superseded primary — e.g. the old seed
    restarted on its old address after a wal-stream takeover — refuses
    the request, and the client abandons that endpoint and re-dials
    until it finds the current primary. This is the client half of the
    epoch fence raft gave the reference for free
    (/root/reference/cluster/cluster.go:120-147).

    Dial timeout defaults to the reference's 5 s (registry.go:37,
    store.go:25, cluster.go:53).
    """

    def __init__(self, address: str | list[str], dial_timeout: float = 5.0,
                 request_timeout: float = 30.0,
                 reconnect_timeout: float = 30.0,
                 discovery_interval: float = 0.0):
        eps = [address] if isinstance(address, str) else list(address)
        if not eps:
            raise CoordinationError("RemoteCoord: no endpoints")
        self.endpoints = eps
        #: The configured endpoints — never pruned by discovery
        #: (discovered standbys come and go; the static list is the
        #: operator's contract).
        self._seed_endpoints = list(eps)
        #: Guards endpoints/address against the discovery thread: a
        #: remove() between _dial's membership check and .index(), or
        #: between a len() and the modular index, would raise out of
        #: the reader's reconnect path. Created before the first _dial.
        self._endpoints_lock = lockcheck.lock("coord.remote.endpoints")
        self.address = eps[0]
        self._dial_timeout = dial_timeout
        self._request_timeout = request_timeout
        #: How long to re-dial a lost coordinator before giving up
        #: (covers a seed restart from its WAL data_dir, or a standby
        #: takeover on another endpoint); 0 disables.
        self._reconnect_timeout = reconnect_timeout
        try:
            self._sock = self._dial()
        except OSError as e:
            raise CoordinationError(
                f"failed to dial coordination service at {eps}: {e}"
            ) from e
        self._send_lock = lockcheck.lock("coord.remote.send")
        #: Highest fencing term seen in any reply (never decreases).
        self._term = 0
        #: Set while a dialed connection is live; cleared on loss and
        #: by a stale-endpoint bounce, so fence retries can wait for
        #: the reader's re-dial instead of spinning on a dead socket.
        self._connected = threading.Event()
        self._connected.set()
        self._pending: dict[int, _Pending] = {}
        self._pending_lock = lockcheck.lock("coord.remote.pending")
        self._watches: dict[int, Watch] = {}
        #: Watch pushes that arrived before their watch id was
        #: registered (see _dispatch_watch); drained at registration.
        self._orphan_events: dict[int, list] = {}
        self._watches_lock = lockcheck.lock("coord.remote.watches")
        self._next_id = 1
        self._id_lock = lockcheck.lock("coord.remote.id")
        self._closed = threading.Event()
        #: Cleared while watches are being re-armed after a reconnect;
        #: ordinary calls wait on it so a caller cannot slip a write in
        #: before the re-watch and silently miss its own event.
        self._rewatch_gate = threading.Event()
        self._rewatch_gate.set()
        self._rewatch_thread: threading.Thread | None = None
        self._reader = threading.Thread(
            target=self._read_loop, name=f"coord-client-{self.address}",
            daemon=True
        )
        self._reader.start()
        # discovery_interval > 0: periodically merge promote-eligible
        # standbys from the membership into the endpoint list, so this
        # client can fail over to standbys attached after it connected.
        if discovery_interval > 0:
            threading.Thread(
                target=self._discovery_loop, args=(discovery_interval,),
                name=f"coord-discovery-{self.address}", daemon=True,
            ).start()
        _live_clients.add(self)

    # ------------------------------------------------------------- plumbing

    def _cur_addr(self) -> str:
        """The active endpoint, read under the endpoints lock — the
        discovery thread and stale-bounces rewrite ``self.address``
        concurrently, and log/error paths must not read it torn
        against the endpoint list."""
        with self._endpoints_lock:
            return self.address

    def _dial(self) -> socket.socket:
        """Dial the endpoint list in order, starting at the currently
        active one; first success wins and becomes ``self.address``.
        Works off a snapshot so concurrent discovery churn can't shift
        indices mid-iteration."""
        with self._endpoints_lock:
            eps = list(self.endpoints)
            addr = self.address
        start = eps.index(addr) if addr in eps else 0
        last: OSError | None = None
        for i in range(len(eps)):
            ep = eps[(start + i) % len(eps)]
            host, _, port = ep.rpartition(":")
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=self._dial_timeout
                )
            except OSError as e:
                last = e
                continue
            if sock.getsockname() == sock.getpeername():
                # TCP simultaneous-open self-connect: dialing a loopback
                # ephemeral port with no listener can connect the socket
                # to itself — not a coordinator.
                sock.close()
                last = OSError("self-connected (no listener)")
                continue
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Under the lock: _bounce_endpoint's single-advance guard
            # and discovery's keep-current-address prune both read
            # address under it — an unlocked write here could let a
            # stale-reply bounce shut down this fresh connection.
            with self._endpoints_lock:
                self.address = ep
            return sock
        raise last or OSError("no endpoints")

    def _read_loop(self) -> None:
        try:
            while not self._closed.is_set():
                try:
                    msg = wire.recv_msg(self._sock)
                except (wire.WireError, OSError):
                    # Connection lost: fail outstanding requests (their
                    # callers retry — registry keepalive, balancer),
                    # mark every watch dis-armed, and try to reach a
                    # coordinator again (seed restarting from its WAL,
                    # or a standby taking over). Deliberate close()
                    # skips the re-dial.
                    self._connected.clear()
                    self._fail_pending()
                    with self._watches_lock:
                        for w in self._watches.values():
                            w._armed = False
                        # Stashed pushes are scoped to the DEAD
                        # connection's watch-id space: after a failover
                        # a fresh CoordState numbers watches from
                        # scratch, and a stale stash could drain into
                        # an unrelated (wrong-prefix) new watch.
                        self._orphan_events.clear()
                    if self._closed.is_set() or not self._try_reconnect():
                        break
                    continue
                if "watch" in msg and "id" not in msg:
                    self._dispatch_watch(msg)
                    continue
                with self._pending_lock:
                    p = self._pending.pop(msg.get("id"), None)
                if p is not None:
                    p.reply = msg
                    p.event.set()
        finally:
            # Giving up for good — including via an UNEXPECTED
            # exception: the cleanup must still run, or the client is
            # left half-alive (reader dead, _closed unset, every
            # future call burning its full timeout on a dead socket).
            self._closed.set()
            self._fail_pending()
            with self._watches_lock:
                watches, self._watches = list(self._watches.values()), {}
                self._orphan_events.clear()
            for w in watches:
                w.cancel()

    def _fail_pending(self, keep_sock=None) -> None:
        """Fail outstanding requests. ``keep_sock``: spare requests
        sent on that (current) socket — used after a re-dial to reap
        only the stragglers that raced the reconnect onto the old
        socket."""
        with self._pending_lock:
            doomed = [(i, p) for i, p in self._pending.items()
                      if keep_sock is None or p.sock is not keep_sock]
            for i, _ in doomed:
                del self._pending[i]
        for _, p in doomed:
            p.event.set()

    def _try_reconnect(self) -> bool:
        if not self._reconnect_timeout:
            return False
        deadline = time.monotonic() + self._reconnect_timeout
        bo = retry.Backoff(base=0.2, cap=2.0)
        while not self._closed.is_set():
            try:
                self._sock = self._dial()
            except OSError:
                delay = bo.next_delay()
                if time.monotonic() + delay > deadline:
                    log.warning("coordination reconnect gave up",
                                kv={"addr": self._cur_addr()})
                    return False
                bo.sleep(delay)
                continue
            addr = self._cur_addr()
            log.info("coordination connection re-established",
                     kv={"addr": addr})
            chaos.note_ok("coord.reconnect", addr)
            # Reap requests that were sent while we were re-dialing:
            # they went into the OLD socket (its first post-FIN write
            # "succeeds" locally) after the loss-path _fail_pending had
            # already run, so nothing else will ever complete them.
            self._fail_pending(keep_sock=self._sock)
            # Re-arm watches on a fresh thread — _call needs this read
            # loop back in recv. The rewatch gate holds OTHER callers'
            # requests until re-arm completes, so a client's own
            # post-reconnect write can't race ahead of its watches;
            # events produced by third parties during the outage are
            # still missed (watch consumers re-list — the
            # registry.WatchService snapshot-then-delta contract).
            # Gen bump + gate clear are atomic (watches lock): a
            # superseded rewatch thread checking its generation must
            # never interleave with this clear and re-open the gate.
            with self._watches_lock:
                self._rewatch_gen = getattr(self, "_rewatch_gen", 0) + 1
                gen = self._rewatch_gen
                self._rewatch_gate.clear()
            t = threading.Thread(target=self._rewatch,
                                 args=(gen,), daemon=True)
            self._rewatch_thread = t
            t.start()
            self._connected.set()
            return True
        return False

    def _rewatch(self, gen: int) -> None:
        """Re-arm every dis-armed watch, RETRYING until all are live (a
        one-shot attempt whose failure waits for the *next* disconnect
        leaves watches dead forever on a healthy connection). A newer
        reconnect's rewatch (gen bump) supersedes this one — watches it
        didn't finish stay dis-armed and the successor picks them up."""
        def current() -> bool:
            return gen == getattr(self, "_rewatch_gen", gen)

        bo = retry.Backoff(base=0.5, cap=1.0)
        try:
            while not self._closed.is_set() and current():
                failed = False
                with self._watches_lock:
                    todo = [w for w in self._watches.values()
                            if not w.closed
                            and not getattr(w, "_armed", True)]
                for w in todo:
                    # Resume from the last DELIVERED revision: the
                    # server replays the missed interval from its MVCC
                    # event history — no events lost, no re-list. Only
                    # when that interval has been compacted (outage
                    # outlived the history window) fall back to a
                    # fresh watch + epoch bump (consumers re-list:
                    # snapshot-then-delta).
                    replayed = True
                    try:
                        try:
                            res = self._call("watch", prefix=w.prefix,
                                             start_rev=w.last_rev + 1)
                        except CoordinationError as e:
                            if "compacted" not in str(e):
                                raise
                            replayed = False
                            res = self._call("watch", prefix=w.prefix)
                    except CoordinationError:
                        failed = True
                        continue  # retried next round (backoff below)
                    new_id = res["id"]
                    with self._watches_lock:
                        if self._watches.pop(w.id, None) is not None:
                            w.id = new_id
                            w._armed = True
                            if not replayed:
                                # Events in the gap are gone for good:
                                # signal consumers to re-list.
                                w.epoch += 1
                                if res.get("rev", 0) > w.last_rev:
                                    w.last_rev = res["rev"]
                            self._watches[new_id] = w
                            for _, m in self._orphan_events.pop(
                                    new_id, []):
                                w._push(self._wire_events(m))
                            continue
                    # The local watch was closed concurrently: the
                    # server-side watch we just created is orphaned —
                    # cancel it or it pumps events nobody reads for
                    # the connection's lifetime.
                    try:
                        self._call("watch_cancel", watch=new_id)
                    except CoordinationError:
                        pass  # connection died; server cleans up
                # Open the gate only once every watch re-armed — the
                # gate's contract is that a caller's post-reconnect
                # write cannot race ahead of its own watches, which a
                # partially-armed set would silently break. (Callers
                # have a bounded gate wait, so a persistently failing
                # re-arm degrades to that timeout, not a deadlock.)
                if not failed:
                    with self._watches_lock:
                        if current():
                            self._rewatch_gate.set()
                with self._watches_lock:
                    if not any(not w.closed
                               and not getattr(w, "_armed", True)
                               for w in self._watches.values()):
                        return
                bo.sleep()
        finally:
            # A superseded generation must NOT open the gate — its
            # successor cleared it and is still re-arming; opening it
            # here would let a caller's write race ahead of its watches.
            # (Atomic with the successor's bump+clear via the lock.)
            with self._watches_lock:
                if self._closed.is_set() or current():
                    self._rewatch_gate.set()

    @staticmethod
    def _wire_events(msg: dict) -> list[Event]:
        return [
            Event(
                type=EventType(ev["type"]),
                key=ev["key"],
                value=ev["value"],
                mod_rev=ev["mod_rev"],
            )
            for ev in msg.get("events", [])
        ]

    def _dispatch_watch(self, msg: dict) -> None:
        with self._watches_lock:
            w = self._watches.get(msg["watch"])
            if w is None:
                # The server starts pumping the moment the create-reply
                # is sent, so a push can reach this reader BEFORE the
                # calling thread registers the new watch id — a hot
                # race for replay-from-revision re-arms (their events
                # are pre-queued). Stash briefly; _register_watch
                # drains under this same lock, preserving order.
                now = time.monotonic()
                self._orphan_events.setdefault(
                    msg["watch"], []).append((now, msg))
                for wid in list(self._orphan_events):
                    self._orphan_events[wid] = [
                        (t, m) for t, m in self._orphan_events[wid]
                        if now - t < 30.0]
                    if not self._orphan_events[wid]:
                        del self._orphan_events[wid]
                return
            events = self._wire_events(msg)
        w._push(events)

    def _register_watch(self, w: Watch) -> None:
        """Register a (re)armed watch id and drain any pushes that
        outran the registration (under the watches lock, so no later
        push can interleave ahead of the drained ones)."""
        with self._watches_lock:
            self._watches[w.id] = w
            for _, msg in self._orphan_events.pop(w.id, []):
                w._push(self._wire_events(msg))

    def _call(self, op: str, reply_timeout: float | None = None, **kwargs):
        """One request/response, with fence-aware endpoint cycling: a
        ``stale`` refusal (superseded primary — the op was NOT
        executed) bounces to the next endpoint and retries until the
        current primary is found or the endpoint list is exhausted."""
        stale: _StaleCoordinator | None = None
        bo = retry.Backoff(base=0.3, cap=1.0)
        for _ in range(2 * len(self.endpoints) + 2):
            if stale is not None:
                # Wait for the reader's re-dial after the bounce.
                self._connected.wait(timeout=5.0)
            try:
                return self._call_once(op, reply_timeout, kwargs)
            except _StaleCoordinator as e:
                stale = e
                self._bounce_endpoint(e.endpoint)
            except _SendFailed:
                if stale is None:
                    raise  # ordinary failure: callers own the retry
                bo.sleep()  # mid-re-dial; let the reader land
            # Any other CoordinationError (timeout, lost mid-request)
            # propagates even after a bounce: the op may have EXECUTED
            # on the current primary, and re-sending a non-idempotent
            # op (grant, member_add) here would double-apply it.
        raise CoordinationError(
            f"no current-term coordinator among {self.endpoints}: {stale}")

    def _bounce_endpoint(self, stale_ep: str | None) -> None:
        """Abandon a superseded primary: advance the endpoint cursor so
        the reader's re-dial starts at the NEXT endpoint, then drop the
        socket to trigger the reconnect loop. Concurrent callers whose
        stale replies came from the same endpoint bounce it ONCE — a
        double advance could skip straight past the current primary."""
        with self._endpoints_lock:
            if stale_ep is not None and self.address != stale_ep:
                return  # another caller (or the reader) already moved on
            try:
                idx = self.endpoints.index(self.address)
            except ValueError:
                idx = -1
            stale_ep = self.address
            self.address = self.endpoints[(idx + 1) % len(self.endpoints)]
            nxt = self.address
        self._connected.clear()
        log.info("abandoning superseded coordinator",
                 kv={"stale": stale_ep, "next": nxt,
                     "fence_term": self._term})
        sock = self._sock
        try:
            # shutdown() interrupts the reader parked in recv(2) on this
            # socket; close() alone does not (same reason as
            # WalFollower.close) — without it the reconnect loop never
            # runs and the bounce strands the client.
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _call_once(self, op: str, reply_timeout: float | None, kwargs):
        addr = self._cur_addr()
        if self._closed.is_set():
            raise CoordinationError(
                f"coordination connection to {addr} closed")
        if (not self._connected.is_set()
                and threading.current_thread() is not self._rewatch_thread):
            # The reader is mid-re-dial: a send into the dead socket
            # can "succeed" locally and then park this op until the
            # whole reconnect window lapses. Fail fast instead — the
            # op never left this client, so callers retry safely
            # (exactly the outage contract the registry keepalive and
            # failover tests already code against).
            raise _SendFailed(
                f"connection to {addr} down (reconnect in flight)")
        if (not self._rewatch_gate.is_set()
                and threading.current_thread() is not self._rewatch_thread):
            # A reconnect is re-arming watches; hold ordinary traffic so
            # callers observe their own effects through their watches.
            self._rewatch_gate.wait(timeout=5.0)
        with self._id_lock:
            req_id = self._next_id
            self._next_id += 1
        sock = self._sock
        p = _Pending(sock)
        with self._pending_lock:
            self._pending[req_id] = p
        try:
            wire.send_msg(sock, self._send_lock,
                          {"id": req_id, "op": op,
                           "min_term": self._term, **kwargs})
        except (wire.WireError, OSError) as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise _SendFailed(f"send to {addr} failed: {e}") from e
        if sock is not self._sock and not p.event.is_set():
            # The reader replaced the connection while we were sending:
            # the bytes went into the dead socket (a kill's RST races
            # the local send buffer, so send() "succeeds") and
            # _fail_pending has already run — this reply can never
            # arrive. Fail fast; callers retry like any connection loss.
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise _SendFailed(
                f"connection to {addr} replaced mid-request")
        if not p.event.wait(reply_timeout if reply_timeout is not None
                            else self._request_timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise CoordinationError(
                f"request {op!r} to {addr} timed out")
        if p.reply is None:
            raise CoordinationError(
                f"connection to {addr} lost mid-request")
        t = p.reply.get("term")
        if isinstance(t, int) and t > self._term:
            self._term = t  # adopt the newest primary's fence
        if not p.reply.get("ok"):
            if p.reply.get("stale"):
                raise _StaleCoordinator(
                    p.reply.get("error", "stale coordinator"),
                    endpoint=addr)
            raise CoordinationError(p.reply.get("error", "unknown coordination error"))
        chaos.note_ok("coord.op", op)
        return p.reply.get("result")

    # ------------------------------------------------------------------- KV

    def put(self, key: str, value: str, lease: int = 0,
            sync: bool = False,
            sync_timeout: float | None = None,
            sync_min_followers: int = 0) -> int:
        if sync_min_followers and not sync:
            raise ValueError(
                "sync_min_followers requires sync=True — without the "
                "barrier the floor would be silently ignored")
        if sync:
            extra = {"sync": True}
            if sync_timeout is not None:
                extra["sync_timeout"] = sync_timeout
            if sync_min_followers:
                extra["sync_min_followers"] = sync_min_followers
            return self._call("put", key=key, value=value, lease=lease,
                              **extra)
        return self._call("put", key=key, value=value, lease=lease)

    def range(self, key: str, options: RangeOptions | None = None) -> RangeResult:
        res = self._call("range", key=key, options=(options or RangeOptions()).to_wire())
        return RangeResult(
            items=[KVItem(**it) for it in res["items"]],
            count=res["count"],
            revision=res["revision"],
        )

    def delete(self, key: str, options: RangeOptions | None = None) -> int:
        return self._call("delete", key=key, options=(options or RangeOptions()).to_wire())

    # --------------------------------------------------------------- leases

    def grant(self, ttl: float) -> int:
        return self._call("grant", ttl=ttl)

    def keepalive(self, lease_id: int) -> float:
        return self._call("keepalive", lease=lease_id)

    def revoke(self, lease_id: int) -> None:
        self._call("revoke", lease=lease_id)

    # -------------------------------------------------------------- watches

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        res = self._call("watch", prefix=prefix, start_rev=start_rev)
        w = Watch(res["id"], prefix, self._cancel_watch)
        # Resume floor: for a fresh watch the server's arm-time head
        # (nothing before it was promised); start_rev watches resume
        # from the caller's own floor. Advances only as events are
        # actually DELIVERED (Watch._push) — so a reconnect mid-replay
        # can never skip undelivered events.
        w.last_rev = (start_rev - 1) if start_rev else res.get("rev", 0)
        self._register_watch(w)
        return w

    def _cancel_watch(self, w: Watch) -> None:
        with self._watches_lock:
            self._watches.pop(w.id, None)
        if not self._closed.is_set():
            try:
                self._call("watch_cancel", watch=w.id)
            except CoordinationError:
                pass

    # -------------------------------------------------------------- members

    def member_add(self, name: str, peer_addr: str, metadata: dict | None = None) -> Member:
        m = self._call("member_add", name=name, peer_addr=peer_addr,
                       metadata=metadata or {})
        return Member(**m)

    def member_promote(self, member_id: int) -> Member:
        return Member(**self._call("member_promote", member=member_id))

    def member_remove(self, member_id: int) -> bool:
        return self._call("member_remove", member=member_id)

    def member_list(self) -> list[Member]:
        return [Member(**m) for m in self._call("member_list")]

    def discover_endpoints(self) -> list[str]:
        """Merge promote-eligible standbys from the membership into the
        failover endpoint list — how a client learns about a standby
        attached AFTER this client was constructed (the dynamic
        counterpart of the static initial_cluster_client_urls list;
        ref: learner add→promote, cluster.go:120-147). Learners are
        skipped: failing over to a standby whose mirror never caught up
        would serve stale or empty state."""
        members = self.member_list()  # network call: outside the lock
        eligible = set()
        added, pruned = [], []
        for m in members:
            md = m.metadata or {}
            if (md.get("role") == "standby"
                    and md.get("learner", True) is False and m.peer_addr):
                eligible.add(m.peer_addr)
        with self._endpoints_lock:
            for addr in eligible:
                if addr not in self.endpoints:
                    self.endpoints.append(addr)
                    added.append(addr)
            # Reconcile removals: a decommissioned standby
            # (Standby.close deregisters it) must not linger as a dead
            # dial target — each stale entry can burn a full
            # dial_timeout per reconnect cycle. Configured seeds and
            # the endpoint currently in use are kept.
            for addr in list(self.endpoints):
                if (addr not in eligible
                        and addr not in self._seed_endpoints
                        and addr != self.address):
                    self.endpoints.remove(addr)
                    pruned.append(addr)
            out = list(self.endpoints)
        for addr in added:
            log.info("discovered standby endpoint", kv={"addr": addr})
        for addr in pruned:
            log.info("pruned decommissioned standby endpoint",
                     kv={"addr": addr})
        return out

    def _discovery_loop(self, interval: float) -> None:
        while not self._closed.wait(interval):
            try:
                self.discover_endpoints()
            except CoordinationError:
                pass  # transient (reconnect in flight); next round

    # ------------------------------------------------------------- barriers

    def barrier(self, name: str, count: int, timeout: float | None = None) -> bool:
        # Give the server-side wait headroom beyond the barrier timeout;
        # the wire field "timeout" is the barrier's own deadline.
        reply_timeout = (timeout + 5.0) if timeout is not None else None
        return self._call("barrier", reply_timeout=reply_timeout,
                          name=name, count=count, timeout=timeout)

    # ---------------------------------------------------------------- misc

    @property
    def term(self) -> int:
        """Highest coordinator fencing term this client has seen."""
        return self._term

    @property
    def closed(self) -> bool:
        """True once the client is closed for good (deliberate close,
        or the reconnect window lapsed) — no call can ever succeed."""
        return self._closed.is_set()

    def ping(self, timeout: float = 5.0) -> bool:
        try:
            return self._call("ping", reply_timeout=timeout) == "pong"
        except CoordinationError:
            return False

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            # shutdown() wakes the reader parked in recv(2); close()
            # alone leaves it wedged until process exit.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
