"""TCP coordination service — hosted by the seed node.

The multi-process deployment model: the coordinator process (platform config
``is_coordinator: true``) starts a :class:`CoordServer` over its
:class:`CoordState`; every process (including the coordinator itself)
connects with :class:`ptype_tpu.coord.remote.RemoteCoord` or, on the
coordinator, may use :class:`LocalCoord` directly. This mirrors how the JAX
distributed coordination service is deployed (process 0 hosts), replacing
the reference's every-process-embeds-etcd model (cluster.go:161-196).
"""

from __future__ import annotations

import socket
import threading
import time

from ptype_tpu import chaos, logs, retry, trace
from ptype_tpu.coord import wire
from ptype_tpu.coord.core import CoordState, RangeOptions, Watch

log = logs.get_logger("coord.service")


def _item_wire(it) -> dict:
    return {
        "key": it.key,
        "value": it.value,
        "create_rev": it.create_rev,
        "mod_rev": it.mod_rev,
        "version": it.version,
        "lease": it.lease,
    }


def _member_wire(m) -> dict:
    return {
        "id": m.id,
        "name": m.name,
        "peer_addr": m.peer_addr,
        "metadata": m.metadata,
    }


def _repl_idle_tick(witness_ttl: float) -> float:
    """Idle-heartbeat period for the repl pump, derived from the
    configured TTL. The follower's repl_pong round-trip is the liveness
    proof the quorum loop counts as the standby's vote — with the old
    fixed 1.0 s tick, any ``witness_ttl`` ≲ 1 s starved a quiet
    cluster's follower of heartbeats within the TTL window and flapped
    its vote. Three ticks per TTL matches the quorum loop's own cadence
    (``_quorum_loop``); 1.0 s stays the ceiling so big TTLs don't slow
    feed-close detection."""
    return min(1.0, witness_ttl / 3)


class CoordServer:
    """Serves a CoordState over TCP. One instance per cluster seed."""

    def __init__(self, address: str = "127.0.0.1:0",
                 state: CoordState | None = None,
                 data_dir: str | None = None,
                 bump_term: bool | int = False,
                 fsync: bool = False,
                 witness_addr: str | None = None,
                 witness_ttl: float = 3.0,
                 witness_holder: str | None = None):
        # bump_term marks this server a PROMOTED successor: the
        # recovered state's fencing term is incremented (by that many
        # slots — juniors promoting past unresponsive seniors skip
        # their slots) so clients that adopt it refuse any superseded
        # primary (coord/standby).
        self.state = state or CoordState(data_dir=data_dir,
                                         bump_term=bump_term,
                                         fsync=fsync)
        self._owns_state = state is None
        host, _, port = address.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            # Bind retries: a restarting seed can race its own clients'
            # reconnect loops — a loopback dial to the (momentarily
            # free) port can TCP-self-connect and squat it as the
            # dialer's ephemeral port for an instant. SO_REUSEADDR
            # doesn't cover an ACTIVE squatter; a short retry does.
            bind_bo = retry.Backoff(base=0.1, cap=0.2)
            for attempt in range(50):
                try:
                    self._sock.bind((host or "127.0.0.1", int(port)))
                    break
                except OSError:
                    if attempt == 49:
                        raise
                    bind_bo.sleep()
            self._sock.listen(128)
        except OSError:
            # A leaked CoordState would hold the WAL-dir flock forever
            # (its sweeper thread pins it against GC), wedging every
            # future promotion in this process — release it.
            self._sock.close()
            if self._owns_state:
                self.state.close()
            raise
        self.address = f"{self._sock.getsockname()[0]}:{self._sock.getsockname()[1]}"
        self._closed = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="coordd-accept", daemon=True
        )
        self._accept_thread.start()
        # Quorum self-fencing (coord/witness.py): with a witness
        # configured, this primary serves only while it holds a second
        # vote of the {primary, standby, witness} majority — a witness
        # lease renewal OR a live follower heartbeat round-trip within
        # the TTL. The minority side of a partition therefore refuses
        # its clients instead of serving possibly-superseded state
        # (raft partition behavior, ref cluster_test.go:47-167).
        self._witness_addr = witness_addr
        self._witness_ttl = witness_ttl
        #: The identity renewals run under. A promoted standby MUST
        #: pass the exact string it acquired the lease with (its
        #: configured listen address) — the getsockname-derived
        #: self.address can differ ('0.0.0.0' binds, hostnames), and a
        #: mismatched renewal would read as a different holder and
        #: hard-fence the fresh primary within one TTL.
        self._witness_holder = witness_holder or self.address
        #: Monotonic deadline until which this server may serve. One
        #: boot-time TTL of grace so a seed can start while the
        #: witness is briefly unreachable.
        self._quorum_until = time.monotonic() + witness_ttl
        #: Set when the witness refused renewal with a STRICTLY higher
        #: term: permanent — a promoted successor exists, so this
        #: server must never serve again. Same-term refusals are
        #: retriable (see _quorum_round) and counted here instead.
        self._superseded = None  # (holder, term) | None
        self._refusals = 0
        if witness_addr is not None:
            # The seed's co-located application talks to this state
            # IN-PROCESS (LocalCoord) — hook the fence into the state
            # itself so those callers are refused exactly like remote
            # clients when quorum is lost.
            self.state.fence = self._fenced
            threading.Thread(target=self._quorum_loop,
                             name="coordd-quorum", daemon=True).start()
        log.info("coordination service listening", kv={"addr": self.address})

    # ------------------------------------------------------------- quorum

    def _quorum_round(self) -> None:
        """One vote-collection round. Each vote extends the serving
        deadline only as far as the EVIDENCE behind it reaches:

        - the witness vote stamps ``t0 + ttl`` with ``t0`` taken BEFORE
          the renewal RPC, so the self-fence always fires at or before
          the moment the witness could hand the lease away;
        - the follower vote stamps ``last_round_trip + ttl`` — the
          follower's actual last contact, NOT "now". Granting a fresh
          full TTL against an almost-TTL-old heartbeat let a primary
          serve up to ~2×TTL after its last real round-trip, inside
          which a partitioned-away standby holding the (vacant) witness
          lease could already be serving — the ADVICE.md self-fence
          window. Anchored, the primary's window always ends within one
          TTL of evidence a majority peer could corroborate.

        The deadline never moves backwards: an older-evidence vote must
        not shrink a window a better vote already granted.
        """
        from ptype_tpu.coord import witness as _witness

        t0 = time.monotonic()
        grant_until = None
        try:
            reply = _witness.renew(
                self._witness_addr, holder=self._witness_holder,
                term=self.state.term,
                timeout=max(0.3, self._witness_ttl / 3))
            if reply.get("granted"):
                grant_until = t0 + self._witness_ttl
                self._refusals = 0
            else:
                r_term = reply.get("term")
                if r_term is not None and r_term <= self.state.term:
                    # Refusal WITHOUT a successor term: a holder-string
                    # mismatch (restart under a different address, a
                    # witness that lost state) — retriable, not proof a
                    # successor exists. Deny the vote; the next round
                    # retries one TTL-third later. Permanent fencing is
                    # reserved for a strictly higher term below.
                    self._refusals += 1
                    if self._refusals == 1 or self._refusals % 10 == 0:
                        log.warning(
                            "witness refused renewal at same term; "
                            "retrying (holder mismatch, not a "
                            "successor)",
                            kv={"holder": reply.get("holder"),
                                "term": r_term,
                                "refusals": self._refusals})
                else:
                    self._superseded = (reply.get("holder"), r_term)
                    log.warning(
                        "witness refused lease renewal: superseded — "
                        "hard-fencing this coordinator",
                        kv={"holder": reply.get("holder"),
                            "term": r_term})
                    return
        except (wire.WireError, OSError):
            pass  # witness unreachable: no vote, not a refusal
        hb = self.state.last_follower_contact(within=self._witness_ttl)
        if hb is not None:
            follower_until = hb + self._witness_ttl
            if grant_until is None or follower_until > grant_until:
                grant_until = follower_until
        if grant_until is not None:  # plus our own vote = majority of 3
            self._quorum_until = max(self._quorum_until, grant_until)

    def _quorum_loop(self) -> None:
        interval = self._witness_ttl / 3
        while not self._closed.wait(interval):
            self._quorum_round()
            if self._superseded is not None:
                return

    def _fenced(self) -> str | None:
        """Non-None (the refusal message) when this server must not
        serve: it lost the majority vote or was outright superseded."""
        if self._witness_addr is None:
            return None
        if self._superseded is not None:
            holder, term = self._superseded
            return (f"fenced: superseded by {holder} (term {term}); "
                    f"this coordinator will never serve again")
        if time.monotonic() > self._quorum_until:
            return ("fenced: lost quorum (no witness lease and no "
                    "live follower) — likely the minority side of a "
                    "partition; refusing to serve possibly-stale state")
        return None

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn,
                args=(conn, peer),
                name=f"coordd-conn-{peer[1]}",
                daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        send_lock = threading.Lock()
        watches: dict[int, Watch] = {}
        # Repl feeds ride the same per-connection registry so a dropped
        # follower connection cancels its subscription — otherwise the
        # primary would append every future mutation to an orphaned
        # in-memory feed forever.
        feeds: dict[int, object] = {}
        watches_lock = threading.Lock()
        try:
            while not self._closed.is_set():
                try:
                    msg = wire.recv_msg(conn)
                except (wire.WireError, OSError):
                    return
                if msg.get("op") == "repl_ack":
                    # Unsolicited fire-and-forget from a WAL follower:
                    # record the mirrored-through sequence (wakes
                    # sync-put waiters). Routed by feed id — the
                    # protocol permits several repl_subscribe feeds per
                    # connection, and crediting them all would let one
                    # feed's acks falsely release barriers for records
                    # a slower sibling never mirrored. No reply, no
                    # handler thread.
                    fid = msg.get("feed")
                    with watches_lock:
                        if fid is not None:
                            acked_feeds = ([feeds[fid]]
                                           if fid in feeds else [])
                        else:  # legacy follower: sole-feed conns only
                            acked_feeds = list(feeds.values())
                    for feed in acked_feeds:
                        self.state.note_repl_ack(feed, int(msg["seq"]))
                    continue
                if msg.get("op") == "repl_pong":
                    # Heartbeat round-trip from a follower: proof of
                    # LIVE two-way contact (a half-dead TCP connection
                    # can't produce one), counted as the standby's
                    # vote in the witness quorum (_quorum_round).
                    fid = msg.get("feed")
                    with watches_lock:
                        feed = feeds.get(fid)
                    if feed is not None:
                        self.state.note_repl_hb(feed)
                    continue
                # Blocking ops (barrier, watch pumps) must not stall the
                # reader; dispatch every request to its own thread — control
                # plane volume is low enough that this is simpler and safer
                # than a pool.
                threading.Thread(
                    target=self._handle,
                    args=(conn, send_lock, watches, feeds, watches_lock,
                          msg),
                    daemon=True,
                ).start()
        finally:
            with watches_lock:
                for w in watches.values():
                    w.cancel()
                for feed in feeds.values():
                    feed.cancel()
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn, send_lock, watches, feeds, watches_lock,
                msg: dict) -> None:
        req_id = msg.get("id")
        op = msg.get("op", "")
        # Wire trace context (coord/wire.py injects "_tp"): popped
        # unconditionally so op handlers never see it; adopted around
        # the dispatch below so coordinator work joins the caller's
        # trace.
        tp = msg.pop("_tp", None)
        pump_watch: Watch | None = None
        pump_feed = None
        # Quorum fence BEFORE anything else: a minority-partition or
        # superseded primary must refuse every client — including ones
        # that never saw the successor's term (the hole the term fence
        # alone cannot close). stale=True makes clients bounce to the
        # other endpoints where the real primary lives.
        #
        # Exception: repl_subscribe passes a SOFT (quorum-lost) fence —
        # a returning follower's round-trips ARE the second vote, so
        # refusing the subscription would make the fence permanent even
        # with a healthy primary+standby pair (witness down + one
        # follower blip). A hard-superseded primary still refuses: a
        # successor exists and mirrors must re-home to it.
        fence = self._fenced()
        if (fence is not None and op == "repl_subscribe"
                and self._superseded is None):
            fence = None
        if fence is not None:
            try:
                wire.send_msg(conn, send_lock, {
                    "id": req_id, "ok": False, "stale": True,
                    "fenced": True, "term": self.state.term,
                    "error": fence})
            except (wire.WireError, OSError):
                pass
            return
        # Fencing check BEFORE any dispatch: a client that has seen a
        # newer primary (higher term) must get refused here — this
        # server is a superseded primary still running on stale state
        # (wal-stream failover has no shared flock; the client-carried
        # term is the fence, mirroring raft's leader epoch —
        # /root/reference/cluster/cluster.go:120-147).
        min_term = msg.get("min_term", 0)
        my_term = self.state.term
        if min_term > my_term:
            try:
                wire.send_msg(conn, send_lock, {
                    "id": req_id, "ok": False, "stale": True,
                    "term": my_term,
                    "error": (f"stale coordinator: term {my_term} is "
                              f"behind client fence {min_term}")})
            except (wire.WireError, OSError):
                pass
            return
        try:
            if op == "watch":
                # The pump must not start until the create-reply is on the
                # wire: the client registers the watch id only after the
                # reply, and events sent before that would be dropped.
                # (Replay-from-start_rev events are queued IN the Watch
                # atomically with the arm, so they also flow after the
                # reply, in order.)
                pump_watch = self.state.watch(
                    msg["prefix"], start_rev=msg.get("start_rev", 0))
                with watches_lock:
                    watches[pump_watch.id] = pump_watch
                # arm_rev, NOT state.revision: a put can land between
                # the arm and this read — its event is queued in the
                # watch, and a floor above the arm revision would skip
                # it on a reconnect before the pump delivers.
                result = {"id": pump_watch.id,
                          "rev": pump_watch.arm_rev}
            elif op == "repl_subscribe":
                # Same ordering contract as watch: the snapshot that
                # heads the feed must not hit the wire before the
                # create-reply the follower is blocking on.
                pump_feed = self.state.repl_subscribe()
                with watches_lock:
                    feeds[pump_feed.id] = pump_feed
                result = pump_feed.id
            elif tp is not None and trace.enabled():
                # Request-scoped op carrying trace context: run it as a
                # child span of the caller's rpc/train span. Untraced
                # callers skip the span (no per-keepalive root-trace
                # noise in the flight recorder).
                with trace.attach(tp), trace.span(f"coord.{op}", op=op):
                    result = self._dispatch(conn, send_lock, watches,
                                            watches_lock, op, msg)
            else:
                result = self._dispatch(conn, send_lock, watches,
                                        watches_lock, op, msg)
            reply = {"id": req_id, "ok": True, "result": result,
                     "term": my_term}
        except Exception as e:  # noqa: BLE001 — remote surface must not die
            reply = {"id": req_id, "ok": False, "error": str(e),
                     "term": my_term}
        try:
            wire.send_msg(conn, send_lock, reply)
        except (wire.WireError, OSError):
            # The connection died under the reply: nothing will pump
            # these — cancel now rather than waiting for the reader
            # thread's cleanup to notice.
            if pump_watch is not None:
                pump_watch.cancel()
            if pump_feed is not None:
                pump_feed.cancel()
            return
        if pump_watch is not None:
            threading.Thread(
                target=self._pump_watch,
                args=(conn, send_lock, watches, watches_lock, pump_watch),
                name=f"coordd-watch-{pump_watch.id}",
                daemon=True,
            ).start()
        if pump_feed is not None:
            threading.Thread(
                target=self._pump_repl,
                args=(conn, send_lock, feeds, watches_lock, pump_feed),
                name=f"coordd-repl-{pump_feed.id}",
                daemon=True,
            ).start()

    def _dispatch(self, conn, send_lock, watches, watches_lock, op: str, msg: dict):
        st = self.state
        if op == "put":
            f = chaos.hit("coord.put", msg.get("key", ""))
            if f is not None and f.action == "kill_primary":
                # Die mid-write: the put IS applied (WAL flushed before
                # ack — same durability a SIGKILL after fs flush gives)
                # but no ack ever leaves and the whole server goes down
                # with it. Clients see a dead primary; a standby's
                # probes start failing from this instant.
                st.put(msg["key"], msg["value"], msg.get("lease", 0))
                threading.Thread(target=self.close,
                                 name="chaos-kill-primary",
                                 daemon=True).start()
                raise OSError("chaos: primary killed mid-write")
            rev = st.put(msg["key"], msg["value"], msg.get("lease", 0))
            if msg.get("sync"):
                # Synchronous replication (the raft-commit analog): ack
                # only after every WAL follower attached at the barrier
                # mirrored the write. Conservative: waits through the
                # current sequence, which includes this record.
                timeout = msg.get("sync_timeout")
                if not st.wait_replicated(
                        timeout=None if timeout is None
                        else float(timeout),
                        min_followers=int(
                            msg.get("sync_min_followers", 0))):
                    raise RuntimeError(
                        f"sync put {msg['key']!r}: replication not "
                        f"acknowledged in time (write IS applied on "
                        f"the primary; a failover before the mirror "
                        f"catches up may lose it)")
            return rev
        if op == "range":
            res = st.range(msg["key"], RangeOptions.from_wire(msg.get("options", {})))
            return {
                "items": [_item_wire(it) for it in res.items],
                "count": res.count,
                "revision": res.revision,
            }
        if op == "delete":
            return st.delete(msg["key"], RangeOptions.from_wire(msg.get("options", {})))
        if op == "grant":
            return st.grant(msg["ttl"])
        if op == "keepalive":
            return st.keepalive(msg["lease"])
        if op == "revoke":
            st.revoke(msg["lease"])
            return None
        if op == "watch_cancel":
            with watches_lock:
                w = watches.pop(msg["watch"], None)
            if w is not None:
                w.cancel()
            return None
        if op == "member_add":
            m = st.member_add(msg["name"], msg["peer_addr"], msg.get("metadata") or {})
            return _member_wire(m)
        if op == "member_promote":
            return _member_wire(st.member_promote(msg["member"]))
        if op == "member_remove":
            return st.member_remove(msg["member"])
        if op == "member_list":
            return [_member_wire(m) for m in st.member_list()]
        if op == "barrier":
            return st.barrier(msg["name"], msg["count"], msg.get("timeout"))
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")

    def _pump_watch(self, conn, send_lock, watches, watches_lock, w: Watch) -> None:
        while True:
            batch = w.get(timeout=1.0)
            if w.closed and not batch:
                return
            if not batch:
                continue
            push = {
                "watch": w.id,
                "events": [
                    {"type": ev.type.value, "key": ev.key, "value": ev.value,
                     "mod_rev": ev.mod_rev}
                    for ev in batch
                ],
            }
            try:
                wire.send_msg(conn, send_lock, push)
            except (wire.WireError, OSError):
                w.cancel()
                with watches_lock:
                    watches.pop(w.id, None)
                return

    def _pump_repl(self, conn, send_lock, feeds, watches_lock,
                   feed) -> None:
        """Stream a ReplFeed to a WAL follower. A follower that stops
        draining eventually backs TCP up; a send failure cancels the
        feed (it re-syncs from a fresh snapshot on reconnect). The idle
        tick is TTL-derived (:func:`_repl_idle_tick`) so small
        ``witness_ttl`` configs don't flap the follower vote."""
        tick = _repl_idle_tick(self._witness_ttl)
        while True:
            batch = feed.get(timeout=tick)
            if feed.closed and not batch:
                return
            if not batch:
                # Idle tick: heartbeat the follower. Its repl_pong
                # round-trip is the liveness proof the quorum loop
                # counts as the standby's vote — a quiet cluster must
                # not look like a partitioned one.
                try:
                    wire.send_msg(conn, send_lock,
                                  {"repl_hb": feed.id})
                except (wire.WireError, OSError):
                    feed.cancel()
                    with watches_lock:
                        feeds.pop(feed.id, None)
                    return
                continue
            push = {"repl": feed.id,
                    "items": [{"kind": k, "data": d, "seq": s}
                              for k, d, s in batch]}
            try:
                wire.send_msg(conn, send_lock, push)
            except (wire.WireError, OSError):
                feed.cancel()
                with watches_lock:
                    feeds.pop(feed.id, None)
                return

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # shutdown() before close() throughout: accept/recv-parked
        # threads are not woken by close() alone and would linger as
        # wedged daemons (the chaos soak's thread-hygiene invariant).
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.state.close()
