"""Warm-standby coordinator — failover, not just restart.

The reference got control-plane availability from raft quorum: any
member's death left the registry/store served by the survivors
(/root/reference/cluster/cluster.go:120-147). This rebuild's seed is a
single coordination service with a WAL (coord/core.py); round 2 made it
survive its own *restart*, but a permanently dead coordinator still took
registry, leases, KV and barriers with it (VERDICT r2 missing #1).

:class:`Standby` closes that gap for the deployment shape the WAL
already implies — a shared ``data_dir`` (same host, or any shared
filesystem):

- it health-probes the primary on a short interval;
- after ``failure_threshold`` consecutive probe failures it PROMOTES:
  starts a :class:`CoordServer` on its own address over the shared
  ``data_dir``, replaying snapshot + WAL — registrations, leases, KV
  and membership reappear (leases get one fresh TTL of grace, so live
  clients' keepalives reclaim them before expiry);
- clients constructed with the endpoint list (``RemoteCoord([primary,
  standby])`` — ``cluster.join`` wires this from
  ``initial_cluster_client_urls``) ride their reconnect loop onto the
  standby with no client-side action; re-watch + snapshot-then-delta
  semantics make watch consumers whole.

Split-brain scope: ONE standby per primary, and the old primary must
not be restarted on its old address after a takeover (its WAL is now
stale). The reference's raft gave fencing for free; here the operator
contract is documented instead — matching the single-writer WAL model.
"""

from __future__ import annotations

import socket
import threading

from ptype_tpu import logs
from ptype_tpu.coord import wire
from ptype_tpu.coord.service import CoordServer

log = logs.get_logger("coord.standby")


class Standby:
    """Monitor ``primary_address``; take over on ``listen_address``.

    ``data_dir`` must be the primary's coordination data dir (the seed
    passes ``<platform.data_dir>/coord`` — cluster.py). Promotion is
    observable via :attr:`promoted` (a ``threading.Event``) and
    :attr:`server` (the live :class:`CoordServer` after takeover).
    """

    def __init__(self, primary_address: str, listen_address: str,
                 data_dir: str, check_interval: float = 1.0,
                 failure_threshold: int = 3,
                 probe_timeout: float = 2.0):
        self.primary_address = primary_address
        self.listen_address = listen_address
        self.data_dir = data_dir
        self.check_interval = check_interval
        self.failure_threshold = failure_threshold
        self.probe_timeout = probe_timeout
        self.promoted = threading.Event()
        self.server: CoordServer | None = None
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._monitor, name="coord-standby", daemon=True)
        self._thread.start()
        log.info("standby watching primary",
                 kv={"primary": primary_address,
                     "standby": listen_address})

    # ------------------------------------------------------------ probes

    def _probe(self) -> bool:
        """One liveness probe: full request/response, not just a TCP
        accept — a wedged primary that accepts but never answers is
        dead for clients and must fail the probe too."""
        host, _, port = self.primary_address.rpartition(":")
        try:
            sock = socket.create_connection(
                (host, int(port)), timeout=self.probe_timeout)
        except OSError:
            return False
        try:
            sock.settimeout(self.probe_timeout)
            wire.send_msg(sock, threading.Lock(),
                          {"op": "member_list", "id": 1})
            wire.recv_msg(sock)
            return True
        except (wire.WireError, OSError):
            return False
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _monitor(self) -> None:
        failures = 0
        while not self._closed.is_set():
            if self._probe():
                failures = 0
            else:
                failures += 1
                log.debug("primary probe failed",
                          kv={"n": failures,
                              "threshold": self.failure_threshold})
                if failures >= self.failure_threshold:
                    if self._promote():
                        return
                    # Promotion refused (WAL fence held by a live
                    # primary) or failed (port busy): keep monitoring
                    # and retry — a dying monitor thread would leave
                    # the cluster with no failover coverage at all.
            self._closed.wait(self.check_interval)

    def _promote(self) -> bool:
        if self._closed.is_set():
            return True
        log.info("promoting standby: primary declared dead",
                 kv={"primary": self.primary_address,
                     "standby": self.listen_address})
        try:
            # The WAL-dir flock (coord/core.py) is the fence: if the
            # primary is wedged-but-alive and still holds it, this
            # raises instead of double-writing the WAL — probes keep
            # running and promotion retries once the primary truly dies.
            self.server = CoordServer(self.listen_address,
                                      data_dir=self.data_dir)
        except Exception as e:  # noqa: BLE001 — retried by the monitor
            log.warning("standby promotion failed; will retry",
                        kv={"err": str(e)})
            return False
        self.promoted.set()
        return True

    # ------------------------------------------------------------- admin

    def promote(self, timeout: float = 30.0) -> "CoordServer":
        """Operator-triggered switchover — the analog of the reference's
        learner PROMOTE (cluster.go:183-195): stop monitoring, wait for
        the primary to release the WAL fence (shut it down first), and
        serve. Returns the live server; raises on fence timeout."""
        import time as _time

        if self.promoted.is_set() and self.server is not None:
            return self.server  # idempotent: already serving
        self._closed.set()  # stop the monitor; we promote deliberately
        self._thread.join(timeout=5)
        # The monitor may have completed an AUTOMATIC promotion while we
        # were joining it — spinning against our own server's WAL fence
        # would misdiagnose as "primary still alive".
        if self.promoted.is_set() and self.server is not None:
            return self.server
        deadline = _time.monotonic() + timeout
        while True:
            try:
                self.server = CoordServer(self.listen_address,
                                          data_dir=self.data_dir)
                break
            except Exception as e:  # noqa: BLE001 — fence still held
                if _time.monotonic() > deadline:
                    # Re-arm automatic failover before surfacing the
                    # error: a caller that catches it expects the
                    # standby to keep guarding the (still-live)
                    # primary, and the monitor thread was stopped
                    # above.
                    self._closed.clear()
                    self._thread = threading.Thread(
                        target=self._monitor, name="coord-standby",
                        daemon=True)
                    self._thread.start()
                    raise RuntimeError(
                        f"promote: primary still holds the WAL fence "
                        f"after {timeout}s — shut it down first"
                    ) from e
                _time.sleep(0.2)
        self.promoted.set()
        log.info("standby promoted by operator",
                 kv={"standby": self.listen_address})
        return self.server

    def close(self) -> None:
        """Stop monitoring; shut the promoted server down if any."""
        self._closed.set()
        self._thread.join(timeout=5)
        if self.server is not None:
            self.server.close()
