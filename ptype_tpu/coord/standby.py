"""Warm-standby coordinator — failover, not just restart.

The reference got control-plane availability from raft quorum: any
member's death left the registry/store served by the survivors
(/root/reference/cluster/cluster.go:120-147). This rebuild's seed is a
single coordination service with a WAL (coord/core.py); round 2 made it
survive its own *restart*, but a permanently dead coordinator still took
registry, leases, KV and barriers with it (VERDICT r2 missing #1).

:class:`Standby` closes that gap in two deployment shapes — a shared
``data_dir`` (same host, or any shared filesystem), or, with
``replicate=True``, a LOCAL ``data_dir`` kept current by streaming the
primary's WAL over TCP (:class:`WalFollower` — cross-host failover
with no shared storage):

- it health-probes the primary on a short interval;
- after ``failure_threshold`` consecutive probe failures it PROMOTES:
  starts a :class:`CoordServer` on its own address over the shared
  ``data_dir``, replaying snapshot + WAL — registrations, leases, KV
  and membership reappear (leases get one fresh TTL of grace, so live
  clients' keepalives reclaim them before expiry);
- clients constructed with the endpoint list (``RemoteCoord([primary,
  standby])`` — ``cluster.join`` wires this from
  ``initial_cluster_client_urls``) ride their reconnect loop onto the
  standby with no client-side action; re-watch + snapshot-then-delta
  semantics make watch consumers whole.

Split-brain scope: ONE standby per primary. Promotion bumps the
persisted fencing *term* (coord/core.py) — the epoch raft's leader
election gave the reference for free
(/root/reference/cluster/cluster.go:120-147). Clients stamp the
highest term they have seen on every request, so an old primary
restarted on its old address after a takeover (stale WAL, stale term)
refuses them and they re-dial to the current primary
(coord/remote.py). In shared-dir mode the WAL-dir flock additionally
fences a wedged-but-alive primary at the filesystem. The residual gap
is inherent to two nodes: during a live network partition, clients
that can ONLY reach the old primary (and have never seen the new
term) keep being served by it — resolving that needs a quorum tier,
which is why auto-promotion still requires a synced mirror and the
operator path refuses while the primary answers.
"""

from __future__ import annotations

import json
import os
import socket
import threading

from ptype_tpu import chaos, logs, retry
from ptype_tpu.coord import wire
from ptype_tpu.coord.core import fsync_dir
from ptype_tpu.coord.service import CoordServer

log = logs.get_logger("coord.standby")


class WalFollower:
    """Streams the primary's WAL into a LOCAL data_dir.

    The shared-``data_dir`` standby assumes one filesystem; this is the
    cross-host variant: subscribe to the primary's replication feed
    (``repl_subscribe`` — coord/core.py), write the initial snapshot to
    ``coord.snap``, append every subsequent WAL record to ``coord.wal``
    — exactly the files :class:`~ptype_tpu.coord.core.CoordState`
    replays, so a promotion over the mirror recovers the full registry/
    lease/KV/member state with no shared storage. On any disconnect it
    re-subscribes: the fresh head snapshot replaces the mirror
    atomically, so a missed-records gap can never go unnoticed.
    ``synced`` is set once the first snapshot has been mirrored.
    """

    def __init__(self, primary_address: str, data_dir: str,
                 reconnect_delay: float = 0.5,
                 connect_timeout: float = 2.0,
                 fsync: bool = False):
        self.primary_address = primary_address
        self.data_dir = data_dir
        self.reconnect_delay = reconnect_delay
        self.connect_timeout = connect_timeout
        #: fsync mirror writes before acknowledging them. Required in
        #: wal_fsync deployments: a sync-put ack asserts the record is
        #: DURABLE on this host, which a page-cache flush() is not
        #: under power loss.
        self._fsync = fsync
        self.synced = threading.Event()
        self._closed = threading.Event()
        self._sock: socket.socket | None = None
        os.makedirs(data_dir, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="coord-wal-follower", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._closed.is_set():
            try:
                self._follow_once()
            except (wire.WireError, OSError) as e:
                log.debug("wal follower disconnected; retrying",
                          kv={"err": str(e)})
            self._closed.wait(self.reconnect_delay)

    def _follow_once(self) -> None:
        host, _, port = self.primary_address.rpartition(":")
        sock = socket.create_connection((host, int(port)),
                                        timeout=self.connect_timeout)
        self._sock = sock
        wal = None
        try:
            sock.settimeout(self.connect_timeout)
            lock = threading.Lock()
            wire.send_msg(sock, lock, {"op": "repl_subscribe", "id": 1})
            reply = wire.recv_msg(sock)
            if not reply.get("ok"):
                raise wire.WireError(
                    f"repl_subscribe refused: {reply.get('error')}")
            # Our feed id: stamped on every ack so the primary credits
            # exactly this feed (a connection may carry several).
            feed_id = reply.get("result")
            # Stream forever; recv blocks until the primary pushes (the
            # pump batches). Timeout only guards the handshake — a
            # quiet-but-alive primary must not look dead here.
            sock.settimeout(None)
            while not self._closed.is_set():
                msg = wire.recv_msg(sock)
                # Re-check AFTER the blocking recv: close() may have
                # promoted this data_dir to a live CoordState while we
                # were parked — one more mirror write would truncate
                # the WAL underneath the new primary.
                if self._closed.is_set():
                    return
                if "repl_hb" in msg:
                    # Liveness heartbeat: the round-trip is our vote
                    # for the primary in the witness quorum — answer
                    # promptly, mirror nothing.
                    wire.send_msg(sock, lock,
                                  {"op": "repl_pong", "feed": feed_id})
                    continue
                last_seq = None
                for item in msg.get("items", ()):
                    if item["kind"] == "snap":
                        wal = self._mirror_snapshot(item["data"], wal)
                        self.synced.set()
                    else:
                        if wal is None:
                            wal = open(self._wal_path, "a",
                                       encoding="utf-8")
                        wal.write(json.dumps(
                            item["data"], separators=(",", ":")) + "\n")
                        wal.flush()
                    if item.get("seq") is not None:
                        last_seq = item["seq"]
                if last_seq is not None:
                    if self._fsync and wal is not None:
                        # The ack asserts durability; in fsync
                        # deployments flush-to-page-cache isn't it.
                        os.fsync(wal.fileno())
                    # Everything through last_seq is durable in the
                    # mirror: acknowledge so the primary's sync-put
                    # barrier (state.wait_replicated) can release.
                    wire.send_msg(sock, lock,
                                  {"op": "repl_ack", "seq": last_seq,
                                   "feed": feed_id})
        finally:
            self._sock = None
            if wal is not None:
                wal.close()
            try:
                sock.close()
            except OSError:
                pass

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.data_dir, "coord.wal")

    def _mirror_snapshot(self, snap: dict, wal):
        """Replace the mirror: truncate the WAL (stamping the
        snapshot's generation header) BEFORE replacing the snapshot. A
        crash between the two leaves the OLD snapshot with a
        new-generation empty WAL — replay skips the mismatched WAL and
        recovers the stale-but-consistent old snapshot; the follower
        re-syncs from a fresh snapshot on its next connect anyway. The
        reverse order (new snap + old records) would re-apply folded
        records and diverge on replay."""
        if wal is not None:
            wal.close()
        gen = snap.get("wal_gen", 0)
        wal = open(self._wal_path, "w", encoding="utf-8")
        wal.write(json.dumps({"o": "hdr", "gen": gen},
                             separators=(",", ":")) + "\n")
        wal.flush()
        if self._fsync:
            os.fsync(wal.fileno())
        tmp = os.path.join(self.data_dir, "coord.snap.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
            if self._fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.data_dir, "coord.snap"))
        if self._fsync:
            # The rename itself lives in the directory entry; without
            # this the mirrored snapshot can vanish on power loss.
            fsync_dir(self.data_dir)
        return wal

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def close(self) -> bool:
        """Stop mirroring. Returns True when the follower thread has
        actually exited — promotion must not serve over this data_dir
        while a parked reader could still wake up and truncate it."""
        self._closed.set()
        sock = self._sock
        if sock is not None:
            try:
                # shutdown() interrupts a thread parked in recv(2);
                # close() alone does not.
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=5)
        return not self._thread.is_alive()


class Standby:
    """Monitor ``primary_address``; take over on ``listen_address``.

    ``data_dir`` must be the primary's coordination data dir (the seed
    passes ``<platform.data_dir>/coord`` — cluster.py). Promotion is
    observable via :attr:`promoted` (a ``threading.Event``) and
    :attr:`server` (the live :class:`CoordServer` after takeover).
    """

    def __init__(self, primary_address: str, listen_address: str,
                 data_dir: str, check_interval: float = 1.0,
                 failure_threshold: int = 3,
                 probe_timeout: float = 2.0,
                 replicate: bool = False,
                 register: bool = True,
                 succession_grace: float = 10.0,
                 fsync: bool = False,
                 witness_addr: str | None = None,
                 witness_ttl: float = 3.0):
        self.primary_address = primary_address
        self.listen_address = listen_address
        self.data_dir = data_dir
        self.check_interval = check_interval
        self.failure_threshold = failure_threshold
        self.probe_timeout = probe_timeout
        self.promoted = threading.Event()
        self.server: CoordServer | None = None
        self._closed = threading.Event()
        # Learner lifecycle (ref: memberAdd-as-learner → catch up →
        # MemberPromote, cluster.go:120-147, 183-195): the standby
        # joins the primary's membership as a learner, and is promoted
        # to a promote-eligible member only once its mirror caught up —
        # making "which standbys can take over right now" observable
        # through member_list, and letting clients' endpoint discovery
        # pick up standbys attached at runtime.
        self._register = register
        self.member_id: int | None = None
        self._member_promoted = False
        self._admin = None  # lazy RemoteCoord to the primary
        #: Promote-eligible peer standbys [(member_id, addr), ...],
        #: cached from the live primary's membership each probe round.
        #: On primary death this is the succession list: the LOWEST
        #: member id (most senior attach) promotes; juniors defer,
        #: adopt the winner as their new primary, and keep guarding —
        #: deterministic election without a quorum tier (the raft-
        #: election analog; ref cluster.go:120-147).
        self._peer_standbys: list[tuple[int, str]] = []
        self._defer_deadline: float | None = None
        #: Per-senior grace window (seconds) before a junior stops
        #: waiting for an unresponsive senior and promotes itself;
        #: floored at 2 full detection periods.
        self.succession_grace = succession_grace
        #: WAL durability mode for the server this standby starts at
        #: promotion (match the primary's ``wal_fsync`` setting).
        self._fsync = fsync
        #: Witness (coord/witness.py): promotion additionally requires
        #: acquiring the witness lease — the second vote of the
        #: {primary, standby, witness} majority. Without it a standby
        #: partitioned AWAY from a healthy primary could promote and
        #: split the brain for clients that can reach only one side.
        self._witness_addr = witness_addr
        self._witness_ttl = witness_ttl
        # replicate=True: ``data_dir`` is LOCAL and a WalFollower
        # mirrors the primary's WAL into it over TCP — the cross-host
        # deployment. False: ``data_dir`` IS the primary's (shared
        # filesystem), and the WAL-dir flock doubles as the
        # split-brain fence.
        self._replicate = replicate
        self.follower: WalFollower | None = None
        self._thread: threading.Thread | None = None
        self._start_guarding()  # creates the follower in wal-stream mode
        log.info("standby watching primary",
                 kv={"primary": primary_address,
                     "standby": listen_address,
                     "mode": "wal-stream" if replicate else "shared-dir"})

    def _ensure_follower(self) -> None:
        """wal-stream mode: make sure a LIVE follower is mirroring —
        replaces one closed by a failed/deferred promotion attempt
        (guarding with a frozen mirror would promote stale state on
        the next primary death)."""
        if not self._replicate:
            return
        if self.follower is not None and not self.follower.closed:
            return
        if self.follower is not None and not self.follower.close():
            # The old reader thread hasn't exited: a replacement would
            # make TWO writers on one mirror (the zombie can wake and
            # truncate coord.wal mid-write). Retry on the next probe
            # round instead.
            log.warning("follower re-arm deferred: old reader thread "
                        "still live")
            return
        self.follower = WalFollower(self.primary_address, self.data_dir,
                                    fsync=self._fsync)

    def _start_guarding(self) -> None:
        """(Re)arm everything a guarding standby needs: the probe
        monitor, and in wal-stream mode a live follower. Called at
        construction and after every failed promotion path — partial
        re-arms (monitor without follower) would leave the standby
        silently guarding with a frozen mirror."""
        self._ensure_follower()
        self._closed.clear()
        self._thread = threading.Thread(
            target=self._monitor, name="coord-standby", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ probes

    def _probe(self, address: str | None = None) -> bool:
        """One liveness probe: full request/response, not just a TCP
        accept — a wedged primary that accepts but never answers is
        dead for clients and must fail the probe too."""
        host, _, port = (address or self.primary_address).rpartition(":")
        try:
            sock = socket.create_connection(
                (host, int(port)), timeout=self.probe_timeout)
        except OSError:
            return False
        try:
            sock.settimeout(self.probe_timeout)
            wire.send_msg(sock, threading.Lock(),
                          {"op": "member_list", "id": 1})
            wire.recv_msg(sock)
            return True
        except (wire.WireError, OSError):
            return False
        finally:
            try:
                sock.close()
            except OSError:
                pass

    @property
    def promote_eligible(self) -> bool:
        """True when promotion would recover full cluster state: always
        in shared-dir mode (the data_dir IS the primary's), and once
        the WAL mirror has received its first snapshot in wal-stream
        mode. The learner→member transition in the primary's
        membership mirrors this flag."""
        if self.promoted.is_set():
            return True
        if self.follower is not None:
            return self.follower.synced.is_set()
        return not self._replicate

    def _sync_membership(self) -> None:
        """Keep the standby's learner/member record on the (live)
        primary current. Called from the monitor after each successful
        probe; every step is retried on the next round on failure."""
        if not self._register:
            return
        from ptype_tpu.coord.remote import RemoteCoord
        from ptype_tpu.errors import CoordinationError

        try:
            if self._admin is not None and self._admin.closed:
                # The client gave up for good during a primary outage
                # that outlasted its reconnect window — it can never
                # serve another call; rebuild now that probes succeed.
                self._close_admin()
            if self._admin is None:
                self._admin = RemoteCoord(
                    [self.primary_address], dial_timeout=2.0,
                    request_timeout=5.0, reconnect_timeout=5.0)
            if self.member_id is None:
                # A previous incarnation of this standby (same address)
                # may still be registered: replace it, don't accumulate.
                for m in self._admin.member_list():
                    md = m.metadata or {}
                    if (md.get("role") == "standby"
                            and m.peer_addr == self.listen_address):
                        self._admin.member_remove(m.id)
                member = self._admin.member_add(
                    f"standby:{self.listen_address}", self.listen_address,
                    metadata={"role": "standby", "learner": True,
                              "mode": ("wal-stream" if self._replicate
                                       else "shared-dir")})
                self.member_id = member.id
                log.info("standby joined membership as learner",
                         kv={"member": member.id,
                             "addr": self.listen_address})
            if not self._member_promoted and self.promote_eligible:
                try:
                    self._admin.member_promote(self.member_id)
                except CoordinationError as e:
                    if "not found" in str(e):
                        # Our record was removed out from under us
                        # (operator cleanup, or a same-address dedup):
                        # forget the stale id so the next round
                        # re-registers instead of retrying it forever.
                        self.member_id = None
                    raise
                self._member_promoted = True
                log.info("standby promoted to member: mirror caught up",
                         kv={"member": self.member_id})
            # Refresh the succession list while the primary can still
            # tell us — it is read AFTER the primary dies.
            self._peer_standbys = [
                (m.id, m.peer_addr) for m in self._admin.member_list()
                if (m.metadata or {}).get("role") == "standby"
                and (m.metadata or {}).get("learner") is False
                and m.peer_addr != self.listen_address]
        except CoordinationError as e:
            log.debug("standby membership sync failed; retrying",
                      kv={"err": str(e)})

    def _close_admin(self) -> None:
        if self._admin is not None:
            self._admin.close()
            self._admin = None

    def _monitor(self) -> None:
        failures = 0
        while not self._closed.is_set():
            if self._probe():
                failures = 0
                self._defer_deadline = None
                # The primary is back after a failed/deferred promotion
                # attempt that closed the follower: resume mirroring.
                self._ensure_follower()
                self._sync_membership()
            else:
                failures += 1
                log.debug("primary probe failed",
                          kv={"n": failures,
                              "threshold": self.failure_threshold})
                if failures >= self.failure_threshold:
                    verdict = self._defer_to_senior()
                    if verdict == "adopted":
                        # Fresh primary: it must fail threshold
                        # CONSECUTIVE probes of its own before we act
                        # on it (a single slow post-takeover probe is
                        # not a death).
                        failures = 0
                    elif verdict == "defer":
                        pass
                    elif self._promote():
                        return
                    # Promotion refused (WAL fence held by a live
                    # primary) or failed (port busy): keep monitoring
                    # and retry — a dying monitor thread would leave
                    # the cluster with no failover coverage at all.
            self._closed.wait(self.check_interval)

    # ------------------------------------------------------- succession

    def _seniors(self) -> list[tuple[int, str]]:
        """Promote-eligible peer standbys senior to us (lower member
        id = earlier attach), in succession order. The current primary
        is excluded defensively — a stale cache entry for it must not
        make us "re-adopt" our own primary."""
        peers = [(mid, a) for mid, a in self._peer_standbys
                 if a != self.primary_address]
        if self.member_id is None:
            # We never registered: every known eligible peer outranks
            # us — promoting over their heads would split the brain.
            return sorted(peers)
        return sorted((mid, a) for mid, a in peers
                      if mid < self.member_id)

    def _defer_to_senior(self) -> str | None:
        """Succession arbitration for MULTIPLE standbys on one primary
        (reachable since standbys attach dynamically): only the most
        senior eligible standby promotes; juniors defer — and when the
        winner starts serving, they ADOPT it as their new primary and
        keep guarding (the self-healing chain). Returns "adopted" when
        a promoted senior became our new primary, "defer" while inside
        a senior's grace window, and None when this standby should
        promote."""
        seniors = self._seniors()
        if not seniors:
            self._defer_deadline = None
            return None
        for _, addr in seniors:
            if self._probe(addr):
                self._adopt_primary(addr)
                return "adopted"
        # No senior is serving yet. Give each of them a staggered
        # grace window to come up before assuming they died with the
        # primary and promoting anyway — deterministic, no
        # coordination needed. The window floor is generous (a
        # senior's promotion replays its whole mirror, which can take
        # tens of seconds at scale) — and even if we DO promote while
        # a slow senior is mid-replay, our rank-based term bump
        # (_promote) lands us on a strictly higher term, so clients
        # fence whichever of us is superseded rather than splitting.
        import time as _time

        if self._defer_deadline is None:
            grace = max(
                self.succession_grace,
                2 * self.failure_threshold * self.check_interval)
            self._defer_deadline = (_time.monotonic()
                                    + len(seniors) * grace)
            log.info("standby deferring to senior peers",
                     kv={"seniors": [a for _, a in seniors],
                         "window_s": round(len(seniors) * grace, 1)})
        if _time.monotonic() < self._defer_deadline:
            return "defer"
        # Window expired: promote. Deliberately NOT clearing the
        # deadline — a transiently failed promotion must retry next
        # round, not re-arm a fresh multi-second window with nobody
        # serving. (It clears on probe success or adoption.)
        log.warning("senior standbys never took over; promoting",
                    kv={"seniors": [a for _, a in seniors]})
        return None

    def _adopt_primary(self, addr: str) -> None:
        """A senior peer has promoted: re-point at it and keep
        guarding — the standby chain re-forms without an operator."""
        log.info("adopting promoted peer as new primary",
                 kv={"old": self.primary_address, "new": addr})
        self.primary_address = addr
        self._defer_deadline = None
        self._close_admin()  # rebuilt against the new primary
        # Our member record rode the WAL mirror into the winner's
        # state, so member_id/_member_promoted stay valid.
        if self.follower is not None and self.follower.close():
            self.follower = None
        # A reader thread that refused to die leaves self.follower set
        # (closed, thread live): _ensure_follower's re-arm deferral
        # machinery retries on later rounds rather than risking two
        # writers on one mirror.
        self._ensure_follower()

    def _mirror_term(self) -> int:
        """Fencing term recorded in the mirrored snapshot (the
        primary's current term — terms only change at promotions,
        which always write a snapshot). 0 when unreadable."""
        try:
            with open(os.path.join(self.data_dir, "coord.snap"),
                      encoding="utf-8") as f:
                return int(json.load(f).get("term", 0))
        except (OSError, ValueError):
            return 0

    def _acquire_witness(self) -> bool:
        """Take the witness lease for the about-to-promote server (its
        bumped term). Grant = we are the majority side; refusal or an
        unreachable witness = no majority, DON'T promote: a healthy
        primary may be serving clients we cannot see."""
        if self._witness_addr is None:
            return True
        from ptype_tpu.coord import witness as _witness

        new_term = self._mirror_term() + 1 + len(self._seniors())
        try:
            reply = _witness.acquire(
                self._witness_addr, candidate=self.listen_address,
                term=new_term, timeout=max(1.0, self._witness_ttl))
        except (wire.WireError, OSError) as e:
            log.warning(
                "standby refusing promotion: witness unreachable "
                "(no majority)", kv={"err": str(e)})
            return False
        if not reply.get("granted"):
            log.warning(
                "standby refusing promotion: witness lease refused — "
                "the primary (or a peer) still holds it",
                kv={"holder": reply.get("holder"),
                    "term": reply.get("term"),
                    "reason": reply.get("reason")})
            return False
        return True

    def _promote(self) -> bool:
        if self._closed.is_set():
            return True
        if self.follower is not None and not self.follower.synced.is_set():
            # The mirror never received a snapshot (primary died inside
            # the first connect window, or was never reachable from
            # this host): promoting would serve EMPTY cluster state —
            # silently wiping the control plane. Refuse and keep
            # probing; an operator can still force it via promote().
            # Checked BEFORE the witness acquire: this standby is not
            # going to promote, so it must not consume the lease/term —
            # a lease taken here would brand a later-returning primary
            # "superseded" by a successor that never serves, turning a
            # recoverable outage into a permanently fenced cluster
            # (ADVICE.md, standby._promote ordering).
            log.warning("standby refusing auto-promotion: WAL mirror "
                        "never synced", kv={"primary":
                                            self.primary_address})
            return False
        if not self._acquire_witness():
            # Keep guarding; the witness grants once the primary's
            # lease truly lapses (it is still renewing = still alive).
            return False
        log.info("promoting standby: primary declared dead",
                 kv={"primary": self.primary_address,
                     "standby": self.listen_address})
        if self.follower is not None:
            # Stop mirroring before serving over the mirror: the
            # follower's reconnect loop re-truncating coord.wal under
            # a live CoordState would corrupt the new primary.
            if not self.follower.close():
                # A reader refusing to die (wedged primary holding the
                # TCP stream mid-push) could wake and truncate the
                # mirror under the promoted server — retry next probe
                # round instead of serving over contested files.
                log.warning("standby promotion deferred: follower "
                            "thread still live")
                return False
            self.follower = None
        try:
            # The WAL-dir flock (coord/core.py) is the shared-dir
            # fence: if the primary is wedged-but-alive and still holds
            # it, this raises instead of double-writing the WAL — probes
            # keep running and promotion retries once the primary truly
            # dies. bump_term marks this server the successor so
            # clients refuse any stale primary (the wal-stream fence);
            # a junior promoting past unresponsive seniors skips their
            # term slots so a slow senior finishing its own promotion
            # later can never land on the same term.
            self.server = CoordServer(self.listen_address,
                                      data_dir=self.data_dir,
                                      bump_term=1 + len(self._seniors()),
                                      fsync=self._fsync,
                                      witness_addr=self._witness_addr,
                                      witness_ttl=self._witness_ttl,
                                      witness_holder=self.listen_address)
        except Exception as e:  # noqa: BLE001 — retried by the monitor
            log.warning("standby promotion failed; will retry",
                        kv={"err": str(e)})
            # Resume mirroring (wal-stream): the primary may come back
            # (no takeover happened) and a monitor guarding a frozen
            # mirror would promote stale state on the NEXT death.
            self._ensure_follower()
            return False
        self.promoted.set()
        chaos.note_ok("coord.failover", self.listen_address)
        self._close_admin()  # it pointed at the dead primary
        self._retire_own_member_record()
        return True

    # ------------------------------------------------------------- admin

    def promote(self, timeout: float = 30.0,
                force: bool = False) -> "CoordServer":
        """Operator-triggered switchover — the analog of the reference's
        learner PROMOTE (cluster.go:183-195): stop monitoring, wait for
        the primary to release the WAL fence (shut it down first), and
        serve. Returns the live server; raises on fence timeout.
        ``force=True`` overrides the never-synced-mirror refusal (for
        deliberately bootstrapping an empty control plane)."""
        import time as _time

        if self.promoted.is_set() and self.server is not None:
            return self.server  # idempotent: already serving
        self._closed.set()  # stop the monitor; we promote deliberately
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # The monitor is MID-automatic-promotion (CoordServer
            # construction can replay a large WAL); racing it would
            # spin against our own server's flock and misdiagnose as
            # "primary still alive". Wait for its outcome — but a
            # monitor whose attempt FAILS exits cleanly (it saw
            # _closed) without promoting: fall through to the
            # deliberate promotion below rather than misdiagnosing a
            # healthy standby as wedged.
            deadline = _time.monotonic() + timeout
            while (self._thread.is_alive()
                   and _time.monotonic() < deadline):
                if self.promoted.wait(timeout=0.2):
                    break
            if self._thread.is_alive() and not self.promoted.is_set():
                raise RuntimeError(
                    "promote: standby monitor wedged mid-promotion — "
                    "inspect the coordinator data_dir before retrying")
        # The monitor may have completed an AUTOMATIC promotion while we
        # were joining/waiting on it.
        if self.promoted.is_set() and self.server is not None:
            return self.server
        if self.follower is not None:
            if not force and not self.follower.synced.is_set():
                # Same refusal as auto-promotion: a mirror that never
                # received a snapshot holds NOTHING — serving it would
                # silently wipe the control plane.
                self._start_guarding()
                raise RuntimeError(
                    "promote: WAL mirror never synced — promoting would "
                    "serve an empty control plane (force=True overrides)")
            # Cross-host mode has no flock fence to refuse a split
            # brain — the probe is the only guard. Refuse while the
            # primary still answers, and keep guarding.
            if self._probe():
                self._start_guarding()
                raise RuntimeError(
                    "promote: primary is still alive — shut it down "
                    "first (wal-stream mode has no fence)")
            if not self.follower.close():
                self._start_guarding()
                raise RuntimeError(
                    "promote: follower reader thread still live — a "
                    "late wake-up would truncate the mirror under the "
                    "promoted server; retry once it exits")
            self.follower = None
        deadline = _time.monotonic() + timeout
        # Deliberate switchover still takes the witness vote (unless
        # forced): the lease frees one TTL after the primary was shut
        # down, so retry within the operator's timeout.
        if self._witness_addr is not None and not force:
            witness_bo = retry.Backoff(
                base=min(1.0, self._witness_ttl / 2), cap=1.0)
            while not self._acquire_witness():
                if _time.monotonic() > deadline:
                    self._start_guarding()
                    raise RuntimeError(
                        "promote: witness lease not acquired — the "
                        "primary still holds it (shut it down and let "
                        "its TTL lapse) or the witness is unreachable "
                        "(force=True overrides)")
                witness_bo.sleep()
        start_bo = retry.Backoff(base=0.2, cap=1.0)
        while True:
            try:
                self.server = CoordServer(
                    self.listen_address, data_dir=self.data_dir,
                    bump_term=1 + len(self._seniors()),
                    fsync=self._fsync,
                    witness_addr=self._witness_addr,
                    witness_ttl=self._witness_ttl,
                    witness_holder=self.listen_address)
                break
            except Exception as e:  # noqa: BLE001 — fence / transient
                if _time.monotonic() > deadline:
                    # Re-arm automatic failover (monitor + follower)
                    # before surfacing the error: a caller that
                    # catches it expects the standby to keep guarding
                    # the (still-live) primary.
                    self._start_guarding()
                    if self._replicate:
                        # wal-stream: the mirror dir is LOCAL — no
                        # flock contention with the primary is
                        # possible, so the failure is this host's own
                        # (port bind, replay error). Say so; "primary
                        # holds the fence" would send the operator to
                        # the wrong host.
                        raise RuntimeError(
                            f"promote: standby server failed to start "
                            f"after {timeout}s (wal-stream mode; local "
                            f"cause — last error: {e})"
                        ) from e
                    raise RuntimeError(
                        f"promote: primary still holds the WAL fence "
                        f"after {timeout}s — shut it down first "
                        f"(last error: {e})"
                    ) from e
                start_bo.sleep()
        self.promoted.set()
        chaos.note_ok("coord.failover", self.listen_address)
        self._close_admin()  # it pointed at the superseded primary
        self._retire_own_member_record()
        log.info("standby promoted by operator",
                 kv={"standby": self.listen_address})
        return self.server

    def _retire_own_member_record(self) -> None:
        """We are the primary now: drop our own role=standby member
        record from OUR state (it rode the mirror in). Leaving it
        would poison peers' succession lists with the current primary
        posing as an eligible standby — every later failover would
        burn a grace window probing it (or worse, 're-adopt' it)."""
        if self.member_id is None or self.server is None:
            return
        try:
            self.server.state.member_remove(self.member_id)
        except Exception as e:  # noqa: BLE001 — cosmetic cleanup
            log.debug("could not retire own standby member record",
                      kv={"err": str(e)})
        self.member_id = None
        self._member_promoted = False

    def close(self) -> None:
        """Stop monitoring; shut the promoted server down if any.
        Deregisters from the (live) primary's membership — a detached
        standby must not look promote-eligible to endpoint discovery."""
        self._closed.set()
        self._thread.join(timeout=5)
        if self._admin is not None and self.member_id is not None:
            from ptype_tpu.errors import CoordinationError

            try:
                self._admin.member_remove(self.member_id)
            except CoordinationError:
                pass  # best-effort: primary may already be gone
        self._close_admin()
        if self.follower is not None:
            self.follower.close()
            self.follower = None
        if self.server is not None:
            self.server.close()
