"""In-process coordination backend.

Wraps a :class:`CoordState` directly — the single-process analog of the
reference's embedded etcd (every ``Cluster`` in one process shares the
named state, the way the reference's test suite shared one embedded member
across suites, registry_test.go:17-39).
"""

from __future__ import annotations

import threading

from ptype_tpu.coord.api import CoordBackend
from ptype_tpu.coord.core import CoordState, Member, RangeOptions, RangeResult, Watch

_states: dict[str, CoordState] = {}
_states_lock = threading.Lock()


def local_coord(name: str = "default") -> "LocalCoord":
    """Return a backend over the process-local state named ``name``."""
    with _states_lock:
        state = _states.get(name)
        if state is None or state._closed.is_set():
            state = CoordState()
            _states[name] = state
    return LocalCoord(state)


def reset_local_coords() -> None:
    """Tear down all named local states (test isolation)."""
    with _states_lock:
        for state in _states.values():
            state.close()
        _states.clear()


class LocalCoord(CoordBackend):
    def __init__(self, state: CoordState | None = None):
        self.state = state or CoordState()

    def put(self, key: str, value: str, lease: int = 0,
            sync: bool = False,
            sync_timeout: float | None = None,
            sync_min_followers: int = 0) -> int:
        if sync_min_followers and not sync:
            raise ValueError(
                "sync_min_followers requires sync=True — without the "
                "barrier the floor would be silently ignored")
        rev = self.state.put(key, value, lease)
        if sync and not self.state.wait_replicated(
                timeout=sync_timeout, min_followers=sync_min_followers):
            from ptype_tpu.errors import CoordinationError

            raise CoordinationError(
                f"sync put {key!r}: replication not acknowledged in "
                f"time (write IS applied on the primary)")
        return rev

    def range(self, key: str, options: RangeOptions | None = None) -> RangeResult:
        return self.state.range(key, options)

    def delete(self, key: str, options: RangeOptions | None = None) -> int:
        return self.state.delete(key, options)

    def grant(self, ttl: float) -> int:
        return self.state.grant(ttl)

    def keepalive(self, lease_id: int) -> float:
        return self.state.keepalive(lease_id)

    def revoke(self, lease_id: int) -> None:
        self.state.revoke(lease_id)

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        return self.state.watch(prefix, start_rev=start_rev)

    def member_add(self, name: str, peer_addr: str, metadata: dict | None = None) -> Member:
        return self.state.member_add(name, peer_addr, metadata)

    def member_promote(self, member_id: int) -> Member:
        return self.state.member_promote(member_id)

    def member_remove(self, member_id: int) -> bool:
        return self.state.member_remove(member_id)

    def member_list(self) -> list[Member]:
        return self.state.member_list()

    def barrier(self, name: str, count: int, timeout: float | None = None) -> bool:
        return self.state.barrier(name, count, timeout)

    @property
    def closed(self) -> bool:
        """True once the underlying state is closed — keepalive loops
        use this to go quiet instead of warn-spinning forever."""
        return self.state._closed.is_set()

    def close(self) -> None:
        # Shared named states are closed via reset_local_coords(); closing a
        # handle must not tear down state other Cluster handles still use.
        pass
