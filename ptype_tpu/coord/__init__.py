"""Coordination service — the control-plane substrate.

The reference embedded a raft-replicated etcd member in every process
(cluster/cluster.go:161-196). The TPU-native equivalent is the model JAX's
own distributed runtime uses: a **single coordinator process** serving a
linearizable KV with leases and watches, and every other process a client.
This trades raft availability for the simplicity that matches how TPU pods
are actually scheduled (a fixed process set with process 0 as coordinator);
durability comes from Store snapshots to ``data_dir`` rather than a raft log.

Three tiers, mirroring the reference's test seams (SURVEY.md §4):

- :class:`ptype_tpu.coord.core.CoordState` — the authoritative in-memory
  state machine (KV + revisions, leases + TTL, prefix watches, members,
  barriers).
- :class:`ptype_tpu.coord.local.LocalCoord` — in-process backend wrapping a
  (possibly shared) ``CoordState`` (the embedded-etcd test tier).
- :class:`ptype_tpu.coord.service.CoordServer` /
  :class:`ptype_tpu.coord.remote.RemoteCoord` — TCP server + client for real
  multi-process clusters.
"""

from ptype_tpu.coord.core import (
    CoordState,
    Event,
    EventType,
    KVItem,
    Lease,
    Member,
    RangeOptions,
    SortOrder,
    SortTarget,
    Watch,
)
from ptype_tpu.coord.local import LocalCoord, local_coord, reset_local_coords
from ptype_tpu.coord.service import CoordServer
from ptype_tpu.coord.remote import RemoteCoord
from ptype_tpu.coord.api import CoordBackend, connect
from ptype_tpu.coord.standby import Standby, WalFollower

__all__ = [
    "CoordBackend",
    "CoordServer",
    "CoordState",
    "Event",
    "EventType",
    "KVItem",
    "Lease",
    "LocalCoord",
    "Member",
    "RangeOptions",
    "RemoteCoord",
    "SortOrder",
    "SortTarget",
    "Standby",
    "WalFollower",
    "Watch",
    "connect",
    "local_coord",
    "reset_local_coords",
]
