"""Abstract coordination backend + connection factory.

Everything above the coordination layer (registry, store, cluster) programs
against :class:`CoordBackend`, never a concrete transport — preserving the
reference's interface seam that made its RPC layer testable with a mock
registry (SURVEY.md §4 tier 2, registry.go:17-21).
"""

from __future__ import annotations

import abc

from ptype_tpu.coord.core import Member, RangeOptions, RangeResult, Watch


class CoordBackend(abc.ABC):
    """KV + leases + watches + members + barrier, transport-agnostic."""

    # KV. sync=True acks only after every WAL follower attached at the
    # barrier mirrored the write (the raft-commit analog;
    # coord/core.wait_replicated) — raises if replication is not
    # acknowledged within sync_timeout (None = the shared
    # DEFAULT_SYNC_TIMEOUT). sync_min_followers>0 additionally fails
    # the put when fewer live followers are attached — otherwise a
    # zero-follower window (mirror reconnecting) degrades to an
    # indistinguishable unreplicated ack.
    @abc.abstractmethod
    def put(self, key: str, value: str, lease: int = 0,
            sync: bool = False,
            sync_timeout: float | None = None,
            sync_min_followers: int = 0) -> int: ...

    @abc.abstractmethod
    def range(self, key: str, options: RangeOptions | None = None) -> RangeResult: ...

    @abc.abstractmethod
    def delete(self, key: str, options: RangeOptions | None = None) -> int: ...

    # Leases
    @abc.abstractmethod
    def grant(self, ttl: float) -> int: ...

    @abc.abstractmethod
    def keepalive(self, lease_id: int) -> float: ...

    @abc.abstractmethod
    def revoke(self, lease_id: int) -> None: ...

    # Watches. start_rev > 0 replays retained history from that
    # revision at arm time (etcd watch start-revision; raises when
    # compacted).
    @abc.abstractmethod
    def watch(self, prefix: str, start_rev: int = 0) -> Watch: ...

    # Membership
    @abc.abstractmethod
    def member_add(self, name: str, peer_addr: str, metadata: dict | None = None) -> Member: ...

    @abc.abstractmethod
    def member_promote(self, member_id: int) -> Member: ...

    @abc.abstractmethod
    def member_remove(self, member_id: int) -> bool: ...

    @abc.abstractmethod
    def member_list(self) -> list[Member]: ...

    # Synchronization
    @abc.abstractmethod
    def barrier(self, name: str, count: int, timeout: float | None = None) -> bool: ...

    @abc.abstractmethod
    def close(self) -> None: ...


def connect(
    address: str | list[str],
    *,
    dial_timeout: float = 5.0,
    in_process: bool = False,
    discovery_interval: float = 0.0,
) -> CoordBackend:
    """Dial a coordination backend.

    ``in_process=True`` (or an address of the form ``local:<name>``) returns
    the shared in-process backend — the embedded-etcd-style test tier.
    Otherwise dials the TCP coordination service with the reference's 5s
    default dial timeout (registry.go:37). ``address`` may be a list of
    endpoints (primary + standbys); the client fails over between them.
    ``discovery_interval`` > 0 additionally polls the membership for
    promote-eligible standbys attached at runtime and extends the
    failover list with them (no-op for the in-process tier, which has
    no failover).
    """
    from ptype_tpu.coord.local import local_coord
    from ptype_tpu.coord.remote import RemoteCoord

    if isinstance(address, str) and (
            in_process or address.startswith("local:")):
        name = (address.split(":", 1)[1]
                if address.startswith("local:") else address)
        return local_coord(name)
    return RemoteCoord(address, dial_timeout=dial_timeout,
                       discovery_interval=discovery_interval)
