"""The coordination state machine: KV + revisions, leases, watches, members.

This is the authoritative store behind both the in-process backend
(:mod:`ptype_tpu.coord.local`) and the TCP service
(:mod:`ptype_tpu.coord.service`). Linearizability is by construction — every
mutation takes one lock and bumps one revision counter — which is the role
raft quorum played for the reference's Store (SURVEY.md §3.4).

Capability parity targets (all behaviors the reference's tests encode):
- lease-expiry liveness: key granted under a TTL lease disappears after the
  TTL unless kept alive (ref: registry.go:58-83, registry_test.go:135-147);
- watch streams that fire on any change under a prefix
  (ref: registry.go:119-150);
- range queries with prefix/limit/sort/keys-only/count-only options
  (ref: store_config.go:33-103).
"""

from __future__ import annotations

import enum
import threading
import time

from ptype_tpu import lockcheck
from collections import deque
from dataclasses import dataclass, field, replace

from ptype_tpu import chaos, logs
from ptype_tpu.errors import CoordinationError

log = logs.get_logger("coord")


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-``os.replace``d entry survives host
    power loss — the rename lives in the directory's metadata, not in
    the file that was renamed (etcd fsyncs the dir on snapshot rename;
    without this the wal_fsync durability claim is overstated)."""
    import os

    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)

#: One default for the sync-put replication barrier everywhere (wire
#: dispatch, LocalCoord, the backend API) — three hardcoded copies
#: would drift.
DEFAULT_SYNC_TIMEOUT = 5.0


class EventType(enum.Enum):
    PUT = "put"
    DELETE = "delete"


class SortOrder(enum.Enum):
    NONE = "none"
    ASCEND = "ascend"
    DESCEND = "descend"


class SortTarget(enum.Enum):
    KEY = "key"
    VERSION = "version"
    CREATE = "create"
    MOD = "mod"
    VALUE = "value"


@dataclass(frozen=True)
class KVItem:
    key: str
    value: str
    create_rev: int
    mod_rev: int
    version: int  # number of writes to this key since creation
    lease: int = 0  # 0 = no lease


@dataclass(frozen=True)
class Event:
    type: EventType
    key: str
    value: str  # empty for DELETE
    mod_rev: int


@dataclass
class Lease:
    id: int
    ttl: float
    expires_at: float
    keys: set[str] = field(default_factory=set)


@dataclass(frozen=True)
class Member:
    id: int
    name: str
    peer_addr: str
    metadata: dict = field(default_factory=dict)


def prefix_range_end(prefix: str) -> str:
    """Smallest key greater than every key with this prefix.

    Mirrors clientv3.GetPrefixRangeEnd (ref: store_config.go:41-58) at the
    granularity of this keyspace: the reference bumped the last non-0xff
    *byte*; our keys are unicode strings, so bump the last non-maximal
    *code point*. Empty / unbumpable prefixes mean "to the end".
    """
    for i in reversed(range(len(prefix))):
        c = ord(prefix[i])
        if c < 0x10FFFF:
            return prefix[:i] + chr(c + 1)
    return "\0"  # etcd's "range to end" sentinel


@dataclass
class RangeOptions:
    """Query modifiers (ref: store_config.go:33-103 re-exports)."""

    prefix: bool = False
    range_end: str = ""  # explicit [key, range_end) range
    from_key: bool = False  # [key, end-of-keyspace)
    limit: int = 0  # 0 = no limit
    sort_order: SortOrder = SortOrder.NONE
    sort_target: SortTarget = SortTarget.KEY
    keys_only: bool = False
    count_only: bool = False
    serializable: bool = False  # no-op here: every read is linearizable
    min_mod_rev: int = 0
    #: Read AT this historical revision (etcd WithRev,
    #: store_config.go:71-73): the result is the state as of revision
    #: ``rev``, served from the bounded MVCC history. 0 = head. Raises
    #: when the revision is compacted or in the future.
    rev: int = 0

    def to_wire(self) -> dict:
        return {
            "prefix": self.prefix,
            "range_end": self.range_end,
            "from_key": self.from_key,
            "limit": self.limit,
            "sort_order": self.sort_order.value,
            "sort_target": self.sort_target.value,
            "keys_only": self.keys_only,
            "count_only": self.count_only,
            "serializable": self.serializable,
            "min_mod_rev": self.min_mod_rev,
            "rev": self.rev,
        }

    @staticmethod
    def from_wire(d: dict) -> "RangeOptions":
        return RangeOptions(
            prefix=d.get("prefix", False),
            range_end=d.get("range_end", ""),
            from_key=d.get("from_key", False),
            limit=d.get("limit", 0),
            sort_order=SortOrder(d.get("sort_order", "none")),
            sort_target=SortTarget(d.get("sort_target", "key")),
            keys_only=d.get("keys_only", False),
            count_only=d.get("count_only", False),
            serializable=d.get("serializable", False),
            min_mod_rev=d.get("min_mod_rev", 0),
            rev=d.get("rev", 0),
        )


@dataclass(frozen=True)
class RangeResult:
    items: list[KVItem]
    count: int
    revision: int


class Watch:
    """A stream of events for keys under a prefix.

    Consumers iterate or call :meth:`get`; producers (CoordState) push.
    Closing is idempotent; a closed watch raises ``StopIteration`` once
    drained.
    """

    _CLOSED = object()

    def __init__(self, watch_id: int, prefix: str, cancel_fn):
        self.id = watch_id
        self.prefix = prefix
        #: Bumped by RemoteCoord when a watch re-arm could NOT replay
        #: the missed interval (history compacted): events between the
        #: loss and the re-arm are gone and consumers that see the bump
        #: must re-list to resync (the snapshot-then-delta contract's
        #: resync point). Since round 5 a reconnect that resumes from
        #: ``last_rev`` via the MVCC event history does NOT bump.
        self.epoch = 0
        #: Highest mod_rev delivered through this watch (or the arm-
        #: time head revision) — the resume point for reconnect replay.
        self.last_rev = 0
        #: Head revision at arm time, IMMUTABLE after arming — what a
        #: remote client may safely adopt as its initial resume floor.
        #: (last_rev races live pushes by the pump; reading it outside
        #: the state lock could skip an event queued-but-undelivered.)
        self.arm_rev = 0
        self._cancel_fn = cancel_fn
        self._cond = lockcheck.condition("coord.watch")
        self._events: list[Event] = []
        self._closed = False

    def _push(self, events: list[Event]) -> None:
        with self._cond:
            if self._closed:
                return
            self._events.extend(events)
            if events and events[-1].mod_rev > self.last_rev:
                self.last_rev = events[-1].mod_rev
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> list[Event]:
        """Block for the next batch of events; [] on timeout or close."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._events and not self._closed:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                self._cond.wait(remaining)
            batch, self._events = self._events, []
            return batch

    def cancel(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._cancel_fn(self)

    close = cancel

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def __iter__(self):
        while True:
            batch = self.get()
            if not batch:
                if self.closed:
                    return
                continue
            for ev in batch:
                yield ev


class ReplFeed:
    """A follower's view of the primary's WAL: one ``("snap", dict)``
    item with the full state at subscribe time (and after each
    compaction), then a ``("rec", dict)`` item per mutation, in commit
    order. Consumed by the WAL-shipping standby
    (:class:`ptype_tpu.coord.standby.WalFollower`). The queue is
    bounded at :data:`MAX_BUFFER` items and SELF-CANCELS on overflow
    (see below) — a cancelled follower re-syncs from a fresh snapshot
    on reconnect, so dropping the feed is always safe; a follower that
    stops draining without wedging simply loses its connection
    (service.py pump), which also cancels the feed.
    """

    #: Max buffered items before the feed self-cancels. A follower
    #: whose process is wedged (SIGSTOP, stuck disk) keeps its TCP
    #: window open, so the pump blocks in sendall and never errors —
    #: without this bound every mutation would accumulate in the
    #: feed's list and the COORDINATOR would OOM. A cancelled follower
    #: re-syncs from a fresh snapshot on reconnect, so dropping the
    #: feed is always safe.
    MAX_BUFFER = 100_000

    def __init__(self, feed_id: int, cancel_fn):
        self.id = feed_id
        self._cancel_fn = cancel_fn
        self._cond = lockcheck.condition("coord.repl_feed")
        self._items: list[tuple[str, dict, int]] = []
        self._closed = False
        #: Highest replication sequence this follower has ACKNOWLEDGED
        #: mirroring (durable on its side). A snapshot ack covers every
        #: record folded into it. Read by CoordState.wait_replicated —
        #: the sync-put (raft-commit-analog) barrier.
        self.acked = 0
        #: Last heartbeat/ack ROUND-TRIP from this follower
        #: (monotonic). A live round-trip within the witness TTL is
        #: the standby's vote in the partition-tolerance quorum
        #: (service.CoordServer._quorum_round) — a half-dead TCP
        #: connection cannot fake it.
        self.last_hb = time.monotonic()

    def _push(self, kind: str, data: dict, seq: int) -> None:
        overflow = False
        with self._cond:
            if self._closed:
                return
            self._items.append((kind, data, seq))
            if len(self._items) > self.MAX_BUFFER:
                overflow = True
            self._cond.notify_all()
        if overflow:
            log.warning("replication feed overflowed; cancelling "
                        "(follower will re-sync on reconnect)",
                        kv={"feed": self.id, "buffered": self.MAX_BUFFER})
            self.cancel()

    def get(self, timeout: float | None = None
            ) -> list[tuple[str, dict, int]]:
        """Block for the next batch; [] on timeout or close."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._items and not self._closed:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                self._cond.wait(remaining)
            batch, self._items = self._items, []
            return batch

    def cancel(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._cancel_fn(self)

    close = cancel

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed


class CoordState:
    """Single-lock linearizable KV + leases + watches + members + barriers.

    Durability (VERDICT r1 missing #1 — the reference's store survived
    restarts via etcd's raft log + data-dir, testdata/node1.yml): pass
    ``data_dir`` and every mutation is appended to ``coord.wal`` before
    it is acknowledged; a restarted coordinator replays snapshot + WAL
    and resumes with identical revisions, lease ids, and member ids.
    Scope: the WAL is flushed (not fsynced) per record — it survives
    coordinator *process* death (the elastic story's failure mode), not
    host power loss; etcd's raft log fsyncs and does cover that.
    Leases are re-armed at ``now + ttl`` on restart (a grace window for
    clients to reconnect and resume keepalives — dead clients still
    expire one TTL later). The WAL is compacted into ``coord.snap``
    every ``compact_every`` records. Barriers and watches are ephemeral
    rendezvous state and are deliberately not persisted.
    """

    def __init__(self, sweep_interval: float = 0.25,
                 data_dir: str | None = None,
                 compact_every: int = 10_000,
                 bump_term: bool | int = False,
                 fsync: bool = False,
                 history_window: int = 10_000):
        self._lock = lockcheck.rlock("coord.state")
        self._kv: dict[str, KVItem] = {}
        self._rev = 0
        #: Promotion generation (fencing token). Persisted in the
        #: snapshot; bumped when a standby takes over (``bump_term``).
        #: Clients carry the highest term they have seen and a
        #: superseded primary — lower term — refuses their requests,
        #: the role raft's leader epoch played for the reference
        #: (/root/reference/cluster/cluster.go:120-147).
        self._term = 0
        self._leases: dict[int, Lease] = {}
        self._next_lease = 1
        self._watches: list[Watch] = []
        self._next_watch = 1
        self._members: dict[int, Member] = {}
        self._next_member = 1
        self._barriers: dict[str, dict] = {}
        self._barrier_cond = threading.Condition(self._lock)
        self._closed = threading.Event()
        self._sweep_interval = sweep_interval
        self._wal = None
        self._wal_count = 0
        self._wal_gen = 0
        #: fsync per appended record (and through compaction). Off =
        #: flush-only: survives process death, not host power loss —
        #: the documented default scope. On = etcd raft-log parity.
        self._fsync = fsync
        self._compact_every = compact_every
        self._data_dir = data_dir
        self._flock = None
        self._repl_feeds: list[ReplFeed] = []
        self._next_repl = 1
        #: Monotonic replication sequence: one per feed-visible event
        #: (mutation record or snapshot). Follower acks reference it;
        #: wait_replicated barriers on it.
        self._repl_seq = 0
        self._ack_cond = threading.Condition(self._lock)
        #: Quorum fence hook: a callable returning a refusal message
        #: (or None) checked at every public entry point. Installed by
        #: CoordServer when a witness is configured so in-process
        #: callers fence like remote ones (see _check_fence).
        self.fence = None
        # ---- bounded MVCC history (etcd WithRev + watch-start-rev
        # parity, store_config.go:71-73). Two structures, one feed
        # point (_notify):
        #: Global event log for watch replay-from-revision, bounded at
        #: ``history_window`` events; ``_event_floor`` = mod_rev of the
        #: newest EVICTED event (resume below it must re-list).
        self._event_log: deque[Event] = deque()
        self._event_floor = 0
        #: Per-key version chains for read-at-revision:
        #: key -> [(mod_rev, KVItem|None)] (None = tombstone), oldest
        #: first. Eviction keeps the newest entry at-or-below the
        #: compaction floor as each key's base version (what etcd's
        #: compaction keeps), so any revision in
        #: [_compacted_rev, head] reconstructs exactly.
        self._hist: dict[str, list] = {}
        self._hist_log: deque = deque()  # (mod_rev, key) eviction order
        self._compacted_rev = 0
        self._history_window = history_window
        if data_dir:
            import fcntl
            import os

            os.makedirs(data_dir, exist_ok=True)
            # Single-writer fence on the WAL dir: a standby promoting
            # against a wedged-but-alive primary (or an operator
            # double-starting the seed) must fail here instead of
            # interleaving two coordinators' appends into one WAL.
            # The kernel releases the lock on crash/SIGKILL, so a truly
            # dead primary never blocks takeover.
            self._flock = open(os.path.join(data_dir, ".lock"), "w")
            try:
                fcntl.flock(self._flock, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                self._flock.close()
                self._flock = None
                raise RuntimeError(
                    f"coordination data_dir {data_dir!r} is locked by a "
                    "live coordinator — refusing to double-write the WAL"
                ) from e
            self._replay(data_dir)
            if bump_term:
                # Promotion: supersede every prior primary BEFORE the
                # compact below persists the new term — a crash after
                # serving even one request must not resurrect at the
                # old term. May bump by >1: a junior standby promoting
                # past unresponsive seniors jumps their term slots so
                # a slow senior finishing its own promotion later can
                # never land on the SAME term (coord/standby.py
                # succession).
                self._term += int(bump_term)
                log.info("coordination term bumped (promotion)",
                         kv={"term": self._term, "by": int(bump_term)})
            self._wal = open(self._wal_path(), "a", encoding="utf-8")
            # Compact-on-start: fold the recovered state into a fresh
            # snapshot + truncated WAL. Appending to the replayed file
            # would be wrong in the stale-generation case (a crash
            # between _compact's snapshot-replace and WAL-truncate):
            # new records after a mismatched header would be skipped
            # wholesale by the NEXT replay. Rewriting both files makes
            # every start leave a consistent (snap, WAL-gen) pair —
            # and bounds future replay work as a side effect.
            self._compact_locked()
        elif bump_term:
            self._term += int(bump_term)
        self._publish_term()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="coord-lease-sweeper", daemon=True
        )
        self._sweeper.start()

    def _publish_term(self) -> None:
        """Stamp the term into the ``coord.term`` gauge so the health
        plane's sampler turns promotions into a series — the
        coord-flap alert rule counts its increases. Only when metrics
        is ALREADY loaded: the module imports jax, and a lean
        coordinator/standby (deliberately jax-free, and on the
        promotion path latency-critical) must not pay a cold jax
        import for a gauge no sampler in that process would read."""
        import sys

        metrics_mod = sys.modules.get("ptype_tpu.metrics")
        if metrics_mod is None:
            return
        metrics_mod.metrics.gauge("coord.term").set(float(self._term))

    # ------------------------------------------------------------ WAL
    def _wal_path(self) -> str:
        import os

        return os.path.join(self._data_dir, "coord.wal")

    def _snap_path(self) -> str:
        import os

        return os.path.join(self._data_dir, "coord.snap")

    def _append_locked(self, rec: dict) -> None:
        """Log one mutation (called under the lock, before ack)."""
        # Key is "<kind>:<kv-key>" (e.g. "p:services/x") so plans can
        # target one record precisely — bare kind codes collide as
        # substrings ("p" is inside "mp").
        f = chaos.hit("coord.wal_append",
                      f"{rec.get('o', '')}:{rec.get('k', '')}")
        if f is not None and f.action == "delay":
            # Deliberately sleeps UNDER the state lock: every op —
            # including probe-serving member_list — wedges for the
            # duration, which is how a drill makes a standby's probes
            # time out and promote while this primary is alive-but-hung.
            f.sleep()
        self._repl_seq += 1
        # Copy: an overflowing feed self-cancels INSIDE _push, which
        # removes it from this list mid-iteration — a sibling feed
        # would silently miss this record (divergent mirror).
        for feed in list(self._repl_feeds):
            feed._push("rec", rec, self._repl_seq)
        if self._wal is None:
            return
        import json

        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        if self._fsync:
            import os

            os.fsync(self._wal.fileno())
        self._wal_count += 1
        if self._wal_count >= self._compact_every:
            self._compact_locked()

    def _snapshot_dict_locked(self, wal_gen: int | None = None) -> dict:
        """Full state in ``coord.snap`` format (called under the lock).

        ``wal_gen`` is the generation of WAL records that FOLLOW this
        snapshot: replay accepts a WAL only when its header generation
        matches the snapshot's. This closes the crash window between
        "snapshot replaced" and "WAL truncated" — a stale WAL paired
        with a fresh snapshot would re-apply already-folded records
        and diverge (grant ids, revisions).
        """
        return {
            "wal_gen": self._wal_gen if wal_gen is None else wal_gen,
            "term": self._term,
            "rev": self._rev,
            "next_lease": self._next_lease,
            "next_member": self._next_member,
            "kv": [
                {"k": it.key, "v": it.value, "cr": it.create_rev,
                 "mr": it.mod_rev, "ver": it.version, "l": it.lease}
                for it in self._kv.values()
            ],
            "leases": [
                {"id": l.id, "ttl": l.ttl, "keys": sorted(l.keys)}
                for l in self._leases.values()
            ],
            "members": [
                {"id": m.id, "n": m.name, "a": m.peer_addr,
                 "md": m.metadata}
                for m in self._members.values()
            ],
        }

    def _compact_locked(self) -> None:
        """Snapshot full state, truncate the WAL (under the lock)."""
        import json
        import os

        new_gen = self._wal_gen + 1
        snap = self._snapshot_dict_locked(wal_gen=new_gen)
        # A snapshot folds every record through the current seq, so a
        # follower's ack of it covers them all.
        for feed in list(self._repl_feeds):  # _push may self-cancel
            feed._push("snap", snap, self._repl_seq)
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
            if self._fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())
        if self._fsync:
            fsync_dir(self._data_dir)
        # Crash here leaves the new snapshot with the OLD-generation
        # WAL — replay sees the header mismatch and skips it (those
        # records are already folded into the snapshot).
        self._wal.close()
        self._wal = open(self._wal_path(), "w", encoding="utf-8")
        self._wal_gen = new_gen
        self._wal.write(json.dumps({"o": "hdr", "gen": new_gen},
                                   separators=(",", ":")) + "\n")
        self._wal.flush()
        self._wal_count = 0

    def _replay(self, data_dir: str) -> None:
        """Load snapshot + WAL; re-arm surviving leases."""
        import json
        import os

        snap_path = os.path.join(data_dir, "coord.snap")
        snap_gen = 0
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            snap_gen = snap.get("wal_gen", 0)
            self._term = snap.get("term", 0)
            self._rev = snap["rev"]
            self._next_lease = snap["next_lease"]
            self._next_member = snap["next_member"]
            for r in snap["kv"]:
                self._kv[r["k"]] = KVItem(
                    key=r["k"], value=r["v"], create_rev=r["cr"],
                    mod_rev=r["mr"], version=r["ver"], lease=r["l"])
            for r in snap["leases"]:
                self._leases[r["id"]] = Lease(
                    id=r["id"], ttl=r["ttl"], expires_at=0.0,
                    keys=set(r["keys"]))
            for r in snap["members"]:
                self._members[r["id"]] = Member(
                    id=r["id"], name=r["n"], peer_addr=r["a"],
                    metadata=r["md"])
            # History below the snapshot revision is unknowable: set
            # the MVCC floors there and seed each key's base version,
            # so [snap_rev, head] reconstructs exactly (WAL replay
            # appends the rest through the normal mutation paths).
            self._compacted_rev = self._event_floor = self._rev
            for k, it in self._kv.items():
                self._hist[k] = [(it.mod_rev, it)]
        self._wal_gen = snap_gen
        wal_path = os.path.join(data_dir, "coord.wal")
        if os.path.exists(wal_path):
            with open(wal_path, encoding="utf-8") as f:
                first = True
                skip = False
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # torn tail write from a crash — stop here
                    if first:
                        first = False
                        if rec.get("o") == "hdr":
                            if rec["gen"] != snap_gen:
                                # Stale WAL beside a newer snapshot (a
                                # crash between snapshot-replace and
                                # WAL-truncate): every record here is
                                # already folded into the snapshot.
                                skip = True
                            continue
                        # Headerless WAL (pre-compaction, or legacy):
                        # belongs to generation 0 — apply only if the
                        # snapshot agrees.
                        skip = snap_gen != 0
                    if not skip:
                        self._apply(rec)
        now = time.monotonic()
        for lease in self._leases.values():
            lease.expires_at = now + lease.ttl
        if self._kv or self._members:
            log.info("coordination state recovered", kv={
                "rev": self._rev, "keys": len(self._kv),
                "leases": len(self._leases), "members": len(self._members),
            })

    def _apply(self, rec: dict) -> None:
        """Replay one WAL record through the normal mutation paths
        (``self._wal`` is still None, so nothing re-logs)."""
        op = rec["o"]
        if op == "p":
            self.put(rec["k"], rec["v"], rec.get("l", 0))
        elif op == "d":
            self._delete_keys(rec["ks"])
        elif op == "g":
            got = self.grant(rec["ttl"])
            if got != rec["id"]:
                raise CoordinationError(
                    f"WAL replay diverged: granted lease {got}, "
                    f"log says {rec['id']} — refusing to recover from a "
                    "corrupt log")
        elif op == "r" or op == "x":
            self.revoke(rec["id"])
        elif op == "ma":
            self.member_add(rec["n"], rec["a"], rec.get("md") or {})
        elif op == "mp":
            self.member_promote(rec["id"])
        elif op == "mr":
            self.member_remove(rec["id"])

    # ------------------------------------------------------------------ KV

    def _check_fence(self) -> None:
        """Refuse the operation when a quorum fence is active. Set by
        CoordServer when a witness is configured, so the seed's OWN
        in-process callers (LocalCoord — registry, store) fence
        exactly like remote clients do: a minority-partition primary
        must not keep serving its co-located application either."""
        f = self.fence
        if f is not None:
            msg = f()
            if msg:
                raise CoordinationError(msg)

    def put(self, key: str, value: str, lease: int = 0) -> int:
        self._check_fence()
        if not key:
            raise CoordinationError("put: empty key")
        with self._lock:
            if lease:
                lr = self._leases.get(lease)
                if lr is None:
                    raise CoordinationError(f"put: lease {lease} not found")
                lr.keys.add(key)
            self._rev += 1
            prev = self._kv.get(key)
            item = KVItem(
                key=key,
                value=value,
                create_rev=prev.create_rev if prev else self._rev,
                mod_rev=self._rev,
                version=(prev.version + 1) if prev else 1,
                lease=lease,
            )
            self._kv[key] = item
            self._append_locked({"o": "p", "k": key, "v": value, "l": lease})
            self._notify([Event(EventType.PUT, key, value, self._rev)])
            return self._rev

    def range(self, key: str, options: RangeOptions | None = None) -> RangeResult:
        self._check_fence()
        opts = options or RangeOptions()
        with self._lock:
            lo, hi = self._bounds(key, opts)
            if opts.rev:
                if opts.rev > self._rev:
                    raise CoordinationError(
                        f"range: revision {opts.rev} is in the future "
                        f"(head {self._rev})")
                if opts.rev < self._compacted_rev:
                    raise CoordinationError(
                        f"range: revision {opts.rev} has been "
                        f"compacted (floor {self._compacted_rev})")
                items = []
                for k in self._hist:
                    if lo <= k and (hi is None or k < hi):
                        it = self._item_at(k, opts.rev)
                        if it is not None:
                            items.append(it)
            else:
                items = [
                    it for k, it in self._kv.items()
                    if lo <= k and (hi is None or k < hi)
                ]
            if opts.min_mod_rev:
                items = [it for it in items if it.mod_rev >= opts.min_mod_rev]
            items = self._sort(items, opts)
            count = len(items)
            if opts.limit > 0:
                items = items[: opts.limit]
            if opts.count_only:
                items = []
            elif opts.keys_only:
                items = [replace(it, value="") for it in items]
            return RangeResult(items=items, count=count, revision=self._rev)

    def delete(self, key: str, options: RangeOptions | None = None) -> int:
        self._check_fence()
        opts = options or RangeOptions()
        with self._lock:
            lo, hi = self._bounds(key, opts)
            doomed = [
                k for k in self._kv
                if lo <= k and (hi is None or k < hi)
            ]
            if not doomed:
                return 0
            n = self._delete_keys(doomed)
            self._append_locked({"o": "d", "ks": doomed})
            return n

    def _delete_keys(self, doomed: list[str]) -> int:
        """Remove resolved keys + bump rev once (live delete + replay)."""
        with self._lock:
            self._rev += 1
            events = []
            for k in doomed:
                item = self._kv.pop(k, None)
                if item is None:
                    continue
                if item.lease and item.lease in self._leases:
                    self._leases[item.lease].keys.discard(k)
                events.append(Event(EventType.DELETE, k, "", self._rev))
            self._notify(events)
            return len(events)

    @staticmethod
    def _bounds(key: str, opts: RangeOptions) -> tuple[str, str | None]:
        """Resolve (lo, hi) key bounds; hi=None means single exact key."""
        if opts.prefix:
            end = prefix_range_end(key)
            return key, (None if end == "\0" else end) or "￿" * 8
        if opts.range_end:
            return key, opts.range_end
        if opts.from_key:
            return key, "￿" * 8
        # exact key: model as [key, key+minimal-successor)
        return key, key + "\0"

    @staticmethod
    def _sort(items: list[KVItem], opts: RangeOptions) -> list[KVItem]:
        keyfns = {
            SortTarget.KEY: lambda it: it.key,
            SortTarget.VERSION: lambda it: it.version,
            SortTarget.CREATE: lambda it: it.create_rev,
            SortTarget.MOD: lambda it: it.mod_rev,
            SortTarget.VALUE: lambda it: it.value,
        }
        if opts.sort_order is SortOrder.NONE:
            # etcd returns key-ascending by default
            return sorted(items, key=lambda it: it.key)
        return sorted(
            items,
            key=keyfns[opts.sort_target],
            reverse=opts.sort_order is SortOrder.DESCEND,
        )

    # --------------------------------------------------------------- leases

    def grant(self, ttl: float) -> int:
        self._check_fence()
        if ttl <= 0:
            raise CoordinationError("grant: ttl must be > 0")
        with self._lock:
            lease_id = self._next_lease
            self._next_lease += 1
            self._leases[lease_id] = Lease(
                id=lease_id, ttl=ttl, expires_at=time.monotonic() + ttl
            )
            self._append_locked({"o": "g", "id": lease_id, "ttl": ttl})
            return lease_id

    def keepalive(self, lease_id: int) -> float:
        """Refresh a lease; returns the new TTL. Raises if expired/unknown."""
        self._check_fence()
        f = chaos.hit("coord.keepalive", str(lease_id))
        if f is not None and f.action == "revoke":
            # Lease-revoke a member the SIGKILL way: the lease dies
            # server-side and this keepalive fails exactly like one for
            # an expired lease ("not found" routes the registration to
            # its re-register path).
            self.revoke(lease_id)
            raise CoordinationError(
                f"chaos: keepalive: lease {lease_id} not found "
                f"(revoked by fault injection)")
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise CoordinationError(f"keepalive: lease {lease_id} not found")
            lease.expires_at = time.monotonic() + lease.ttl
            return lease.ttl

    def revoke(self, lease_id: int) -> None:
        self._check_fence()
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            self._append_locked({"o": "r", "id": lease_id})
            self._expire_keys_locked(lease)

    def _expire_keys_locked(self, lease: Lease) -> None:
        events = []
        if lease.keys:
            self._rev += 1
        for k in sorted(lease.keys):
            if k in self._kv and self._kv[k].lease == lease.id:
                del self._kv[k]
                events.append(Event(EventType.DELETE, k, "", self._rev))
        if events:
            self._notify(events)

    def _sweep_loop(self) -> None:
        while not self._closed.wait(self._sweep_interval):
            now = time.monotonic()
            with self._lock:
                expired = [
                    l for l in self._leases.values() if l.expires_at <= now
                ]
                for lease in expired:
                    del self._leases[lease.id]
                    self._append_locked({"o": "x", "id": lease.id})
                    self._expire_keys_locked(lease)

    # -------------------------------------------------------------- watches

    def watch(self, prefix: str, start_rev: int = 0) -> Watch:
        """Stream events under ``prefix``. ``start_rev`` > 0 first
        replays every retained event with ``mod_rev >= start_rev``
        (etcd watch start-revision semantics) atomically with the
        arm — the reconnect-resume primitive: a client that saw
        through revision R re-watches with ``start_rev=R+1`` and
        misses nothing, without a snapshot re-list. Raises when the
        requested interval has been compacted (caller falls back to
        snapshot-then-delta)."""
        self._check_fence()
        with self._lock:
            if start_rev and start_rev <= self._event_floor:
                raise CoordinationError(
                    f"watch: start revision {start_rev} has been "
                    f"compacted (floor {self._event_floor + 1})")
            if start_rev > self._rev + 1:
                # The interval [head+1, start_rev) is not covered by
                # this state's history — the client is resuming
                # against a RESET state (fresh data_dir). Claiming
                # continuity would silently skip the gap; report it as
                # compacted so the client re-lists.
                raise CoordinationError(
                    f"watch: start revision {start_rev} is ahead of "
                    f"head {self._rev} — uncovered interval, treat "
                    f"as compacted")
            w = Watch(self._next_watch, prefix, self._remove_watch)
            w.last_rev = w.arm_rev = self._rev
            self._next_watch += 1
            if start_rev:
                replay = [ev for ev in self._event_log
                          if ev.mod_rev >= start_rev
                          and ev.key.startswith(prefix)]
                if replay:
                    w._push(replay)
            self._watches.append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            if w in self._watches:
                self._watches.remove(w)

    # ---------------------------------------------------------- replication

    def repl_subscribe(self) -> ReplFeed:
        """Subscribe a WAL follower: the feed's first item is a full
        state snapshot taken atomically with the subscription (no
        mutation can fall between the snapshot and the record stream),
        then every subsequent mutation's WAL record in commit order.
        The standby's :class:`~ptype_tpu.coord.standby.WalFollower`
        mirrors these into its own data_dir so promotion replays
        locally — control-plane failover without a shared filesystem.
        """
        with self._lock:
            feed = ReplFeed(self._next_repl, self._remove_repl)
            self._next_repl += 1
            feed._push("snap", self._snapshot_dict_locked(), self._repl_seq)
            self._repl_feeds.append(feed)
            return feed

    def _remove_repl(self, feed: ReplFeed) -> None:
        with self._lock:
            if feed in self._repl_feeds:
                self._repl_feeds.remove(feed)
            # A sync-put waiter blocked on this (now dead) feed must
            # re-evaluate against the surviving membership.
            self._ack_cond.notify_all()

    def note_repl_ack(self, feed: ReplFeed, seq: int) -> None:
        """A follower acknowledged mirroring through ``seq``."""
        with self._lock:
            feed.last_hb = time.monotonic()  # an ack proves liveness too
            if seq > feed.acked:
                feed.acked = seq
                self._ack_cond.notify_all()

    def note_repl_hb(self, feed: ReplFeed) -> None:
        """A follower answered a heartbeat (live round-trip)."""
        feed.last_hb = time.monotonic()

    def has_live_follower(self, within: float) -> bool:
        """True when some follower completed a round-trip within
        ``within`` seconds — the standby's quorum vote."""
        return self.last_follower_contact(within) is not None

    def last_follower_contact(self, within: float) -> float | None:
        """Monotonic stamp of the NEWEST follower round-trip no older
        than ``within`` seconds, or None. The quorum loop anchors the
        follower vote's serving window to this stamp (not to "now"):
        granting a fresh full TTL against an almost-TTL-old heartbeat
        let a primary serve up to ~2×TTL past its last real contact —
        overlapping a successor's lease (ADVICE.md, quorum self-fence
        window)."""
        now = time.monotonic()
        with self._lock:
            stamps = [f.last_hb for f in self._repl_feeds
                      if not f.closed and now - f.last_hb <= within]
        return max(stamps) if stamps else None

    def wait_replicated(self, seq: int | None = None,
                        timeout: float | None = None,
                        min_followers: int = 0) -> bool:
        """Block until every replication follower that was attached AT
        BARRIER START has acknowledged mirroring through ``seq``
        (default: everything so far) — the sync-put barrier, the
        closest 2-node analog of a raft quorum commit. With no
        followers attached it returns True immediately (there is
        nobody to replicate to) — but a follower that dies or
        overflows MID-barrier without acking fails the barrier: its
        mirror may not hold the record, and "success because the
        witness vanished" is exactly the silent loss this feature
        exists to prevent. False on timeout/death: the mutation IS
        applied locally; only the replication guarantee is unmet.

        ``min_followers``: RAISE (rather than trivially succeed) when
        fewer than this many live followers are attached at barrier
        start — the zero-follower windows (follower reconnect after a
        drop, post-overflow re-sync) are exactly when a deployment
        that RUNS a standby must not get an indistinguishable
        unreplicated ack. The refusal is a distinct error (not the
        timeout's False): the record is definitely unreplicated and
        the mirror is DOWN, which an operator debugs differently from
        a slow mirror. Degraded acks with min_followers unset are
        logged (rate-limited) so they are at least observable."""
        if timeout is None:
            timeout = DEFAULT_SYNC_TIMEOUT
        deadline = time.monotonic() + timeout
        degraded = False
        with self._ack_cond:
            if seq is None:
                seq = self._repl_seq
            waiting = [f for f in self._repl_feeds if not f.closed]
            if len(waiting) < min_followers:
                raise CoordinationError(
                    f"sync barrier refused: {len(waiting)} live "
                    f"follower(s) attached, {min_followers} required "
                    f"(record is NOT replicated; the standby is down "
                    f"or mid-reconnect)")
            degraded = not waiting
            ok = False
            while True:
                if all(f.acked >= seq for f in waiting):
                    ok = True
                    break
                if any(f.closed and f.acked < seq for f in waiting):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ack_cond.wait(remaining)
        if degraded:
            # Outside the lock (a stalling log sink must not serialize
            # the whole coordinator) and rate-limited (a standby-less
            # deployment sync-putting in a loop would emit thousands).
            now = time.monotonic()
            if now - getattr(self, "_degraded_log_t", 0.0) > 10.0:
                self._degraded_log_t = now
                log.warning(
                    "sync put acked with ZERO followers attached "
                    "(unreplicated; set sync_min_followers to fail "
                    "instead)", kv={"seq": seq})
        return ok

    def _notify(self, events: list[Event]) -> None:
        # called under self._lock
        for ev in events:
            self._record_event_locked(ev)
        for w in self._watches:
            batch = [ev for ev in events if ev.key.startswith(w.prefix)]
            if batch:
                w._push(batch)

    def _record_event_locked(self, ev: Event) -> None:
        """Feed the bounded MVCC history (under the lock). Every
        mutation path funnels through _notify, so this is the single
        point where both the watch-replay log and the per-key version
        chains grow — and where they are compacted."""
        self._event_log.append(ev)
        item = self._kv.get(ev.key) if ev.type is EventType.PUT else None
        self._hist.setdefault(ev.key, []).append((ev.mod_rev, item))
        self._hist_log.append((ev.mod_rev, ev.key))
        while len(self._event_log) > self._history_window:
            self._event_floor = self._event_log.popleft().mod_rev
        while len(self._hist_log) > self._history_window:
            m, k = self._hist_log.popleft()
            if m > self._compacted_rev:
                self._compacted_rev = m
            lst = self._hist.get(k)
            if not lst:
                continue
            # Keep only the NEWEST entry at-or-below the floor as the
            # key's base version (etcd compaction semantics) …
            while len(lst) > 1 and lst[1][0] <= m:
                lst.pop(0)
            # … and a tombstone base is indistinguishable from "no
            # history" (the key is absent either way): drop it fully.
            if lst and lst[0][0] <= m and lst[0][1] is None:
                lst.pop(0)
            if not lst:
                del self._hist[k]

    def _item_at(self, key: str, rev: int) -> KVItem | None:
        """The key's state as of ``rev`` (under the lock): the newest
        version chained at-or-below it. None = absent (never existed
        in the retained window, or tombstoned)."""
        best = None
        for r, item in self._hist.get(key, ()):
            if r > rev:
                break
            best = item
        return best

    # -------------------------------------------------------------- members

    def member_add(self, name: str, peer_addr: str, metadata: dict | None = None) -> Member:
        self._check_fence()
        with self._lock:
            m = Member(
                id=self._next_member,
                name=name,
                peer_addr=peer_addr,
                metadata=metadata or {},
            )
            self._next_member += 1
            self._members[m.id] = m
            self._append_locked({"o": "ma", "id": m.id, "n": m.name,
                          "a": m.peer_addr, "md": m.metadata})
            return m

    def member_promote(self, member_id: int) -> Member:
        """Clear a member's ``learner`` flag — the analog of the
        reference's MemberPromote in the learner add→catch-up→promote
        lifecycle (cluster.go:120-147, 183-195). Idempotent; WAL-logged
        so the promoted status survives coordinator restart."""
        self._check_fence()
        with self._lock:
            m = self._members.get(member_id)
            if m is None:
                raise CoordinationError(
                    f"member_promote: member {member_id} not found")
            md = dict(m.metadata)
            md["learner"] = False
            promoted = replace(m, metadata=md)
            self._members[member_id] = promoted
            self._append_locked({"o": "mp", "id": member_id})
            return promoted

    def member_remove(self, member_id: int) -> bool:
        self._check_fence()
        with self._lock:
            gone = self._members.pop(member_id, None) is not None
            if gone:
                self._append_locked({"o": "mr", "id": member_id})
            return gone

    def member_list(self) -> list[Member]:
        self._check_fence()
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.id)

    # ------------------------------------------------------------- barriers

    def barrier(self, name: str, count: int, timeout: float | None = None) -> bool:
        """Block until ``count`` participants reach the named barrier.

        The reference got step-ordering for free from raft linearizability;
        collective Store epochs need an explicit rendezvous (SURVEY.md §7
        hard part: "barrier/epoch notion absent from the reference").
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._barrier_cond:
            b = self._barriers.setdefault(name, {"arrived": 0, "gen": 0})
            gen = b["gen"]
            b["arrived"] += 1
            if b["arrived"] >= count:
                b["arrived"] = 0
                b["gen"] += 1
                self._barrier_cond.notify_all()
                return True
            while b["gen"] == gen:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        b["arrived"] = max(0, b["arrived"] - 1)
                        return False
                self._barrier_cond.wait(remaining)
            return True

    # ---------------------------------------------------------------- misc

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rev

    @property
    def term(self) -> int:
        with self._lock:
            return self._term

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            watches = list(self._watches)
            feeds = list(self._repl_feeds)
            if self._wal is not None:
                try:
                    self._wal.close()
                except OSError:
                    pass
                self._wal = None
            if self._flock is not None:
                try:
                    self._flock.close()  # releases the WAL-dir fence
                except OSError:
                    pass
                self._flock = None
        for w in watches:
            w.cancel()
        for feed in feeds:
            feed.cancel()
