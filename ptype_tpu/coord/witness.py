"""Witness node: the third vote that closes the partition hole.

The primary + WAL-standby pair covers crash failover, but a LIVE network
partition leaves a linearizability hole raft never had: a superseded
primary that can still reach *some* clients keeps serving them stale
state, because the term fence only helps clients that have SEEN the new
term. The reference embedded a raft member in every process
(/root/reference/cluster/cluster.go:161-196) and proved real quorum
behavior under partition (cluster_test.go:47-167): the minority side
cannot serve.

This module is the TPU build's quorum element — a deliberately tiny
lease server, not a consensus log (the WAL stream already replicates
state; what was missing is only the MAJORITY VOTE):

- The serving primary must hold a renewable lease here (or be in live
  round-trip contact with its WAL follower — either grants the second
  vote of the {primary, standby, witness} majority;
  :class:`~ptype_tpu.coord.service.CoordServer` ``witness_addr``).
  A primary that can reach NEITHER is the minority side of a partition
  and self-fences when the lease TTL lapses — refusing its clients
  rather than serving possibly-superseded state.
- A standby may only promote after acquiring the lease, which the
  witness grants only once the primary's lease has EXPIRED — so at most
  one side of a partition can ever hold it (the fencing-token pattern;
  same shape as a chubby/etcd election lease).

Timing safety: the primary stamps its quorum deadline BEFORE sending a
renewal, the witness stamps the lease deadline AT receipt — so the
primary's self-fence always fires at or before the moment the witness
could hand the lease to a challenger. Only clock RATE drift (not
offset) could narrow that margin.

The witness persists ``(holder, term)`` when given a ``data_dir`` so a
witness restart cannot be tricked into granting a second, lower-term
lease; on restart the lease deadline is re-armed to a full TTL (it
cannot know how fresh the incumbent is, so it assumes the newest).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from ptype_tpu import lockcheck

from ptype_tpu import logs
from ptype_tpu.coord import wire

log = logs.get_logger("coord.witness")

#: Default lease TTL (seconds). Renewals should run at ~ttl/3.
DEFAULT_TTL = 3.0


class WitnessServer:
    """Single-lease vote server. Ops (all fire one reply):

    - ``vote_renew   {holder, term}`` — extend the lease iff ``holder``
      is the incumbent (or the lease is vacant) and ``term`` is not
      behind. Refusal tells a superseded primary it must HARD-fence.
    - ``vote_acquire {candidate, term}`` — take the lease iff vacant,
      expired, or already held by ``candidate``; a takeover from a
      different holder additionally requires ``term`` strictly above
      the recorded one (the promotion bump).
    - ``vote_status  {}`` — introspection: holder/term/remaining.
    """

    def __init__(self, address: str = "127.0.0.1:0",
                 ttl: float = DEFAULT_TTL,
                 data_dir: str | None = None):
        self.ttl = ttl
        self._data_dir = data_dir
        self._lock = lockcheck.lock("coord.witness")
        self._holder: str | None = None
        self._term = 0
        self._deadline = 0.0  # monotonic; 0 = vacant/expired
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()
        host, _, port = address.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(64)
        self.address = (f"{self._sock.getsockname()[0]}:"
                        f"{self._sock.getsockname()[1]}")
        self._closed = threading.Event()
        threading.Thread(target=self._accept_loop, name="witness-accept",
                         daemon=True).start()
        log.info("witness listening",
                 kv={"addr": self.address, "ttl": ttl,
                     "holder": self._holder, "term": self._term})

    # ------------------------------------------------------------ state

    def _state_path(self) -> str:
        return os.path.join(self._data_dir, "witness.json")

    def _load(self) -> None:
        try:
            with open(self._state_path(), encoding="utf-8") as f:
                st = json.load(f)
        except (OSError, ValueError):
            return
        self._holder = st.get("holder")
        self._term = int(st.get("term", 0))
        if self._holder is not None:
            # Can't know how stale the incumbent is across a restart:
            # assume freshest (full TTL) so a restart never hands the
            # lease to a challenger early.
            self._deadline = time.monotonic() + self.ttl

    def _persist_locked(self) -> None:
        if not self._data_dir:
            return
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"holder": self._holder, "term": self._term}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path())
        # Always durable (writes happen only at holder/term changes —
        # rare): a restart that forgot the lease would grant a second,
        # lower-term one, exactly the split brain persistence prevents.
        from ptype_tpu.coord.core import fsync_dir

        fsync_dir(self._data_dir)

    # ------------------------------------------------------------- votes

    def _vote(self, msg: dict) -> dict:
        op = msg.get("op")
        now = time.monotonic()
        with self._lock:
            if op == "vote_renew":
                holder, term = msg["holder"], int(msg.get("term", 0))
                vacant = (self._holder is None or now > self._deadline)
                if ((self._holder == holder or vacant)
                        and term >= self._term):
                    changed = (self._holder != holder
                               or term > self._term)
                    self._holder, self._term = holder, max(
                        term, self._term)
                    self._deadline = now + self.ttl
                    if changed:
                        self._persist_locked()
                    return {"granted": True, "term": self._term}
                return {"granted": False, "term": self._term,
                        "holder": self._holder}
            if op == "vote_acquire":
                cand, term = msg["candidate"], int(msg.get("term", 0))
                if self._holder == cand and term >= self._term:
                    pass  # idempotent re-acquire
                elif self._holder is None or now > self._deadline:
                    if term <= self._term and self._holder is not None:
                        # A takeover must carry the promotion bump:
                        # equal-term challengers (two juniors racing)
                        # must not both get a grant.
                        return {"granted": False, "term": self._term,
                                "holder": self._holder,
                                "reason": "term not above incumbent"}
                else:
                    return {"granted": False, "term": self._term,
                            "holder": self._holder,
                            "reason": "lease active"}
                self._holder = cand
                self._term = max(term, self._term)
                self._deadline = now + self.ttl
                self._persist_locked()
                log.info("witness lease granted",
                         kv={"holder": cand, "term": self._term})
                return {"granted": True, "term": self._term}
            if op == "vote_status":
                return {"holder": self._holder, "term": self._term,
                        "remaining": max(0.0, self._deadline - now)}
        raise ValueError(f"unknown witness op {op!r}")

    # --------------------------------------------------------- transport

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"witness-conn-{peer[1]}",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        lock = threading.Lock()
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._closed.is_set():
                try:
                    msg = wire.recv_msg(conn)
                except (wire.WireError, OSError):
                    return
                try:
                    reply = self._vote(msg)
                    reply.update({"id": msg.get("id"), "ok": True})
                except Exception as e:  # noqa: BLE001 — serve on
                    reply = {"id": msg.get("id"), "ok": False,
                             "error": str(e)}
                try:
                    wire.send_msg(conn, lock, reply)
                except (wire.WireError, OSError):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


def _witness_call(address: str, msg: dict, timeout: float) -> dict:
    """One short-lived request/reply to the witness. Raises OSError /
    WireError on unreachability — callers treat that as a missing vote,
    never as a grant."""
    host, _, port = address.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.settimeout(timeout)
        wire.send_msg(sock, threading.Lock(), dict(msg, id=1))
        reply = wire.recv_msg(sock)
        if not reply.get("ok"):
            raise wire.WireError(
                f"witness error: {reply.get('error')}")
        return reply
    finally:
        try:
            sock.close()
        except OSError:
            pass


def renew(address: str, holder: str, term: int,
          timeout: float = 1.0) -> dict:
    return _witness_call(
        address, {"op": "vote_renew", "holder": holder, "term": term},
        timeout)


def acquire(address: str, candidate: str, term: int,
            timeout: float = 2.0) -> dict:
    return _witness_call(
        address,
        {"op": "vote_acquire", "candidate": candidate, "term": term},
        timeout)


def status(address: str, timeout: float = 2.0) -> dict:
    return _witness_call(address, {"op": "vote_status"}, timeout)
