"""Runtime lock-order watchdog — the dynamic half of the concurrency
checks (ptlint PT013/PT014 are the static half).

Go's race detector kept the reference's concurrency honest for free;
CPython has no equivalent, so this module instruments the locks
themselves. Every lock the package creates goes through the factory
seam (:func:`lock` / :func:`rlock` / :func:`condition`):

- **disarmed** (the default), the factory returns the plain
  ``threading`` primitive — zero per-acquire overhead, one extra
  function call at construction;
- **armed** (:func:`enable`, or ``PTYPE_LOCKCHECK=1`` in the
  environment at import), it returns a tracked wrapper that records
  the per-process lock-acquisition graph: an edge A→B for every
  acquire of B while A is held (by name — every instance of
  ``gateway.pool.replicas`` is one node, which is what makes the
  graph finite and the order contract meaningful).

Findings:

- **cycle** — a new edge closes a directed cycle in the acquisition
  graph: two threads taking the same locks in opposite orders is a
  deadlock waiting for the right interleaving, whether or not it hung
  THIS run. Dumped through the flight-recorder seam
  (:func:`ptype_tpu.trace.add_event` + ``trace.maybe_dump``) the
  moment it is detected, so a post-mortem carries the span ring of
  the run that produced it.
- **hold** — a lock held longer than ``hold_budget_s`` (default 1 s):
  not a deadlock, but exactly the PT014 shape (blocking work inside a
  critical section) measured instead of inferred. Condition ``wait``
  is exempt while parked — waiting released the lock.

Armed through the chaos soak and the reconciler/gateway test tiers,
every future concurrency PR runs under it for free; the bench tail's
``lockcheck_overhead_pct`` prices the wrapper (<1% disarmed, <5%
armed is the bar).

Stdlib-only at import (the trace import is lazy, on the finding
path): locks are created at the very bottom of the stack and this
module must never cycle.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "enable", "disable", "active", "lock", "rlock", "condition",
    "Watchdog", "ENV_VAR", "HOLD_ENV_VAR",
]

ENV_VAR = "PTYPE_LOCKCHECK"
HOLD_ENV_VAR = "PTYPE_LOCKCHECK_HOLD_MS"
DEFAULT_HOLD_BUDGET_S = 1.0


class Watchdog:
    """Per-process acquisition graph + findings ledger."""

    def __init__(self, hold_budget_s: float = DEFAULT_HOLD_BUDGET_S):
        self.hold_budget_s = float(hold_budget_s)
        self._mu = threading.Lock()          # guards graph + findings
        self._edges: dict[str, set[str]] = {}
        #: (src, dst) -> name of the thread that FIRST took dst under
        #: src — the attribution a cycle report carries (bounded by
        #: the lock-name universe, same as the edge set).
        self._edge_threads: dict[tuple[str, str], str] = {}
        self._findings: list[dict] = []
        #: Per-thread acquire tallies, summed by :meth:`report` — a
        #: shared `+= 1` on the no-edge fast path would lose updates
        #: under exactly the contention the watchdog observes, and
        #: taking ``_mu`` there would serialize every tracked lock in
        #: the process through one global lock.
        self._counts: list[list[int]] = []
        #: Releases with no matching acquire on THIS thread's stack:
        #: a lock acquired in one thread and released in another (the
        #: hand-off pattern) is outside the tracker's model — the
        #: acquirer's stack entry leaks and later edges from it are
        #: suspect. Nonzero here means treat the graph with care.
        self._unmatched_releases = 0
        self._tls = threading.local()

    # ------------------------------------------------------------ held

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _count_cell(self) -> list[int]:
        cell = getattr(self._tls, "count", None)
        if cell is None:
            cell = self._tls.count = [0]
            with self._mu:
                self._counts.append(cell)
        return cell

    # -------------------------------------------------------- tracking

    def on_acquired(self, name: str) -> None:
        """Called by a tracked lock AFTER its acquire succeeded."""
        held = self._held()
        new_edges = []
        for h_name, _t0 in held:
            if h_name != name:  # reentrant re-acquire is not an order
                new_edges.append(h_name)
        held.append((name, time.monotonic()))
        self._count_cell()[0] += 1
        if not new_edges:
            return
        cycles: list[list[str]] = []
        with self._mu:
            for src in new_edges:
                dsts = self._edges.setdefault(src, set())
                if name in dsts:
                    continue
                dsts.add(name)
                self._edge_threads[(src, name)] = (
                    threading.current_thread().name)
                cycle = self._find_cycle_locked(name, src)
                if cycle is not None:
                    cycles.append(cycle)
        for cycle in cycles:
            # Record + emit OUTSIDE _mu: the emit path writes a
            # flight-recorder dump (disk I/O) — holding the global
            # graph lock across it would stall every edge-creating
            # acquire in the process (the PT014 shape, in the tool
            # that polices it).
            self._record_cycle(cycle)

    def on_released(self, name: str, waited: bool = False) -> None:
        """Called by a tracked lock BEFORE its release. ``waited``
        marks a Condition.wait park — the hold budget excuses it (the
        lock was not actually held while parked)."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _, t0 = held.pop(i)
                dur = time.monotonic() - t0
                if not waited and dur > self.hold_budget_s:
                    self._record_hold(name, dur)
                return
        with self._mu:
            self._unmatched_releases += 1

    def on_released_all(self, name: str) -> int:
        """Unwind EVERY held entry for ``name`` (a Condition's
        ``_release_save`` drops all recursion levels of an RLock at
        once, to park in wait). Returns the count so the restore can
        re-arm the same depth. Never a hold finding — parking is not
        holding."""
        held = self._held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                held.pop(i)
                n += 1
        return n

    def _find_cycle_locked(self, start: str,
                           target: str) -> list[str] | None:
        """Path start → … → target in the edge graph (its existence
        plus the just-added target→start edge is a cycle)."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == target:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -------------------------------------------------------- findings

    def _record_cycle(self, path: list[str]) -> None:
        cycle = path + [path[0]]
        with self._mu:
            edges = {f"{a}->{b}": self._edge_threads.get((a, b), "?")
                     for a, b in zip(cycle, cycle[1:])}
        finding = {
            "kind": "cycle",
            "cycle": cycle,
            #: Which thread FIRST took each edge — the two (or more)
            #: call paths the runbook tells the operator to grep for.
            "edge_threads": edges,
            "thread": threading.current_thread().name,
            "t": time.time(),
        }
        with self._mu:
            self._findings.append(finding)
        self._emit(finding)

    def _record_hold(self, name: str, dur_s: float) -> None:
        finding = {
            "kind": "hold",
            "lock": name,
            "held_s": round(dur_s, 4),
            "budget_s": self.hold_budget_s,
            "thread": threading.current_thread().name,
            "t": time.time(),
        }
        with self._mu:
            self._findings.append(finding)
        self._emit(finding)

    @staticmethod
    def _emit(finding: dict) -> None:
        """Dump through the flight-recorder seam: an event on the
        active span (when tracing is armed) and a rate-limited ring
        dump for cycles — the post-mortem artifact. Lazy import: locks
        live below every other subsystem."""
        try:
            from ptype_tpu import trace

            trace.add_event(f"lockcheck.{finding['kind']}",
                            **{k: str(v) for k, v in finding.items()
                               if k not in ("kind", "t")})
            if finding["kind"] == "cycle":
                trace.maybe_dump("lock-order cycle: "
                                 + " -> ".join(finding["cycle"]))
        except Exception:  # noqa: BLE001 — a watchdog must never
            pass           # break the lock it watches

    # ------------------------------------------------------ inspection

    def cycles(self) -> list[dict]:
        with self._mu:
            return [f for f in self._findings if f["kind"] == "cycle"]

    def holds(self) -> list[dict]:
        with self._mu:
            return [f for f in self._findings if f["kind"] == "hold"]

    def findings(self) -> list[dict]:
        with self._mu:
            return list(self._findings)

    def report(self) -> dict:
        with self._mu:
            return {
                "acquires": sum(c[0] for c in self._counts),
                "locks": sorted(
                    set(self._edges)
                    | {d for v in self._edges.values() for d in v}),
                "edges": {src: sorted(dsts)
                          for src, dsts in sorted(self._edges.items())},
                "edge_threads": {f"{a}->{b}": t for (a, b), t
                                 in sorted(self._edge_threads.items())},
                "cycles": [f for f in self._findings
                           if f["kind"] == "cycle"],
                "holds": [f for f in self._findings
                          if f["kind"] == "hold"],
                "unmatched_releases": self._unmatched_releases,
            }


class TrackedLock:
    """A named threading.Lock/RLock wrapper feeding the watchdog."""

    __slots__ = ("_name", "_inner", "_wd")

    def __init__(self, name: str, inner, wd: Watchdog):
        self._name = name
        self._inner = inner
        self._wd = wd

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._wd.on_acquired(self._name)
        return got

    def release(self) -> None:
        self._wd.on_released(self._name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # -- the threading.Condition protocol. A Condition built OVER a
    # tracked lock (the coord idiom: ``threading.Condition(self._lock)``
    # with the state RLock) probes ownership via ``_is_owned`` and
    # parks via ``_release_save``/``_acquire_restore``. Without these
    # proxies, Condition's fallback probe does a non-blocking
    # ``acquire(0)`` — which SUCCEEDS on a wrapped re-entrant RLock
    # the caller already owns — and notify/wait raise
    # "cannot notify on un-acquired lock" the moment the watchdog
    # arms.

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # Plain Lock: mirror Condition's own probe semantics.
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        n = self._wd.on_released_all(self._name)
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), n)
        self._inner.release()
        return (None, n)

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        for _ in range(n):
            # Re-arm exactly the depth _release_save unwound: the
            # wake-up re-acquire is an acquisition event (edges from
            # whatever this thread now holds are real order facts).
            self._wd.on_acquired(self._name)

    def __repr__(self) -> str:
        return f"TrackedLock({self._name!r}, {self._inner!r})"


class TrackedCondition:
    """A named Condition wrapper: acquire/release feed the watchdog;
    ``wait``/``wait_for`` unwind the hold (the condition RELEASES the
    lock while parked) and re-arm it on wake."""

    __slots__ = ("_name", "_inner", "_wd")

    def __init__(self, name: str, inner: threading.Condition,
                 wd: Watchdog):
        self._name = name
        self._inner = inner
        self._wd = wd

    def acquire(self, *args):
        got = self._inner.acquire(*args)
        if got:
            self._wd.on_acquired(self._name)
        return got

    def release(self) -> None:
        self._wd.on_released(self._name)
        self._inner.release()

    def __enter__(self):
        self._inner.__enter__()
        self._wd.on_acquired(self._name)
        return self

    def __exit__(self, *exc):
        self._wd.on_released(self._name)
        return self._inner.__exit__(*exc)

    def wait(self, timeout: float | None = None):
        self._wd.on_released(self._name, waited=True)
        try:
            return self._inner.wait(timeout)
        finally:
            self._wd.on_acquired(self._name)

    def wait_for(self, predicate, timeout: float | None = None):
        self._wd.on_released(self._name, waited=True)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._wd.on_acquired(self._name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __repr__(self) -> str:
        return f"TrackedCondition({self._name!r})"


# ------------------------------------------------------------ module API

_watchdog: Watchdog | None = None


def enable(hold_budget_s: float | None = None) -> Watchdog:
    """Arm the watchdog process-wide; locks created through the seam
    FROM NOW ON are tracked (existing plain locks are not retrofit —
    arm before constructing the stack under test). Returns the fresh
    watchdog; re-enabling replaces graph and findings."""
    global _watchdog
    if hold_budget_s is None:
        ms = os.environ.get(HOLD_ENV_VAR)
        hold_budget_s = (float(ms) / 1000.0 if ms
                         else DEFAULT_HOLD_BUDGET_S)
    _watchdog = Watchdog(hold_budget_s)
    return _watchdog


def disable() -> None:
    global _watchdog
    _watchdog = None


def active() -> Watchdog | None:
    return _watchdog


def lock(name: str):
    """A ``threading.Lock`` — tracked under ``name`` when armed. The
    one-line seam every lock in the package rides."""
    wd = _watchdog
    if wd is None:
        return threading.Lock()
    return TrackedLock(name, threading.Lock(), wd)


def rlock(name: str):
    wd = _watchdog
    if wd is None:
        return threading.RLock()
    return TrackedLock(name, threading.RLock(), wd)


def condition(name: str):
    wd = _watchdog
    if wd is None:
        return threading.Condition()
    return TrackedCondition(name, threading.Condition(), wd)


def _maybe_enable_from_env() -> None:
    if os.environ.get(ENV_VAR, "").lower() in ("1", "true", "on"):
        enable()


_maybe_enable_from_env()
