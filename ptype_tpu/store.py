"""Replicated KV store — metadata tier.

Capability parity with the reference's ``KVStore`` (cluster/store.go:18-74):
namespaced get/put/delete under ``store/`` with a typed no-key error, plus
the full query-option surface the reference re-exported from etcd
(cluster/store_config.go:33-103) so callers never import the coordination
layer directly.

This tier is for **small control-plane state** (hyperparameters, schedule
state, epoch counters, checkpoint manifests). The tensor tier — parameters
and gradients whose push/pull lowers to XLA collectives — lives in
``ptype_tpu.parallel.tensorstore``.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable

from ptype_tpu.coord.api import CoordBackend
from ptype_tpu.coord.core import (
    KVItem,
    RangeOptions,
    SortOrder,
    SortTarget,
    prefix_range_end,
)
from ptype_tpu.errors import NoKeyError

STORE_PREFIX = "store"

#: A query option is a pure transform of RangeOptions (functional options,
#: the shape the reference exposed as clientv3.OpOption).
Option = Callable[[RangeOptions], RangeOptions]


# ---------------------------------------------------------------- options
# Mirrors store_config.go:33-103 one for one.

def with_prefix() -> Option:
    """Match every key with the given key as prefix (store_config.go:63-65)."""
    return lambda o: replace(o, prefix=True)


def with_limit(n: int) -> Option:
    """Cap the number of results (store_config.go:69)."""
    return lambda o: replace(o, limit=n)


def with_sort(target: SortTarget, order: SortOrder) -> Option:
    """Sort results (store_config.go:33-37)."""
    return lambda o: replace(o, sort_target=target, sort_order=order)


def with_range(range_end: str) -> Option:
    """Explicit [key, range_end) interval (store_config.go:79-81)."""
    return lambda o: replace(o, range_end=range_end)


def with_from_key() -> Option:
    """All keys >= the given key (store_config.go:85)."""
    return lambda o: replace(o, from_key=True)


def with_serializable() -> Option:
    """Allow a serializable (non-linearizable) read (store_config.go:90-92).

    The single-coordinator backend serves every read linearizably, so this
    is accepted-and-satisfied rather than a relaxation.
    """
    return lambda o: replace(o, serializable=True)


def with_keys_only() -> Option:
    """Return keys with empty values (store_config.go:96-98)."""
    return lambda o: replace(o, keys_only=True)


def with_count_only() -> Option:
    """Return only the match count (store_config.go:101-103)."""
    return lambda o: replace(o, count_only=True)


def with_min_mod_rev(rev: int) -> Option:
    """Filter to entries modified at or after ``rev``."""
    return lambda o: replace(o, min_mod_rev=rev)


def with_rev(rev: int) -> Option:
    """Read AT a historical revision (store_config.go:71-73): the
    result is the store's state as of revision ``rev``, reconstructed
    from the coordinator's bounded MVCC history. Raises once the
    revision falls behind the retained window ("compacted", etcd
    parity) or is ahead of the head."""
    return lambda o: replace(o, rev=rev)


def get_prefix_range_end(prefix: str) -> str:
    """Exclusive upper bound of a prefix range (store_config.go:41-58)."""
    return prefix_range_end(prefix)


def _resolve(options: tuple[Option, ...]) -> RangeOptions:
    opts = RangeOptions()
    for opt in options:
        opts = opt(opts)
    return opts


def _store_key(key: str) -> str:
    return f"{STORE_PREFIX}/{key}"


# ------------------------------------------------------------------ store

class KVStore:
    """Namespaced KV over the coordination backend (ref: store.go:18-35)."""

    def __init__(self, coord: CoordBackend):
        self._coord = coord

    def get(self, key: str, *options: Option) -> list[str]:
        """Values for the best-matched key(s); raises NoKeyError when none
        match (ref: store.go:38-53)."""
        res = self._coord.range(_store_key(key), _resolve(options))
        if res.count == 0:
            raise NoKeyError(key)
        return [it.value for it in res.items]

    def get_one(self, key: str, *options: Option) -> str:
        """Single-value convenience over :meth:`get`."""
        return self.get(key, *options)[0]

    def get_items(self, key: str, *options: Option) -> list[KVItem]:
        """Full KV records (keys, revisions, lease ids) for a query."""
        res = self._coord.range(_store_key(key), _resolve(options))
        if res.count == 0:
            raise NoKeyError(key)
        return list(res.items)

    def count(self, key: str, *options: Option) -> int:
        """Match count without transferring values."""
        opts = _resolve(options + (with_count_only(),))
        return self._coord.range(_store_key(key), opts).count

    def put(self, key: str, value: str, sync: bool = False,
            sync_timeout: float | None = None,
            sync_min_followers: int = 0) -> None:
        """Set the value for the given key (ref: store.go:56-62).

        ``sync=True`` acks only once every attached WAL follower has
        mirrored the write — the raft-quorum-commit analog the
        reference's Put had for free: an acked write then survives an
        immediate primary death + standby takeover. Raises if not
        acknowledged within ``sync_timeout`` (None = default 5 s).
        ``sync_min_followers`` makes the put FAIL when fewer live
        mirrors are attached (e.g. the standby is mid-reconnect) —
        deployments that run a standby should set 1 so a degraded
        unreplicated ack can't masquerade as a replicated one."""
        self._coord.put(_store_key(key), value, sync=sync,
                        sync_timeout=sync_timeout,
                        sync_min_followers=sync_min_followers)

    def delete(self, key: str, *options: Option) -> None:
        """Delete key(s); raises NoKeyError when nothing was deleted
        (ref: store.go:65-74)."""
        deleted = self._coord.delete(_store_key(key), _resolve(options))
        if deleted == 0:
            raise NoKeyError(key)
