"""Cluster membership: join, members, close, new_client.

Capability parity with the reference's L2 (cluster/cluster.go:20-103):
``join(cfg)`` wires up the coordination backend, registry, and store,
self-registers this node, and returns a :class:`Cluster`. Where the
reference started an embedded raft member in every process
(cluster.go:161-196), the TPU-native model is seed-hosts-coordination:
the process whose platform config says ``is_coordinator: true`` serves
:class:`CoordServer`; everyone (including the seed) speaks the same
:class:`CoordBackend` interface. ``local:<name>`` coordinator addresses
select the in-process backend — the embedded-etcd-style test tier.

TPU wiring: when the platform config declares mesh axes, join discovers
this process's JAX devices and publishes their ordinals on the member
record and every service registration, making the registry the pod's
mesh map (north star, BASELINE.json).
"""

from __future__ import annotations

import socket
import threading

from ptype_tpu import logs
from ptype_tpu.config import Config
from ptype_tpu.coord.api import CoordBackend, connect
from ptype_tpu.coord.core import Member
from ptype_tpu.coord.local import local_coord
from ptype_tpu.coord.service import CoordServer
from ptype_tpu.errors import ClusterError, CoordinationError
from ptype_tpu.registry import CoordRegistry, Registration, Registry
from ptype_tpu.rpc import Client, ConnConfig
from ptype_tpu.store import KVStore

log = logs.get_logger("cluster")

# Coordination servers owned by this process, keyed by listen address —
# lets several in-process joins share one server (test topology parity
# with the reference's in-process multi-member suites, cluster_test.go).
_servers: dict[str, CoordServer] = {}
_servers_lock = threading.Lock()


def get_ip() -> str:
    """First non-loopback IPv4 of this host (ref: cluster.go:198-213)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # connect() on UDP sends no packets; it just resolves routing.
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            ip = info[4][0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    return "127.0.0.1"


def _local_device_ordinals() -> tuple[int, ...]:
    """Global ids of this process's JAX devices; () if JAX is unused."""
    try:
        import jax

        return tuple(d.id for d in jax.local_devices())
    except Exception as e:  # noqa: BLE001 — control-plane-only processes
        log.debug("no local JAX devices", kv={"err": str(e)})
        return ()


class Cluster:
    """A joined cluster member (ref: cluster.go:20-26)."""

    def __init__(self, cfg: Config, coord: CoordBackend,
                 registry: Registry, store: KVStore,
                 member: Member, registration: Registration | None,
                 owned_server: CoordServer | None,
                 advertise_host: str,
                 device_ordinals: tuple[int, ...]):
        self.cfg = cfg
        self.coord = coord
        self.registry = registry
        self.store = store
        self.member = member
        self.registration = registration
        self.advertise_host = advertise_host
        self.device_ordinals = device_ordinals
        self._owned_server = owned_server
        self._closed = False

    def member_list(self) -> list[Member]:
        """Ref: cluster.go:86-93."""
        return self.coord.member_list()

    def new_client(self, service_name: str,
                   cfg: ConnConfig | None = None) -> Client:
        """Load-balanced client for a service (ref: cluster.go:101-103)."""
        return Client(self.advertise_host, service_name, self.registry, cfg)

    def mesh(self, axis_names: tuple[str, ...] | None = None):
        """Device mesh from the platform config's axes — the registry-as-
        mesh-map lowering. See ptype_tpu.parallel.mesh."""
        from ptype_tpu.parallel.mesh import build_mesh

        return build_mesh(self.cfg.platform.mesh_axes, axis_names)

    def close(self) -> None:
        """Leave the cluster (ref: cluster.go:95-99 — plus prompt
        deregistration, which the reference skipped; SURVEY.md §2)."""
        if self._closed:
            return
        self._closed = True
        if self.registration is not None:
            self.registration.close(revoke=True)
        try:
            self.coord.member_remove(self.member.id)
        except CoordinationError:
            pass
        self.coord.close()
        if self._owned_server is not None:
            with _servers_lock:
                addr = self._owned_server.address
                if _servers.get(addr) is self._owned_server:
                    del _servers[addr]
            self._owned_server.close()
        log.info("left cluster", kv={"node": self.cfg.node_name})


def _init_jax_distributed(platform) -> None:
    """Initialize the multi-controller JAX runtime as part of join —
    Join does *everything* in the reference (cluster.go:28-84); the TPU
    translation is "Join ≈ jax.distributed.initialize + mesh
    construction" (SURVEY §3.1). No-op when already initialized (e.g.
    the launcher did it) so join stays idempotent."""
    import jax

    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:
            log.debug("jax.distributed already initialized")
            return
    except Exception:  # noqa: BLE001 — internals moved; initialize anyway
        pass
    addr = platform.jax_coordinator_address
    if not addr:
        host, _, port = platform.coordinator_address.rpartition(":")
        addr = f"{host}:{int(port) + 1}"
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=platform.num_processes,
        process_id=platform.process_id,
    )
    log.info("jax distributed initialized",
             kv={"addr": addr, "process": platform.process_id,
                 "n": platform.num_processes})


def join(cfg: Config) -> Cluster:
    """Join (or seed) the cluster described by ``cfg`` (ref: cluster.go:28-84)."""
    logs.set_debug(cfg.debug)
    platform = cfg.platform

    owned_server: CoordServer | None = None
    coord_addr = platform.coordinator_address

    # Control plane FIRST, JAX runtime second: the seed must be
    # dialable before it blocks in jax.distributed.initialize, and
    # joiners must keep retrying within dial_timeout — simultaneous
    # process launch otherwise races join into "connection refused"
    # (observed: a joiner dialing in the ms between the seed's jax init
    # and its server bind).
    if coord_addr.startswith("local:"):
        coord: CoordBackend = local_coord(coord_addr.split(":", 1)[1])
    elif platform.is_coordinator:
        with _servers_lock:
            server = _servers.get(coord_addr)
            if server is None:
                import os as _os

                # Durable control plane (ref: etcd data-dir): the seed
                # WALs its CoordState so registry/store survive restart.
                server = CoordServer(
                    coord_addr,
                    data_dir=(_os.path.join(platform.data_dir, "coord")
                              if platform.data_dir else None),
                    fsync=platform.wal_fsync,
                    witness_addr=platform.witness_address or None,
                    witness_ttl=platform.witness_ttl,
                )
                _servers[server.address] = server
                owned_server = server
        # The seed talks to its own state in-process — no self-dial.
        from ptype_tpu.coord.local import LocalCoord

        coord = LocalCoord(server.state)
        log.debug("seeded coordination service", kv={"addr": server.address})
    else:
        # Join an existing cluster through any known client URL
        # (ref: joinExistingCluster, cluster.go:105-118), retrying the
        # endpoint list until dial_timeout: cluster launchers start the
        # seed and joiners at the same instant.
        import time as _time

        from ptype_tpu import retry as _retry

        endpoints = cfg.initial_cluster_client_urls or [coord_addr]
        deadline = _time.monotonic() + platform.dial_timeout
        last: Exception | None = None
        coord = None  # type: ignore[assignment]
        join_bo = _retry.Backoff(base=0.2, cap=1.0)
        while coord is None:
            per_dial = max(0.5, deadline - _time.monotonic())
            try:
                # The FULL endpoint list goes to the client: on a later
                # connection loss it fails over to any standby
                # (coord.standby) in the list, not just the seed —
                # and discovery extends the list with promote-eligible
                # standbys attached after this process joined.
                coord = connect(endpoints, dial_timeout=per_dial,
                                discovery_interval=5.0)
            except CoordinationError as e:
                last = e
                if _time.monotonic() >= deadline:
                    raise ClusterError(
                        f"failed to reach coordination service via "
                        f"{endpoints}: {last}"
                    ) from e
                join_bo.sleep()

    if platform.num_processes > 1:
        _init_jax_distributed(platform)

    device_ordinals = (
        _local_device_ordinals() if platform.mesh_axes else ()
    )
    advertise_host = get_ip()

    member = coord.member_add(
        cfg.node_name,
        f"{advertise_host}:{cfg.port}",
        metadata={
            "service": cfg.service_name,
            "process_id": platform.process_id,
            "device_ordinals": list(device_ordinals),
        },
    )

    registry = CoordRegistry(coord, lease_ttl=platform.lease_ttl)
    store = KVStore(coord)

    registration = None
    if cfg.service_name:
        # Self-register (ref: cluster.go:69-73). Registration is always on:
        # a node that serves nothing is still discoverable for liveness.
        registration = registry.register(
            cfg.service_name, cfg.node_name, advertise_host, cfg.port,
            process_id=platform.process_id,
            device_ordinals=device_ordinals,
        )

    log.info("joined cluster",
             kv={"service": cfg.service_name, "node": cfg.node_name,
                 "member_id": member.id, "devices": list(device_ordinals)})
    return Cluster(cfg, coord, registry, store, member, registration,
                   owned_server, advertise_host, device_ordinals)
