"""Actor RPC payload codec: JSON structure + raw tensor blobs.

The reference marshalled actor payloads with gob (net/rpc default,
rpc.go:277). The TPU-native requirement (BASELINE.json north star) is that
tensor payloads land as device buffers, not as generic object graphs — so
the codec splits every payload into (a) a JSON-safe structure and (b) a list
of contiguous binary blobs for arrays, which are materialized on the
receiving side with ``jax.device_put`` (JAX arrays) or ``np.frombuffer``
(NumPy). Blob bytes are written directly after the header — no base64, no
copy through a JSON string.

Frame layout::

    [4B header_len][header JSON][blob 0][blob 1]...

Header: ``{"tree": <structure>, "blobs": [len0, len1, ...]}`` where arrays
appear in the structure as ``{"__tensor__": i, "dtype": ..., "shape": ...,
"kind": "jax"|"np"}`` and raw bytes as ``{"__bytes__": i}``.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

try:
    # Registers bfloat16/fp8 etc. with NumPy's dtype system so
    # np.dtype("bfloat16") round-trips; ships with JAX.
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    pass

_LEN = struct.Struct(">I")


class CodecError(ValueError):
    pass


def _is_jax_array(x: Any) -> bool:
    # Avoid importing jax eagerly for pure-control-plane processes.
    mod = type(x).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    import jax

    return isinstance(x, jax.Array)


def _encode_impl(payload: Any) -> tuple[bytes, list]:
    """(header JSON bytes, blob list) — the frame minus assembly."""
    blobs: list[bytes | memoryview] = []

    def enc(x: Any):
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        if isinstance(x, (bytes, bytearray, memoryview)):
            blobs.append(bytes(x))
            return {"__bytes__": len(blobs) - 1}
        if isinstance(x, np.ndarray):
            arr = np.ascontiguousarray(x)
            blobs.append(memoryview(arr).cast("B"))
            return {"__tensor__": len(blobs) - 1, "dtype": str(arr.dtype),
                    "shape": list(arr.shape), "kind": "np"}
        if _is_jax_array(x):
            arr = np.asarray(x)  # device -> host transfer happens here
            arr = np.ascontiguousarray(arr)
            blobs.append(memoryview(arr).cast("B"))
            return {"__tensor__": len(blobs) - 1,
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape), "kind": "jax"}
        if isinstance(x, np.generic):
            return enc(np.asarray(x))
        if isinstance(x, (list, tuple)):
            tag = "__list__" if isinstance(x, list) else "__tuple__"
            return {tag: [enc(v) for v in x]}
        if isinstance(x, dict):
            for k in x:
                if not isinstance(k, str):
                    raise CodecError(f"dict keys must be str, got {type(k)}")
                if k.startswith("__") and k.endswith("__"):
                    raise CodecError(f"reserved key name: {k!r}")
            return {k: enc(v) for k, v in x.items()}
        raise CodecError(f"cannot encode {type(x).__name__}")

    tree = enc(payload)
    header = json.dumps(
        {"tree": tree, "blobs": [len(b) for b in blobs]},
        separators=(",", ":"),
    ).encode("utf-8")
    return header, blobs


def encode(payload: Any) -> bytes:
    """Serialize an arbitrary pytree-ish payload into one frame."""
    return b"".join(encode_parts(payload))


def encode_parts(payload: Any) -> list[bytes]:
    """Like :func:`encode` but WITHOUT the final join: the frame as
    ``[4B header-len, header, blob0, ...]`` pieces. The native wire tier
    (ptype_tpu.native.send_frame) hands these to one writev(), so a
    multi-hundred-MB parameter payload is never copied into a second
    contiguous bytes object. ``b"".join(encode_parts(x)) == encode(x)``.
    """
    header, blobs = _encode_impl(payload)
    return [_LEN.pack(len(header)), header, *(bytes(b) for b in blobs)]


def decode(frame: bytes | memoryview, device: Any = None) -> Any:
    """Deserialize a frame.

    ``device``: optional JAX device (or sharding) that ``kind=="jax"``
    tensors are placed onto; default is JAX's default device. NumPy tensors
    stay on host either way.
    """
    frame = memoryview(frame)
    (header_len,) = _LEN.unpack(frame[: _LEN.size])
    header = json.loads(bytes(frame[_LEN.size : _LEN.size + header_len]))
    blob_lens = header["blobs"]
    blobs: list[memoryview] = []
    offset = _LEN.size + header_len
    for blen in blob_lens:
        blobs.append(frame[offset : offset + blen])
        offset += blen

    def dec(x: Any):
        if isinstance(x, dict):
            if "__bytes__" in x:
                return bytes(blobs[x["__bytes__"]])
            if "__tensor__" in x:
                arr = np.frombuffer(
                    blobs[x["__tensor__"]], dtype=np.dtype(x["dtype"])
                ).reshape(x["shape"])
                if x.get("kind") == "jax":
                    import jax

                    return jax.device_put(arr, device)
                return arr
            if "__list__" in x:
                return [dec(v) for v in x["__list__"]]
            if "__tuple__" in x:
                return tuple(dec(v) for v in x["__tuple__"])
            return {k: dec(v) for k, v in x.items()}
        return x

    return dec(header["tree"])
