"""Cluster telemetry pull plane: aggregate every node's observability.

One process's view lives in :func:`ptype_tpu.trace.telemetry` (metrics
snapshot + recent spans), served by every :class:`ActorServer` as the
built-in ``ptype.Telemetry`` endpoint. This module is the fleet-wide
half:

- :func:`cluster_snapshot` walks the registry and pulls every node's
  telemetry over its existing actor RPC surface — the observability
  plane needs no new server, no sidecar, no push pipeline;
- :func:`stitch_traces` merges the per-node span lists into connected
  traces keyed by ``trace_id`` (the cross-process record the wire
  propagation in rpc.py / coord/wire.py exists to produce);
- :func:`chrome_trace` / :func:`write_chrome_trace` emit Chrome
  trace-event JSON — load the file in Perfetto (ui.perfetto.dev) or
  ``chrome://tracing`` and every process's spans land on one
  wall-clock timeline, stitched by trace id;
- :func:`write_spans_jsonl` is the grep/jq tier (one span per line);
- :func:`render_summary` is the operator one-pager behind
  ``python -m ptype_tpu obs`` and ``make obs-demo``;
- :func:`cluster_profile` is the profiling-plane sibling (ISSUE 8): a
  simultaneous ``jax.profiler`` XPlane capture across every node via
  the built-in ``ptype.Profile`` endpoint, artifacts shipped back and
  written per node — ``python -m ptype_tpu obs profile``.

Also home to :func:`measure_trace_overhead` — the bench probe backing
``trace_overhead_pct`` in bench.py's tail record (the ~zero-cost
contract, measured instead of asserted).
"""

from __future__ import annotations

import json
import os
import time

from ptype_tpu import logs
from ptype_tpu.registry import Node, Registry

log = logs.get_logger("telemetry")

#: Per-node budget for the telemetry pull (dial + one Info-sized RPC).
DEFAULT_NODE_TIMEOUT_S = 3.0


def node_telemetry(node: Node, timeout: float = DEFAULT_NODE_TIMEOUT_S,
                   span_limit: int = 256) -> dict:
    """Pull one node's telemetry over its actor RPC surface."""
    from ptype_tpu import rpc as rpc_mod

    conn = rpc_mod._dial(node, dial_timeout=timeout)
    try:
        fut = conn.call_async("ptype.Telemetry", (span_limit,))
        return fut.result(timeout=timeout)
    finally:
        conn.close()


def cluster_snapshot(registry: Registry, services: list[str] | None = None,
                     timeout: float = DEFAULT_NODE_TIMEOUT_S,
                     span_limit: int = 256,
                     include_local: bool = True) -> dict:
    """Walk the registry and merge every node's telemetry.

    Returns ``{"ts", "nodes": {service/addr: telemetry},
    "errors": {service/addr: why}, "traces": {trace_id: [span, ...]}}``.
    Nodes that are registered but not actor servers (bare mesh members)
    land in ``errors`` — a partial snapshot of a degraded fleet is the
    point, so per-node failures never fail the walk. With
    ``include_local`` the calling process contributes its own telemetry
    under the key ``local`` (the aggregator is usually also the
    interesting client — its gateway/client spans stitch the fleet's
    server spans together).
    """
    from concurrent.futures import ThreadPoolExecutor

    out: dict = {"ts": round(time.time(), 3), "nodes": {}, "errors": {}}
    svc_map = registry.services()
    targets: list[tuple[str, Node]] = []
    for service in sorted(svc_map):
        if services is not None and service not in services:
            continue
        for node in svc_map[service]:
            targets.append((f"{service}/{node.address}:{node.port}", node))
    if targets:
        # Concurrent pulls (same reason the gateway's probe rounds are
        # concurrent): a degraded fleet is exactly when obs runs, and a
        # serial walk pays every blackholed node's dial timeout
        # additively instead of ~once.
        with ThreadPoolExecutor(
                max_workers=min(16, len(targets))) as pool:
            futs = {key: pool.submit(node_telemetry, node,
                                     timeout=timeout,
                                     span_limit=span_limit)
                    for key, node in targets}
        for key, fut in futs.items():
            try:
                out["nodes"][key] = fut.result()
            except Exception as e:  # noqa: BLE001 — partial is the point
                out["errors"][key] = f"{type(e).__name__}: {e}"
    if include_local:
        from ptype_tpu import trace

        out["nodes"]["local"] = trace.telemetry(span_limit=span_limit)
    out["traces"] = stitch_traces(all_spans(out))
    return out


def all_spans(snapshot: dict) -> list[dict]:
    """Every span in a snapshot, tagged with its node key and deduped
    by span id — several registry endpoints can share one process (and
    therefore one flight recorder), and a span must appear once per
    trace no matter how many service names its process serves under.
    The node key is ``<pid>``-qualified so one process is one Perfetto
    row, not one row per service alias."""
    spans: list[dict] = []
    seen: set[str] = set()
    #: pid → first node key seen for it: one process, one label.
    labels: dict = {}
    for key, telem in snapshot.get("nodes", {}).items():
        pid = telem.get("pid")
        label = labels.setdefault(pid, key) if pid else key
        for sp in telem.get("spans", ()):
            sid = sp.get("span_id", "")
            if sid in seen:
                continue
            seen.add(sid)
            spans.append({**sp, "node": label})
    return spans


def stitch_traces(spans: list[dict]) -> dict[str, list[dict]]:
    """Group spans into traces by ``trace_id``, each sorted by start
    time — the cross-process request record, reassembled."""
    traces: dict[str, list[dict]] = {}
    for sp in spans:
        traces.setdefault(sp.get("trace_id", "?"), []).append(sp)
    for tid in traces:
        traces[tid].sort(key=lambda s: s.get("start_s", 0.0))
    return traces


# ---------------------------------------------------- cluster profiling


def node_profile(node: Node, duration_s: float = 0.5,
                 timeout: float | None = None,
                 include_data: bool = True, label: str = "cluster",
                 dial_timeout: float = DEFAULT_NODE_TIMEOUT_S) -> dict:
    """One node's ``ptype.Profile`` capture over its actor surface:
    start an XPlane capture, run ``duration_s``, stop, and ship the
    artifact bytes + HBM snapshot back in the reply. Shared by
    :func:`cluster_profile` and the health plane's alert-triggered
    capture (``label="alert"``) — one dial/capture/ship sequence."""
    from ptype_tpu import rpc as rpc_mod

    timeout = (duration_s + 15.0) if timeout is None else timeout
    conn = rpc_mod._dial(node, dial_timeout=dial_timeout)
    try:
        fut = conn.call_async(
            "ptype.Profile",
            ("capture", {"duration_s": duration_s, "label": label,
                         "include_data": include_data}))
        return fut.result(timeout=timeout)
    finally:
        conn.close()


def cluster_profile(registry: Registry, duration_s: float = 0.5,
                    out_dir: str = ".",
                    services: list[str] | None = None,
                    timeout: float | None = None) -> dict:
    """Simultaneous device-profile capture across every registered
    node (ISSUE 8): every node's ``ptype.Profile`` endpoint starts its
    capture concurrently, so the per-node XPlane timelines cover ONE
    overlapping wall-clock window — and because ``metrics.annotate``
    feeds both the profiler and the distributed-trace plane, the
    ``train.step`` / ``store.push*`` regions in each device timeline
    line up with the same regions in the stitched span view
    (:func:`cluster_snapshot`).

    Artifacts land under ``out_dir/<service_addr_port>/`` per node
    (XPlane ``.pb`` + the host-parseable ``.trace.json.gz`` —
    :func:`ptype_tpu.health.profiling.summarize` reads the latter with
    no TensorBoard). Returns ``{"ts", "duration_s", "nodes":
    {key: {"dir", "files", "memory"}}, "errors": {key: why}}`` — like
    the telemetry pull, a partial capture of a degraded fleet is the
    point, so per-node failures (dead node, profiler already busy)
    never fail the walk.
    """
    from concurrent.futures import ThreadPoolExecutor

    from ptype_tpu.health import profiling

    out: dict = {"ts": round(time.time(), 3),
                 "duration_s": float(duration_s),
                 "nodes": {}, "errors": {}}
    svc_map = registry.services()
    targets: list[tuple[str, Node]] = []
    for service in sorted(svc_map):
        if services is not None and service not in services:
            continue
        for node in svc_map[service]:
            targets.append((f"{service}/{node.address}:{node.port}", node))
    if targets:
        # Concurrent on purpose — simultaneity IS the feature: the
        # fleet's captures must cover one shared window or cross-node
        # comparisons (who stalls while whose reduce runs) mean
        # nothing. One thread per node (they are I/O-bound waiters):
        # a 16-worker cap would queue the overflow into a LATER,
        # non-overlapping window and silently void that contract.
        with ThreadPoolExecutor(max_workers=len(targets)) as pool:
            futs = {key: pool.submit(node_profile, node,
                                     duration_s=duration_s,
                                     timeout=timeout)
                    for key, node in targets}
    else:
        futs = {}
    for key, fut in futs.items():
        try:
            result = fut.result()
        except Exception as e:  # noqa: BLE001 — partial is the point
            out["errors"][key] = f"{type(e).__name__}: {e}"
            continue
        node_dir = os.path.join(
            out_dir, key.replace("/", "_").replace(":", "_"))
        files = profiling.write_artifacts(node_dir, result)
        out["nodes"][key] = {
            "dir": node_dir,
            "files": [os.path.relpath(p, node_dir) for p in files],
            "remote_dir": result.get("dir"),
            "capture_s": result.get("duration_s"),
            "memory": result.get("memory"),
        }
    return out


# ------------------------------------------------------------- exporters


def chrome_trace(spans: list[dict]) -> dict:
    """Chrome trace-event JSON (the ``traceEvents`` array format) from
    span dicts — loadable in Perfetto / chrome://tracing.

    Spans become complete (``ph: X``) events on their process's row
    (grouped by the originating pid — several registry service names
    can alias one process); span events become instants (``ph: i``);
    every event
    carries ``trace_id``/``span_id``/``parent_id`` in ``args`` so a
    request can be followed across process rows by its trace id.
    Timestamps are the spans' wall-clock microseconds: processes share
    one timeline, which is what makes the stitched view readable.
    """
    events: list[dict] = []
    pids: dict[str, int] = {}
    for sp in spans:
        node = str(sp.get("node", sp.get("pid", "local")))
        pid = pids.setdefault(node, len(pids) + 1)
        tid = int(sp.get("tid", 0)) % 1_000_000
        ts_us = sp.get("start_s", 0.0) * 1e6
        args = {"trace_id": sp.get("trace_id"),
                "span_id": sp.get("span_id"),
                "parent_id": sp.get("parent_id"),
                "status": sp.get("status", "ok")}
        args.update(sp.get("attrs", {}))
        events.append({
            "ph": "X", "name": sp.get("name", "?"),
            "cat": sp.get("status", "ok"),
            "ts": ts_us, "dur": max(sp.get("dur_s", 0.0) * 1e6, 1.0),
            "pid": pid, "tid": tid, "args": args,
        })
        for ev in sp.get("events", ()):
            events.append({
                "ph": "i", "s": "t",
                "name": ev.get("name", "event"),
                "ts": ts_us + ev.get("t", 0.0) * 1e6,
                "pid": pid, "tid": tid,
                "args": {**ev.get("attrs", {}),
                         "trace_id": sp.get("trace_id"),
                         "span_id": sp.get("span_id")},
            })
    for node, pid in pids.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": node}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, snapshot_or_spans) -> str:
    """Write a snapshot's (or bare span list's) Chrome trace to
    ``path``; returns the path."""
    spans = (all_spans(snapshot_or_spans)
             if isinstance(snapshot_or_spans, dict) else snapshot_or_spans)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(spans), f, separators=(",", ":"))
    return path


def write_spans_jsonl(path: str, snapshot_or_spans) -> str:
    """One span dict per line — the grep/jq tier."""
    spans = (all_spans(snapshot_or_spans)
             if isinstance(snapshot_or_spans, dict) else snapshot_or_spans)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for sp in spans:
            f.write(json.dumps(sp, separators=(",", ":")) + "\n")
    return path


def render_summary(snapshot: dict) -> str:
    """Operator one-pager: per-node span/metric counts and the stitched
    trace inventory (what ``python -m ptype_tpu obs`` prints)."""
    lines = [f"cluster telemetry @ {snapshot.get('ts')}"]
    nodes = snapshot.get("nodes", {})
    lines.append(f"nodes: {len(nodes)}  "
                 f"unreachable: {len(snapshot.get('errors', {}))}")
    for key in sorted(nodes):
        t = nodes[key]
        m = t.get("metrics", {})
        lines.append(
            f"  {key}: pid={t.get('pid')} tracing={t.get('tracing')} "
            f"spans={len(t.get('spans', ()))} "
            f"(finished {t.get('spans_finished', 0)}) "
            f"counters={len(m.get('counters', {}))} "
            f"timings={len(m.get('timings', {}))} "
            f"gauges={len(m.get('gauges', {}))} "
            f"histograms={len(m.get('histograms', {}))}")
    for key in sorted(snapshot.get("errors", {})):
        lines.append(f"  {key}: UNREACHABLE "
                     f"({snapshot['errors'][key]})")
    traces = snapshot.get("traces", {})
    multi = {tid: sp for tid, sp in traces.items()
             if len({s.get("node") for s in sp}) > 1}
    lines.append(f"traces: {len(traces)} "
                 f"({len(multi)} spanning multiple nodes)")
    for tid, spans in sorted(traces.items(),
                             key=lambda kv: -len(kv[1]))[:8]:
        names = " → ".join(s.get("name", "?") for s in spans[:6])
        more = f" (+{len(spans) - 6})" if len(spans) > 6 else ""
        lines.append(f"  {tid[:16]}…: {len(spans)} spans: {names}{more}")
    return "\n".join(lines)


# ------------------------------------------------------- OpenMetrics


def _om_name(name: str) -> str:
    """Metric-name sanitization: ``gateway.llm.stage_ms.queue-wait``
    → ``gateway_llm_stage_ms_queue_wait`` (OpenMetrics charset)."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _om_labels(labels: dict | None, extra: str = "") -> str:
    parts = [f'{_om_name(k)}="{v}"' for k, v in (labels or {}).items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def openmetrics(source, labels: dict | None = None) -> str:
    """Render metrics as OpenMetrics text — the scrape format every
    standard collector speaks, so a long soak needs no bespoke reader.

    ``source`` is a :class:`~ptype_tpu.metrics.MetricsRegistry`, one
    process's ``snapshot()`` dict, or a full :func:`cluster_snapshot`
    (each node rendered with a ``node`` label). Counters render as
    ``_total`` samples, gauges as gauges, timings and histograms as
    quantile-labelled summaries; a histogram's worst trace-linked
    exemplar rides its ``quantile="0.99"`` sample in OpenMetrics
    exemplar syntax (``# {trace_id="..."} value``) — the p99 line
    literally names the trace to pull with ``obs request``."""
    snap = source.snapshot() if hasattr(source, "snapshot") else source
    lines: list[str] = []
    if "nodes" in snap and "counters" not in snap:
        for key in sorted(snap["nodes"]):
            m = snap["nodes"][key].get("metrics", {})
            node_labels = dict(labels or {})
            node_labels["node"] = key
            _om_family(lines, m, node_labels)
    else:
        _om_family(lines, snap, labels)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _om_family(lines: list, snap: dict, labels: dict | None) -> None:
    lab = _om_labels(labels)
    for name, v in sorted((snap.get("counters") or {}).items()):
        om = _om_name(name)
        lines.append(f"# TYPE {om} counter")
        lines.append(f"{om}_total{lab} {v}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        om = _om_name(name)
        lines.append(f"# TYPE {om} gauge")
        lines.append(f"{om}{lab} {v}")
    for name, s in sorted((snap.get("timings") or {}).items()):
        om = _om_name(name)
        lines.append(f"# TYPE {om} summary")
        for q, key in (("0.5", "p50_s"), ("0.95", "p95_s"),
                       ("0.99", "p99_s")):
            qlab = _om_labels(labels, 'quantile="%s"' % q)
            lines.append(f"{om}{qlab} {s.get(key, 0.0)}")
        lines.append(f"{om}_count{lab} {s.get('count', 0)}")
    for name, s in sorted((snap.get("histograms") or {}).items()):
        om = _om_name(name)
        lines.append(f"# TYPE {om} summary")
        exemplars = s.get("exemplars") or []
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            qlab = _om_labels(labels, 'quantile="%s"' % q)
            line = f"{om}{qlab} {s.get(key, 0.0)}"
            if q == "0.99" and exemplars:
                ex = exemplars[0]  # worst-first
                line += (' # {trace_id="%s"} %s %s'
                         % (ex["trace_id"], ex["value"],
                            ex.get("ts", 0.0)))
            lines.append(line)
        lines.append(f"{om}_count{lab} {s.get('count', 0)}")


# ------------------------------------------------------------ bench probe


def measure_trace_overhead(steps: int = 16, preset: str = "tiny",
                           batch: int = 8, seq: int = 32) -> dict:
    """Tracing cost on the store-DP step loop — the numbers behind
    bench.py's ``trace_overhead_pct``.

    Method: the probe interleaves traced and untraced steps (drift on
    a shared host dwarfs a naive A-then-B comparison) to establish the
    per-step floor and the span rate, then costs the span machinery
    DIRECTLY — a tight loop over ``with trace.span(...)`` enabled, and
    over the bare ``trace.span`` call disabled — and scales by the
    measured spans-per-step. The direct product is the estimator
    because it is the only part a differential can't lie about: the
    span machinery (allocate span, two contextvar ops, ring append) IS
    everything tracing adds to the step loop, it measures in
    microseconds, and the step measures in tens of milliseconds — a
    wall-clock A/B on a noisy host reports scheduler jitter, not the
    0.0x% signal. The raw interleaved wall clocks ride along for
    transparency.

    - ``trace_overhead_pct``: enabled span cost × span rate / step —
      the cost of leaving tracing ON (acceptance: <5%);
    - ``trace_disabled_overhead_pct``: disabled hook cost × span rate
      / step — the compiled-out contract (acceptance: <1%).
    """
    import jax

    from ptype_tpu import trace
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.topology import DATA_AXIS
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer

    # Capture the host process's tracing state: the probe toggles
    # enable/disable around its loops and must hand back the ORIGINAL
    # recorder (ring, service name, dump config), not a fresh one.
    orig_rec, orig_dump = trace.recorder(), trace._dump_dir
    mesh = build_mesh({DATA_AXIS: jax.device_count()})
    cfg = tfm.preset(preset)
    trainer = StoreDPTrainer(cfg, TensorStore(mesh))
    stream = synthetic_batches(cfg.vocab_size, batch, seq)

    trainer.step(next(stream))  # compile
    # Span rate, from the recorder's own counter over a traced pair.
    rec = trace.enable("bench-trace-overhead")
    trainer.step(next(stream))  # warm the traced path
    before = rec.finished
    trainer.step(next(stream))
    spans_per_step = max(1.0, float(rec.finished - before))
    trace.disable()

    # Interleaved A/B: per-arm MIN step time (robust to load spikes).
    t_on: list[float] = []
    t_off: list[float] = []
    for i in range(2 * steps):
        traced = bool(i % 2)
        if traced:
            trace.enable("bench-trace-overhead")
        else:
            trace.disable()
        t0 = time.perf_counter()
        trainer.step(next(stream))
        (t_on if traced else t_off).append(time.perf_counter() - t0)
    trace.disable()

    # Enabled span machinery, costed directly.
    trace.enable("bench-trace-overhead")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("probe"):
            pass
    span_cost_s = (time.perf_counter() - t0) / n
    trace.disable()

    # The disabled hook: one global load + None check + singleton.
    t0 = time.perf_counter()
    for _ in range(n):
        trace.span("probe")
    noop_cost_s = (time.perf_counter() - t0) / n

    step_s = min(t_off)
    trace._restore(orig_rec, orig_dump)
    return {
        "untraced_step_ms": round(step_s * 1e3, 2),
        "traced_step_ms": round(min(t_on) * 1e3, 2),
        "span_cost_us": round(span_cost_s * 1e6, 2),
        "noop_cost_us": round(noop_cost_s * 1e6, 3),
        "spans_per_step": round(spans_per_step, 1),
        "trace_overhead_pct": round(
            100.0 * span_cost_s * spans_per_step / step_s, 4),
        "trace_disabled_overhead_pct": round(
            100.0 * noop_cost_s * spans_per_step / step_s, 6),
        "steps": steps,
    }
