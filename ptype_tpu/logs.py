"""Structured logging for ptype_tpu.

The reference uses zap with a global dev logger swapped in when
``Debug: true`` (cluster/cluster.go:29-35) and structured key-value fields
on every event (e.g. registry.go:77-82). We mirror that: stdlib ``logging``
with a key-value formatter, a package-root logger, and ``set_debug`` to flip
the global level the way ``zap.ReplaceGlobals`` did.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Any

from ptype_tpu import trace as trace_mod

_ROOT_NAME = "ptype_tpu"
_configured = False
_lock = threading.Lock()


class _KVFormatter(logging.Formatter):
    """``ts level logger msg k=v k=v`` — zap's dev-console shape."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        base = f"{ts}.{int(record.msecs):03d} {record.levelname:<5} {record.name} {record.getMessage()}"
        fields = getattr(record, "kv", None)
        if fields:
            kv = " ".join(f"{k}={v!r}" for k, v in fields.items())
            base = f"{base} {kv}"
        if record.exc_info:
            base = f"{base}\n{self.formatException(record.exc_info)}"
        return base


class KVLogger(logging.LoggerAdapter):
    """Logger adapter carrying structured fields via ``kv=`` kwargs.

    When the calling thread is inside an active trace span
    (:mod:`ptype_tpu.trace`), ``trace_id``/``span_id`` are attached
    automatically — logs and traces correlate with zero call-site
    changes (grep a trace_id across every process's logs, or jump from
    a log line into the stitched Perfetto view). Costs one enabled
    check per log call when tracing is off."""

    def process(self, msg, kwargs):
        kv = kwargs.pop("kv", None)
        sp = trace_mod.current()
        if sp is not None:
            kv = dict(kv) if kv else {}
            kv.setdefault("trace_id", sp.trace_id)
            kv.setdefault("span_id", sp.span_id)
        extra = kwargs.setdefault("extra", {})
        extra["kv"] = kv
        return msg, kwargs


def _configure() -> None:
    global _configured
    with _lock:
        if _configured:
            return
        root = logging.getLogger(_ROOT_NAME)
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_KVFormatter())
            root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True


def get_logger(name: str = "") -> KVLogger:
    """Return a structured logger under the ``ptype_tpu`` root."""
    _configure()
    full = f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME
    return KVLogger(logging.getLogger(full), {})


def set_debug(debug: bool) -> None:
    """Flip global verbosity (ref: cluster.go:29-35 zap.ReplaceGlobals)."""
    _configure()
    logging.getLogger(_ROOT_NAME).setLevel(
        logging.DEBUG if debug else logging.INFO
    )


def log_kv(logger: KVLogger, level: int, msg: str, **fields: Any) -> None:
    logger.log(level, msg, kv=fields)
