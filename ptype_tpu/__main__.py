"""Operator CLI: ``python -m ptype_tpu <command>``.

The reference shipped bare binaries selected by ``$CONFIG``
(server.go:22); this adds the thin launcher the framework's own
operations need. Commands:

- ``info``   — devices, mesh axes from config (if any), native wire
- ``join``   — join the cluster described by $CONFIG and idle (a seed
               or bare member; ^C to leave)
- ``serve``  — join + serve a GeneratorActor ($PRESET, default tiny)
- ``train``  — join + train ($PRESET/$STEPS/$BATCH/$SEQ/$MODE as in
               examples/optimus/trainer.py; $CKPT_DIR/$CKPT_EVERY for
               save/resume, $COMPRESS for store-mode grad wire)
- ``eval``   — held-out loss/perplexity of a checkpoint ($CKPT_DIR;
               $PRESET/$BATCH/$SEQ/$EVAL_STEPS; $CORPUS points at a raw
               token file, else a fixed synthetic stream)
- ``bench``  — the headline one-line JSON benchmark
- ``standby`` — warm-standby coordinator: probe the seed, take over on
               failure ($STANDBY_ADDR to listen on; the platform
               config supplies coordinator_address + data_dir;
               $STANDBY_REPLICATE=1 streams the WAL cross-host
               instead of assuming a shared data_dir).
               ``kill -USR1`` for operator switchover; ^C exits.
- ``witness`` — quorum witness (platform ``witness_address`` /
               ``witness_ttl``): the third vote that lets a
               partitioned-minority primary self-fence and gates
               standby promotion on a real majority.
- ``obs``    — fleet-wide observability snapshot: walk the registry
               of the cluster described by $CONFIG, pull every node's
               telemetry (metrics + flight-recorder spans) over its
               actor RPC surface, write a stitched Chrome trace
               ($OBS_DIR/trace.json — load in Perfetto) + spans JSONL,
               and print the summary (docs/OBSERVABILITY.md).
- ``obs top`` — LIVE cluster health view: re-pull the cluster
               telemetry every $TOP_INTERVAL (default 2 s), run the
               health alert rules over the per-node series, and
               repaint per-node goodput / step breakdown / memory +
               recent alerts ($TOP_ITERS bounds the refreshes for
               scripted runs; ^C exits). docs/OPERATIONS.md has the
               per-alert runbook.
- ``obs serve`` — LIVE serving-plane view (ISSUE 10): re-pull the
               cluster telemetry every $TOP_INTERVAL, run the alert
               rules (incl. kv-pressure / prefix-hit-collapse /
               serve-stall; ttft-p99 when an SLO is set), and repaint
               per-replica TTFT/TPOT/e2e tails, queue + batch
               occupancy, and KV-pool pressure from the serving
               ledger ($TOP_ITERS bounds refreshes; ^C exits).
- ``obs scale`` — LIVE elastic-fleet view (ISSUE 13): re-pull the
               cluster telemetry every $TOP_INTERVAL and repaint
               every reconciler's desired-vs-actual fleet size, warm/
               draining/pending counts, and decision/spawn/drain/
               escalation counters, plus every serving replica's
               lifecycle state (spawning/warm/active/draining) —
               the autoscaling loop and its effect in one screen
               ($TOP_ITERS bounds refreshes; ^C exits).
               docs/OPERATIONS.md "Elastic serving" has the runbook.
- ``obs topo`` — LIVE topology view (ISSUE 18): re-pull the cluster
               telemetry every $TOP_INTERVAL and repaint per-domain
               replica counts (the ``serve.domain`` gauge), per-leg
               collective wire bytes (inner vs the slow outer leg vs
               the flat baseline), and the KV-migration locality
               split (local-domain vs cross-domain) — the
               cross-domain-pressure runbook row reads this after
               ``obs serve`` ($TOP_ITERS bounds refreshes; ^C exits).
- ``obs traffic`` — LIVE traffic-plane view (ISSUE 19): re-pull the
               cluster telemetry every $TOP_INTERVAL and repaint each
               open-loop load driver's offered/achieved rates,
               SLO-attributed goodput, shed/overrun/chaos-drop split,
               open-loop TTFT p99, and the last measured capacity
               knee with live headroom against it ($TOP_ITERS bounds
               refreshes; ^C exits). docs/OPERATIONS.md "Capacity
               planning" has the runbook.
- ``obs profile`` — cluster-wide device profiling: simultaneous
               jax.profiler XPlane capture on every registered node
               via the built-in ptype.Profile endpoint
               ($PROFILE_DURATION seconds, default 1), artifacts
               shipped back under $OBS_DIR/profile/<node>/, then a
               host-side top-ops table + per-node HBM table (no
               TensorBoard needed; load the .xplane.pb there for the
               full device timeline). ``obs profile summarize``
               re-parses an existing artifact tree ($PROFILE_DIR or
               $OBS_DIR/profile) without touching the cluster.
- ``obs request <trace_id>`` — tail forensics (ISSUE 20): render one
               request's stage-attributed waterfall (queue-wait /
               route / prefill / migrate / decode-queue / decode …)
               from its stitched cross-process spans. Post-mortem
               first: reads $TRACE_FILE, else $OBS_DIR/spans.jsonl,
               else the newest $PTYPE_TRACE_DUMP_DIR flight dump,
               and only dials the cluster when no file exists.
               Trace-id prefixes match (paste the short id from
               ``obs tail``).
- ``obs tail`` — the fleet's worst tail: per-histogram worst
               exemplars (value + trace id, the input to ``obs
               request``) and the gateway stage-time p99 breakdown
               ($TAIL_LIMIT bounds rows, default 8).
               docs/OBSERVABILITY.md "Tail forensics".
- ``obs export`` — OpenMetrics text dump of every node's metric
               families (counters/gauges/timings/histograms, p99
               exemplars inline) for standard scrape tooling.
"""

from __future__ import annotations

import json
import sys
import threading


def _info() -> None:
    import jax

    from ptype_tpu import native

    devices = jax.devices()
    out = {
        "version": __import__("ptype_tpu").__version__,
        "platform": devices[0].platform,
        "devices": len(devices),
        "device_kind": getattr(devices[0], "device_kind", ""),
        "native_wire": native.available(),
    }
    import os

    if os.environ.get("CONFIG"):
        from ptype_tpu import config_from_env

        cfg = config_from_env()
        out["service"] = cfg.service_name
        out["mesh_axes"] = cfg.platform.mesh_axes
    print(json.dumps(out, indent=2))


def _join() -> None:
    from ptype_tpu import config_from_env, join

    cluster = join(config_from_env())
    print(f"joined as {cluster.cfg.node_name} "
          f"(member {cluster.member.id}); ^C to leave", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.close()


def _serve() -> None:
    import os

    from ptype_tpu import config_from_env, join
    from ptype_tpu.models import transformer as tfm
    # Replica lifecycle has ONE home (lint PT012): the server that
    # fronts a serving replica is constructed by reconciler/replica.py
    # — the same code path the elastic reconciler's spawned workers
    # use, so an operator-launched replica and an autoscaled one are
    # the same thing.
    from ptype_tpu.reconciler.replica import serve_actor
    from ptype_tpu.serve import BatchingGeneratorActor

    cfg = config_from_env()
    model_cfg = tfm.preset(os.environ.get("PRESET", "tiny"))
    # $SERVE_MODE=continuous: slot-based continuous batching (requests
    # join/leave the one running decode loop at step boundaries;
    # $SERVE_SLOTS caches). Default: dynamic batching — concurrent
    # greedy requests coalesce into one decode round
    # ($SERVE_WINDOW_MS/$SERVE_MAX_BATCH to tune). Sampled requests
    # run solo in both modes.
    if os.environ.get("SERVE_MODE") == "continuous":
        from ptype_tpu.serve import ContinuousGeneratorActor

        actor = ContinuousGeneratorActor(
            model_cfg,
            n_slots=int(os.environ.get("SERVE_SLOTS", "8")))
    else:
        actor = BatchingGeneratorActor(
            model_cfg,
            window_ms=float(os.environ.get("SERVE_WINDOW_MS", "5")),
            max_batch=int(os.environ.get("SERVE_MAX_BATCH", "32")))
    server = serve_actor(actor, "Generator", port=cfg.port)
    cfg.port = server.port
    cluster = join(cfg)
    print(f"serving Generator.{{Generate,Logits,Info}} on :{server.port}",
          flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.close()
        server.close()


def _train() -> None:
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "optimus_trainer",
        os.path.join(os.path.dirname(__file__), "..", "examples",
                     "optimus", "trainer.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


def _eval() -> None:
    import json as _json
    import os

    import jax

    from ptype_tpu.checkpoint import Checkpointer
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.topology import DATA_AXIS
    from ptype_tpu.train.data import TokenFileDataset, synthetic_batches
    from ptype_tpu.train.trainer import Trainer, default_optimizer

    ckpt_dir = os.environ.get("CKPT_DIR")
    if not ckpt_dir:
        print("eval: set CKPT_DIR to the checkpoint directory",
              file=sys.stderr)
        raise SystemExit(2)
    ck = Checkpointer(ckpt_dir)
    step = ck.latest_step()
    if step is None:
        print(f"eval: no complete checkpoint under {ckpt_dir}",
              file=sys.stderr)
        raise SystemExit(2)

    cfg = tfm.preset(os.environ.get("PRESET", "tiny"))
    mesh = build_mesh({DATA_AXIS: jax.device_count()})
    steps = int(os.environ.get("EVAL_STEPS", "10"))
    batch = int(os.environ.get("BATCH", str(8 * mesh.devices.size)))
    seq = int(os.environ.get("SEQ", "1024"))

    # The TrainState skeleton + shardings come from a Trainer; restore
    # replaces its fresh params with the checkpoint's, and
    # Trainer.evaluate threads the attention lowering AND its matching
    # seq-axis sharding (ring/ulysses presets shard batches over "seq").
    tr = Trainer(cfg, mesh, optimizer=default_optimizer())
    tr.state = ck.restore(tr.state, step=step,
                          shardings=tr.state_shardings)

    corpus = os.environ.get("CORPUS")
    if corpus:
        stream = TokenFileDataset(corpus).batches(batch, seq, seed=1234)
    else:
        stream = synthetic_batches(cfg.vocab_size, batch, seq, seed=1234)
    out = tr.evaluate(stream, steps)
    print(_json.dumps({"checkpoint_step": step, "eval_steps": steps,
                       "batch": batch, "seq": seq, **out}))


def _bench() -> None:
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


def _standby() -> None:
    import os
    import signal

    from ptype_tpu import config_from_env
    from ptype_tpu.coord.standby import Standby

    cfg = config_from_env()
    listen = os.environ.get("STANDBY_ADDR")
    if not listen:
        print("standby: set STANDBY_ADDR=host:port (the address this "
              "standby serves on after takeover)", file=sys.stderr)
        raise SystemExit(2)
    data_dir = os.path.join(cfg.platform.data_dir, "coord")
    if not cfg.platform.data_dir:
        print("standby: platform config needs data_dir (the seed's WAL "
              "directory, shared)", file=sys.stderr)
        raise SystemExit(2)
    # STANDBY_REPLICATE=1: cross-host mode — data_dir is local and a
    # WalFollower streams the primary's WAL into it (no shared fs).
    sb = Standby(cfg.platform.coordinator_address, listen, data_dir,
                 replicate=os.environ.get("STANDBY_REPLICATE") == "1",
                 fsync=cfg.platform.wal_fsync,
                 witness_addr=cfg.platform.witness_address or None,
                 witness_ttl=cfg.platform.witness_ttl)

    def _switchover(*_):
        # promote() raises if the primary still holds the WAL fence
        # (and re-arms monitoring); a raise out of a signal handler
        # would tear down the whole standby process.
        try:
            sb.promote()
        except RuntimeError as e:
            print(f"standby: switchover refused: {e}", file=sys.stderr,
                  flush=True)

    signal.signal(signal.SIGUSR1, _switchover)
    print(f"standby for {cfg.platform.coordinator_address}; will serve "
          f"on {listen} (SIGUSR1 = switchover)", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        sb.close()


def _witness() -> None:
    import os

    from ptype_tpu import config_from_env
    from ptype_tpu.coord.witness import WitnessServer

    cfg = config_from_env()
    addr = cfg.platform.witness_address
    if not addr:
        print("witness: platform config needs witness_address "
              "(host:port this witness listens on)", file=sys.stderr)
        raise SystemExit(2)
    data_dir = (os.path.join(cfg.platform.data_dir, "witness")
                if cfg.platform.data_dir else None)
    srv = WitnessServer(addr, ttl=cfg.platform.witness_ttl,
                        data_dir=data_dir)
    print(f"witness on {srv.address} (ttl {srv.ttl}s)", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()


def _obs_profile_summarize(root: str) -> None:
    """Host-side re-parse of an artifact tree — one top-ops table per
    node directory (or the root itself when it holds a capture)."""
    import os

    from ptype_tpu.health import profiling

    if not os.path.isdir(root):
        print(f"no artifacts under {root} (set $PROFILE_DIR or "
              f"$OBS_DIR, or run `obs profile` first)")
        return
    dirs = [os.path.join(root, d) for d in sorted(os.listdir(root))
            if os.path.isdir(os.path.join(root, d))] or [root]
    for d in dirs:
        s = profiling.summarize(d)
        if not s["files"]:
            continue
        print(f"{d}: {len(s['files'])} files, {s['events']} events")
        for op in s["top_ops"]:
            print(f"  {op['total_us']:>12.1f} us  x{op['count']:<6} "
                  f"{op['name'][:80]}")


def _obs_profile(registry) -> None:
    import os

    from ptype_tpu import telemetry as tel
    from ptype_tpu.health import profiling

    out_dir = os.path.join(os.environ.get("OBS_DIR", "."), "profile")
    dur = float(os.environ.get("PROFILE_DURATION", "1"))
    res = tel.cluster_profile(registry, duration_s=dur,
                              out_dir=out_dir)
    print(f"cluster profile @ {res['ts']} ({dur}s capture)")
    for key in sorted(res["nodes"]):
        n = res["nodes"][key]
        print(f"{key}: {len(n['files'])} artifacts -> {n['dir']}")
        s = profiling.summarize(n["dir"], top=8)
        for op in s["top_ops"]:
            print(f"  {op['total_us']:>12.1f} us  x{op['count']:<6} "
                  f"{op['name'][:80]}")
        if n.get("memory"):
            print(profiling.render_hbm_table(n["memory"]))
    for key in sorted(res["errors"]):
        print(f"{key}: FAILED ({res['errors'][key]})")
    print(f"artifacts under {out_dir} (xplane.pb loads in "
          f"TensorBoard's profile plugin / xprof)")


def _obs_request_offline(trace_id: str) -> bool:
    """Render a request waterfall from span files on disk — returns
    False when no file source exists (caller falls through to the
    live cluster pull). Sources, in order: $TRACE_FILE (a spans.jsonl
    or flight-recorder dump), $OBS_DIR/spans.jsonl (what a plain
    ``obs`` run writes), the newest flight dump under
    $PTYPE_TRACE_DUMP_DIR (what an SLO violation wrote)."""
    import os

    from ptype_tpu.health import forensics

    path = os.environ.get("TRACE_FILE")
    if not path:
        cand = os.path.join(os.environ.get("OBS_DIR", "."),
                            "spans.jsonl")
        if os.path.isfile(cand):
            path = cand
    if not path:
        dump_dir = os.environ.get("PTYPE_TRACE_DUMP_DIR")
        if dump_dir:
            path = forensics.latest_dump(dump_dir)
    if not path or not os.path.isfile(path):
        return False
    traces = forensics.load_dump_traces(path)
    try:
        wf = forensics.waterfall_from_snapshot({"traces": traces},
                                               trace_id)
    except KeyError:
        # The dump predates (or never saw) this trace — fall through
        # to the live cluster pull rather than dead-ending offline.
        print(f"(trace {trace_id!r} not in {path}; "
              f"{len(traces)} traces there — trying the cluster)",
              file=sys.stderr)
        return False
    print(forensics.render_waterfall(wf))
    print(f"(source: {path})")
    return True


def _obs() -> None:
    import os

    from ptype_tpu import config_from_env
    from ptype_tpu import telemetry as tel
    from ptype_tpu.coord.remote import RemoteCoord
    from ptype_tpu.registry import CoordRegistry

    if (len(sys.argv) > 3 and sys.argv[2] == "profile"
            and sys.argv[3] == "summarize"):
        # Offline re-parse of an existing artifact tree — the
        # post-mortem path must work with the cluster (and its
        # coordinator) down, so dispatch before dialing anything.
        _obs_profile_summarize(os.environ.get(
            "PROFILE_DIR",
            os.path.join(os.environ.get("OBS_DIR", "."), "profile")))
        return
    if len(sys.argv) > 3 and sys.argv[2] == "request":
        # Waterfall forensics. Same post-mortem rule as profile
        # summarize: when a span file exists ($TRACE_FILE, or the
        # spans.jsonl / flight dump a previous obs run or SLO
        # violation wrote), render from it without dialing — the tail
        # request's trace must be readable after the cluster is gone.
        if _obs_request_offline(sys.argv[3]):
            return
    cfg = config_from_env()
    coord = RemoteCoord([cfg.platform.coordinator_address])
    try:
        if len(sys.argv) > 2 and sys.argv[2] == "profile":
            _obs_profile(CoordRegistry(coord))
            return
        if len(sys.argv) > 2 and sys.argv[2] == "top":
            from ptype_tpu.health import run_top

            try:
                run_top(CoordRegistry(coord),
                        iters=int(os.environ.get("TOP_ITERS", "0")),
                        interval_s=float(
                            os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 2 and sys.argv[2] == "serve":
            from ptype_tpu.health import run_serve

            try:
                run_serve(CoordRegistry(coord),
                          iters=int(os.environ.get("TOP_ITERS", "0")),
                          interval_s=float(
                              os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 2 and sys.argv[2] == "scale":
            from ptype_tpu.health import run_scale

            try:
                run_scale(CoordRegistry(coord),
                          iters=int(os.environ.get("TOP_ITERS", "0")),
                          interval_s=float(
                              os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 2 and sys.argv[2] == "traffic":
            from ptype_tpu.health import run_traffic

            try:
                run_traffic(CoordRegistry(coord),
                            iters=int(os.environ.get(
                                "TOP_ITERS", "0")),
                            interval_s=float(
                                os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 2 and sys.argv[2] == "topo":
            from ptype_tpu.health import run_topo

            try:
                run_topo(CoordRegistry(coord),
                         iters=int(os.environ.get("TOP_ITERS", "0")),
                         interval_s=float(
                             os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 2 and sys.argv[2] == "jit":
            from ptype_tpu.health import run_jit

            try:
                run_jit(CoordRegistry(coord),
                        iters=int(os.environ.get("TOP_ITERS", "0")),
                        interval_s=float(
                            os.environ.get("TOP_INTERVAL", "2")))
            except KeyboardInterrupt:
                pass
            return
        if len(sys.argv) > 3 and sys.argv[2] == "request":
            from ptype_tpu.health import forensics

            snap = tel.cluster_snapshot(CoordRegistry(coord),
                                        include_local=False)
            try:
                wf = forensics.waterfall_from_snapshot(snap, sys.argv[3])
            except KeyError as e:
                # The flight ring is bounded; old request traces get
                # evicted by probe churn. Point the operator at dumps.
                print(f"obs request: {e.args[0]}", file=sys.stderr)
                print("  (flight rings are bounded; an evicted trace "
                      "may survive in $PTYPE_TRACE_DUMP_DIR flight "
                      "dumps or $OBS_DIR/spans.jsonl)", file=sys.stderr)
                sys.exit(1)
            print(forensics.render_waterfall(wf))
            return
        if len(sys.argv) > 2 and sys.argv[2] == "tail":
            from ptype_tpu.health import forensics

            snap = tel.cluster_snapshot(CoordRegistry(coord),
                                        include_local=False)
            print(forensics.render_tail(
                snap, limit=int(os.environ.get("TAIL_LIMIT", "8"))))
            return
        if len(sys.argv) > 2 and sys.argv[2] == "export":
            snap = tel.cluster_snapshot(CoordRegistry(coord),
                                        include_local=False)
            sys.stdout.write(tel.openmetrics(snap))
            return
        snap = tel.cluster_snapshot(CoordRegistry(coord),
                                    include_local=False)
        out_dir = os.environ.get("OBS_DIR", ".")
        chrome = tel.write_chrome_trace(
            os.path.join(out_dir, "trace.json"), snap)
        jsonl = tel.write_spans_jsonl(
            os.path.join(out_dir, "spans.jsonl"), snap)
        print(tel.render_summary(snap))
        print(f"chrome trace: {chrome} (load in ui.perfetto.dev or "
              f"chrome://tracing)")
        print(f"spans jsonl:  {jsonl}")
    finally:
        coord.close()


COMMANDS = {
    "info": _info,
    "join": _join,
    "serve": _serve,
    "train": _train,
    "eval": _eval,
    "bench": _bench,
    "standby": _standby,
    "witness": _witness,
    "obs": _obs,
}


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in COMMANDS:
        print(f"usage: python -m ptype_tpu {{{'|'.join(COMMANDS)}}}",
              file=sys.stderr)
        raise SystemExit(2)
    COMMANDS[sys.argv[1]]()


if __name__ == "__main__":
    main()
