"""Operator CLI: ``python -m ptype_tpu <command>``.

The reference shipped bare binaries selected by ``$CONFIG``
(server.go:22); this adds the thin launcher the framework's own
operations need. Commands:

- ``info``   — devices, mesh axes from config (if any), native wire
- ``join``   — join the cluster described by $CONFIG and idle (a seed
               or bare member; ^C to leave)
- ``serve``  — join + serve a GeneratorActor ($PRESET, default tiny)
- ``train``  — join + train ($PRESET/$STEPS/$BATCH/$SEQ/$MODE as in
               examples/optimus/trainer.py; $CKPT_DIR/$CKPT_EVERY for
               save/resume, $COMPRESS for store-mode grad wire)
- ``bench``  — the headline one-line JSON benchmark
- ``standby`` — warm-standby coordinator: probe the seed, take over on
               failure ($STANDBY_ADDR to listen on; the platform
               config supplies coordinator_address + data_dir;
               $STANDBY_REPLICATE=1 streams the WAL cross-host
               instead of assuming a shared data_dir).
               ``kill -USR1`` for operator switchover; ^C exits.
"""

from __future__ import annotations

import json
import sys
import threading


def _info() -> None:
    import jax

    from ptype_tpu import native

    devices = jax.devices()
    out = {
        "version": __import__("ptype_tpu").__version__,
        "platform": devices[0].platform,
        "devices": len(devices),
        "device_kind": getattr(devices[0], "device_kind", ""),
        "native_wire": native.available(),
    }
    import os

    if os.environ.get("CONFIG"):
        from ptype_tpu import config_from_env

        cfg = config_from_env()
        out["service"] = cfg.service_name
        out["mesh_axes"] = cfg.platform.mesh_axes
    print(json.dumps(out, indent=2))


def _join() -> None:
    from ptype_tpu import config_from_env, join

    cluster = join(config_from_env())
    print(f"joined as {cluster.cfg.node_name} "
          f"(member {cluster.member.id}); ^C to leave", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.close()


def _serve() -> None:
    import os

    from ptype_tpu import ActorServer, config_from_env, join
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.serve import BatchingGeneratorActor

    cfg = config_from_env()
    model_cfg = tfm.preset(os.environ.get("PRESET", "tiny"))
    server = ActorServer(port=cfg.port)
    # Dynamic batching: concurrent greedy requests coalesce into one
    # decode round ($SERVE_WINDOW_MS to tune; sampled requests run solo).
    server.register(
        BatchingGeneratorActor(
            model_cfg,
            window_ms=float(os.environ.get("SERVE_WINDOW_MS", "5"))),
        "Generator")
    server.serve()
    cfg.port = server.port
    cluster = join(cfg)
    print(f"serving Generator.{{Generate,Logits,Info}} on :{server.port}",
          flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.close()
        server.close()


def _train() -> None:
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "optimus_trainer",
        os.path.join(os.path.dirname(__file__), "..", "examples",
                     "optimus", "trainer.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


def _bench() -> None:
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


def _standby() -> None:
    import os
    import signal

    from ptype_tpu import config_from_env
    from ptype_tpu.coord.standby import Standby

    cfg = config_from_env()
    listen = os.environ.get("STANDBY_ADDR")
    if not listen:
        print("standby: set STANDBY_ADDR=host:port (the address this "
              "standby serves on after takeover)", file=sys.stderr)
        raise SystemExit(2)
    data_dir = os.path.join(cfg.platform.data_dir, "coord")
    if not cfg.platform.data_dir:
        print("standby: platform config needs data_dir (the seed's WAL "
              "directory, shared)", file=sys.stderr)
        raise SystemExit(2)
    # STANDBY_REPLICATE=1: cross-host mode — data_dir is local and a
    # WalFollower streams the primary's WAL into it (no shared fs).
    sb = Standby(cfg.platform.coordinator_address, listen, data_dir,
                 replicate=os.environ.get("STANDBY_REPLICATE") == "1")

    def _switchover(*_):
        # promote() raises if the primary still holds the WAL fence
        # (and re-arms monitoring); a raise out of a signal handler
        # would tear down the whole standby process.
        try:
            sb.promote()
        except RuntimeError as e:
            print(f"standby: switchover refused: {e}", file=sys.stderr,
                  flush=True)

    signal.signal(signal.SIGUSR1, _switchover)
    print(f"standby for {cfg.platform.coordinator_address}; will serve "
          f"on {listen} (SIGUSR1 = switchover)", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        sb.close()


COMMANDS = {
    "info": _info,
    "join": _join,
    "serve": _serve,
    "train": _train,
    "bench": _bench,
    "standby": _standby,
}


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in COMMANDS:
        print(f"usage: python -m ptype_tpu {{{'|'.join(COMMANDS)}}}",
              file=sys.stderr)
        raise SystemExit(2)
    COMMANDS[sys.argv[1]]()


if __name__ == "__main__":
    main()
