"""Actor-per-layer pipeline — registry PID→stage (the north-star config
"ResNet-50 actor-per-layer pipeline (registry PID→stage)", BASELINE.json).

Unlike parallel/pipeline.py (one compiled SPMD program over the ``stage``
mesh axis — the throughput path), this is the reference-shaped topology:
each stage is an ACTOR owning its layer chunk, discovered through the
registry, called over the balanced RPC client. Activations flow
stage→stage as tensor-codec payloads (device buffers, zero-copy when
co-located). It trades ICI-speed pipelining for elasticity: stages can
live in different processes/hosts, die, and be re-registered — the
scatter-gather failure model of the reference's optimus
(coordinator.go:67-99), applied layer-wise.

Training semantics (GPipe-equivalent): within one ``train_step`` sweep
the stage parameters are FROZEN. ``Forward(mb, x)`` stashes the stage
input per microbatch id; ``Backward(mb, g)`` replays the stage under
``jax.vjp`` against the frozen params and ACCUMULATES the parameter
gradient; ``Apply()`` — called once per sweep after every microbatch's
backward — applies the stage-local optimizer to the summed grads. Each
stage owns its optimizer state (per-stage Adam, no global state).
Microbatches traverse the stages concurrently (one in-flight chain per
microbatch), so stage i works on microbatch m while stage i+1 works on
m-1 — the pipeline overlap, bounded by RPC latency rather than ICI.

Service naming: ``<pipeline>-stage<i>`` (see :func:`stage_service`) —
the registry's service map IS the pipeline topology; the client requires
the discovered indices to be contiguous from 0 and refuses to run a
pipeline with a hole where a stage should be.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

import jax
import optax

from ptype_tpu import logs
from ptype_tpu.errors import ClusterError

log = logs.get_logger("actor_pipeline")

SERVICE_SEP = "-stage"


def stage_service(pipeline: str, idx: int) -> str:
    return f"{pipeline}{SERVICE_SEP}{idx}"


def discover_stages(registry, pipeline: str) -> list[str]:
    """Stage service names of a pipeline, in stage order, from the live
    registry (the PID→stage map). Raises if the indices are not
    contiguous from 0 — a hole means a dead/unregistered stage, and
    piping around it would silently compute garbage."""
    prefix = pipeline + SERVICE_SEP
    found = []
    for svc in registry.services():
        if svc.startswith(prefix):
            try:
                found.append((int(svc[len(prefix):]), svc))
            except ValueError:
                continue
    found.sort()
    indices = [i for i, _ in found]
    if indices and indices != list(range(len(indices))):
        raise ClusterError(
            f"pipeline {pipeline!r} has non-contiguous stages {indices} "
            "— a stage is missing/unregistered"
        )
    return [svc for _, svc in found]


class StageActor:
    """One pipeline stage: params + a pure ``fn(params, x) -> y``.

    Drops into an ActorServer (``server.register(stage, "Stage")``).
    Thread-safe; per-microbatch stashes allow several microbatches in
    flight. Params are frozen between ``Apply`` calls, so concurrent
    Forward/Backward of different microbatches all see one version.
    """

    def __init__(self, fn: Callable, params, optimizer=None):
        from ptype_tpu.train.trainer import make_apply_fn

        self.fn = fn
        self.params = params
        self.optimizer = optimizer or optax.adam(1e-3)
        self.opt_state = self.optimizer.init(params)
        self._stash: dict[int, object] = {}
        self._accum = None
        self._accum_count = 0
        self._lock = threading.Lock()

        self._jit_fwd = jax.jit(lambda params, x: self.fn(params, x))

        def bwd(params, x, g):
            _, vjp = jax.vjp(self.fn, params, x)
            return vjp(g)

        self._jit_bwd = jax.jit(bwd)
        self._jit_add = jax.jit(
            lambda a, b: jax.tree.map(jax.numpy.add, a, b))
        self._jit_scale = jax.jit(
            lambda t, s: jax.tree.map(lambda l: l * s, t))
        self._jit_apply = make_apply_fn(self.optimizer)

    def Forward(self, mb: int, x):
        """Run the stage on microbatch ``mb``, stashing x for backward."""
        with self._lock:
            self._stash[mb] = x
            params = self.params
        return self._jit_fwd(params, x)

    def Backward(self, mb: int, g):
        """VJP for microbatch ``mb`` against the frozen params;
        accumulates the param grad, returns the upstream gradient."""
        with self._lock:
            x = self._stash.pop(mb)
            params = self.params
        dparams, dx = self._jit_bwd(params, x, g)
        with self._lock:
            if self._accum is None:
                self._accum = dparams
            else:
                self._accum = self._jit_add(self._accum, dparams)
            self._accum_count += 1
        return dx

    def Apply(self, mean: bool = True):
        """One optimizer step on the grads accumulated this sweep
        (mean over microbatches by default — matches the dense loss's
        mean reduction). Returns the number of microbatches folded in."""
        with self._lock:
            grads, n = self._accum, self._accum_count
            self._accum, self._accum_count = None, 0
            if grads is None:
                return 0
            if mean and n > 1:
                grads = self._jit_scale(grads, 1.0 / n)
            self.params, self.opt_state = self._jit_apply(
                self.params, grads, self.opt_state)
            return n

    def Infer(self, x):
        """Stateless forward (no stash) — the inference path."""
        with self._lock:
            params = self.params
        return self._jit_fwd(params, x)


class PipelineClient:
    """Drives microbatches through registry-discovered stage actors."""

    def __init__(self, cluster, pipeline: str,
                 stages: Sequence[str] | None = None, conn_cfg=None):
        names = list(stages) if stages is not None else discover_stages(
            cluster.registry, pipeline)
        if not names:
            raise ClusterError(
                f"no stages registered for pipeline {pipeline!r}")
        self.stage_names = names
        self._clients = [cluster.new_client(n, conn_cfg) for n in names]

    @property
    def n_stages(self) -> int:
        return len(self._clients)

    def infer(self, x):
        for c in self._clients:
            x = c.call("Stage.Infer", x)
        return x

    def train_step(self, x, loss_grad_fn, n_microbatches: int = 1):
        """One pipelined fwd+bwd sweep + per-stage Apply.

        ``loss_grad_fn(y) -> (loss, dy)`` computes the loss and its
        gradient at the pipeline output (the driver owns the loss, the
        stages own the layers). One concurrent chain per microbatch:
        each walks forward through the stages, through the loss, then
        backward — so stage i processes microbatch m while stage i+1
        processes m-1 (wall-clock ≈ (S+M-1)·t, not S·M·t). Grads
        accumulate server-side; Apply once per sweep keeps params frozen
        during the sweep (GPipe semantics, reproducible)."""
        B = x.shape[0]
        if B % n_microbatches:
            raise ValueError(
                f"batch {B} not divisible by {n_microbatches}")
        mbs = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])

        def chain(m):
            a = mbs[m]
            for c in self._clients:
                a = c.call("Stage.Forward", m, a)
            loss, g = loss_grad_fn(a)
            for c in reversed(self._clients):
                g = c.call("Stage.Backward", m, g)
            return float(loss)

        with ThreadPoolExecutor(max_workers=n_microbatches) as pool:
            losses = list(pool.map(chain, range(n_microbatches)))

        applied = [c.call("Stage.Apply") for c in self._clients]
        if any(n != n_microbatches for n in applied):
            raise ClusterError(
                f"pipeline sweep incomplete: stages applied {applied} "
                f"microbatch grads, expected {n_microbatches}"
            )
        return sum(losses) / len(losses)
