"""Training layer: jit train steps over meshes + Store-backed modes.

The reference's "training loop" shape is the optimus scatter-gather
(SURVEY.md §3.3): coordinator fans work out, gathers replies. Here the
fan-out is the mesh's data axes and the gather is a compiled ICI
collective — either implicit (GSPMD inserts it from sharding annotations,
the fast path) or explicit through the TensorStore (the Store push/pull
lowering, BASELINE.json north star).
"""

from ptype_tpu.train.trainer import (
    Trainer,
    TrainState,
    make_train_step,
    make_eval_step,
    evaluate,
    init_state,
    default_optimizer,
)
from ptype_tpu.train.store_dp import StoreDPTrainer
from ptype_tpu.train.data import synthetic_batches

__all__ = [
    "Trainer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "evaluate",
    "init_state",
    "default_optimizer",
    "StoreDPTrainer",
    "synthetic_batches",
]
