"""The jit'd train step — GSPMD fast path.

One compiled program per (config, mesh): loss → grads → optax update,
jit'd with NamedSharding on every input/output and donated state buffers.
XLA inserts the collectives the shardings imply (grad allreduce over
data axes, per-layer allgathers for fsdp, psums for model/TP) and
overlaps them with compute — the compiler-scheduled equivalent of the
reference's hand-rolled scatter-gather (coordinator.go:67-99).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu.models import transformer as tfm


@dataclass
class TrainState:
    """Minimal train state pytree (params + optax state + step)."""

    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def _decay_mask(params) -> Any:
    """True for leaves that should receive weight decay: matmul weights
    only — norm scales and biases (ndim ≤ 1) are exempt, the standard
    AdamW recipe. Note block leaves carry a leading layer dim, so norm
    scales there are ndim == 2; they are exempted by name."""

    def mask_leaf(path, leaf):
        name = ""
        for p in path:
            if hasattr(p, "key"):
                name = str(p.key)
        if "norm" in name:
            return False
        return jnp.ndim(leaf) > 1

    return jax.tree_util.tree_map_with_path(mask_leaf, params)


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, decay_steps: int = 100_000,
                      clip: float = 1.0):
    """AdamW + cosine schedule + global-norm clip — the standard recipe.
    Weight decay applies to matmul weights only (mask exempts norm
    scales), matching common practice."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, decay_steps=decay_steps, end_value=lr * 0.1
    )
    return optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mask=_decay_mask),
    )


def make_apply_fn(optimizer):
    """Jitted ``(params, grads, opt_state) -> (params, opt_state)`` —
    the one optimizer-step helper every eager trainer shares (store_dp,
    param_server, actor_pipeline)."""

    def apply(params, grads, opt_state):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    return jax.jit(apply)


def _state_shardings(mesh: Mesh, cfg: tfm.TransformerConfig,
                     optimizer) -> TrainState:
    """Sharding pytree for TrainState: optax mirrors param specs."""
    axis_sizes = {n: int(mesh.shape[n]) for n in mesh.axis_names}
    pspecs = tfm.param_specs(cfg, axis_sizes)
    to_ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    param_sh = jax.tree.map(to_ns, pspecs,
                            is_leaf=lambda x: isinstance(x, P))

    # Derive the opt-state sharding by eval_shape: any leaf whose shape
    # matches a param leaf inherits that param's sharding (adam moments);
    # everything else (counts, scalars) is replicated.
    params_shape = jax.eval_shape(lambda: tfm.init_params(
        jax.random.PRNGKey(0), cfg))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)

    flat_params, ptree = jax.tree_util.tree_flatten(params_shape)
    flat_specs = jax.tree_util.tree_flatten(
        pspecs, is_leaf=lambda x: isinstance(x, P))[0]
    by_shape: dict[tuple, P] = {}
    for leaf, spec in zip(flat_params, flat_specs):
        by_shape.setdefault(tuple(leaf.shape), spec)

    def opt_leaf(leaf):
        return to_ns(by_shape.get(tuple(leaf.shape), P()))

    opt_sh = jax.tree.map(opt_leaf, opt_shape)
    return TrainState(param_sh, opt_sh, to_ns(P()))


def init_state(rng: jax.Array, cfg: tfm.TransformerConfig, mesh: Mesh,
               optimizer=None) -> tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState ON DEVICE: init is jit'd with
    out_shardings so even 8B params never materialize unsharded.
    Returns (state, state_shardings)."""
    optimizer = optimizer or default_optimizer()
    shardings = _state_shardings(mesh, cfg, optimizer)
    state = jax.jit(
        lambda r: _init_impl(r, cfg, optimizer),
        out_shardings=shardings,
    )(rng)
    return state, shardings


def _init_impl(rng, cfg, optimizer):
    params = tfm.init_params(rng, cfg)
    return TrainState(params, optimizer.init(params),
                      jnp.zeros((), jnp.int32))


def make_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                    optimizer=None, attn_fn: Callable | None = None,
                    seq_axis: bool = False,
                    batch_keys: tuple[str, ...] = ("tokens", "targets"),
                    grad_accum: int = 1):
    """Compile the train step: (state, batch) → (state, metrics).

    State buffers are donated (in-place update, no HBM copy). Batch comes
    in sharded over the data-like axes; grads reduce over them via the
    sharding-implied allreduce. ``batch_keys`` fixes the batch signature
    (add "loss_mask" for masked training — every key shards the same way).
    ``grad_accum > 1`` splits the batch into that many microbatches and
    averages their grads in a ``lax.scan`` before one optimizer step —
    big effective batches on bounded activation memory.
    """
    optimizer = optimizer or default_optimizer()
    axis_sizes = {n: int(mesh.shape[n]) for n in mesh.axis_names}
    state_sh = _state_shardings(mesh, cfg, optimizer)
    batch_sh = NamedSharding(mesh, tfm.batch_spec(axis_sizes, seq_axis))
    batch_shardings = {k: batch_sh for k in batch_keys}
    repl = NamedSharding(mesh, P())

    def grads_of(params, batch):
        if grad_accum == 1:
            return jax.value_and_grad(tfm.loss_fn)(
                params, batch, cfg, attn_fn)
        split = jax.tree.map(
            lambda x: x.reshape(grad_accum, x.shape[0] // grad_accum,
                                *x.shape[1:]),
            batch,
        )

        def micro(carry, mb):
            loss_sum, grads_sum = carry
            loss, grads = jax.value_and_grad(tfm.loss_fn)(
                params, mb, cfg, attn_fn)
            return (loss_sum + loss,
                    jax.tree.map(jnp.add, grads_sum, grads)), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, grads_sum), _ = jax.lax.scan(
            micro, (jnp.float32(0.0), zeros), split)
        inv = 1.0 / grad_accum
        return loss_sum * inv, jax.tree.map(
            lambda g: g * inv, grads_sum)

    def step(state: TrainState, batch: dict):
        loss, grads = grads_of(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new = TrainState(params, opt_state, state.step + 1)
        return new, {"loss": loss, "grad_norm": gnorm, "step": new.step}

    return jax.jit(
        step,
        in_shardings=(state_sh, batch_shardings),
        out_shardings=(state_sh, {"loss": repl, "grad_norm": repl,
                                  "step": repl}),
        donate_argnums=(0,),
    )


class Trainer:
    """Convenience loop: init + compiled step + throughput stats.

    The user-facing shape mirrors the reference's optimus coordinator
    (make work → fan out → gather → repeat, coordinator.go:46-99), but
    the fan-out/gather is one compiled SPMD program per step.
    """

    def __init__(self, cfg: tfm.TransformerConfig, mesh: Mesh,
                 optimizer=None, rng: jax.Array | None = None,
                 attn_fn=None, seq_axis: bool = False):
        from ptype_tpu.metrics import StepStats, device_peak_tflops

        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        self._attn_fn = attn_fn
        self._seq_axis = seq_axis
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.state, self.state_shardings = init_state(
            rng, cfg, mesh, self.optimizer
        )
        # Compiled steps keyed by the batch's key set (tokens/targets
        # always; loss_mask when the data provides one).
        self._steps: dict[tuple[str, ...], Callable] = {}
        self.n_params = tfm.count_params(self.state.params)
        self._stats: StepStats | None = None
        self._peak = device_peak_tflops(mesh.devices.flat[0])

    _BATCH_KEYS = ("tokens", "targets", "loss_mask")

    def _step_for(self, batch: dict) -> Callable:
        keys = tuple(k for k in self._BATCH_KEYS if k in batch)
        if "tokens" not in keys or "targets" not in keys:
            raise ValueError("batch must contain 'tokens' and 'targets'")
        fn = self._steps.get(keys)
        if fn is None:
            fn = make_train_step(self.cfg, self.mesh, self.optimizer,
                                 self._attn_fn, self._seq_axis,
                                 batch_keys=keys)
            self._steps[keys] = fn
        return fn

    @property
    def train_step(self) -> Callable:
        """The compiled (tokens, targets) step — compile on first access."""
        return self._step_for({"tokens": None, "targets": None})

    def shard_batch(self, batch: dict) -> dict:
        axis_sizes = {n: int(self.mesh.shape[n])
                      for n in self.mesh.axis_names}
        sh = NamedSharding(
            self.mesh, tfm.batch_spec(axis_sizes, self._seq_axis)
        )
        return {k: jax.device_put(v, sh) for k, v in batch.items()
                if k in self._BATCH_KEYS}

    def step(self, batch: dict) -> dict:
        from ptype_tpu.metrics import StepStats, step_annotation

        batch = self.shard_batch(batch)
        train_step = self._step_for(batch)
        if self._stats is None:
            self._stats = StepStats(
                flops_per_token=tfm.flops_per_token(
                    self.cfg, batch["tokens"].shape[1]),
                n_chips=self.mesh.devices.size,
                peak_tflops=self._peak,
            )
            self._stats.start()
        with step_annotation(int(self.state.step)):
            self.state, out = train_step(self.state, batch)
        jax.block_until_ready(out["loss"])
        self._stats.step(batch["tokens"].size)
        return {
            "loss": float(out["loss"]),
            "grad_norm": float(out["grad_norm"]),
            "step": int(out["step"]),
            "tokens_per_sec": self._stats.tokens_per_sec,
            "tokens_per_sec_per_chip": self._stats.tokens_per_sec_per_chip,
            "mfu": self._stats.mfu,
        }
