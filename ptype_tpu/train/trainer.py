"""The jit'd train step — GSPMD fast path.

One compiled program per (config, mesh): loss → grads → optax update,
jit'd with NamedSharding on every input/output and donated state buffers.
XLA inserts the collectives the shardings imply (grad allreduce over
data axes, per-layer allgathers for fsdp, psums for model/TP) and
overlaps them with compute — the compiler-scheduled equivalent of the
reference's hand-rolled scatter-gather (coordinator.go:67-99).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu.models import transformer as tfm
from ptype_tpu.parallel.topology import DATA_AXIS


@dataclass
class TrainState:
    """Minimal train state pytree (params + optax state + step)."""

    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def _decay_mask(params) -> Any:
    """True for leaves that should receive weight decay: matmul weights
    only — norm scales and biases (ndim ≤ 1) are exempt, the standard
    AdamW recipe. Note block leaves carry a leading layer dim, so norm
    scales there are ndim == 2; they are exempted by name."""

    def mask_leaf(path, leaf):
        name = ""
        for p in path:
            if hasattr(p, "key"):
                name = str(p.key)
        if "norm" in name:
            return False
        return jnp.ndim(leaf) > 1

    return jax.tree_util.tree_map_with_path(mask_leaf, params)


@dataclass(frozen=True)
class OptHParams:
    """The default recipe's hyperparameters as ONE hashable record —
    the single source every materialization of the recipe reads:
    :func:`default_optimizer` (whole-tree optax chain),
    :func:`default_optimizer_pieces` (per-bucket optax, overlap mode),
    and the flat shard-local AdamW in :mod:`ptype_tpu.parallel.zero`
    (ZeRO-1). Three copies of ``b1=0.9`` would silently drift; one
    frozen dataclass cannot."""

    lr: float = 3e-4
    weight_decay: float = 0.1
    warmup: int = 100
    decay_steps: int = 100_000
    clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8

    def schedule(self):
        return optax.warmup_cosine_decay_schedule(
            0.0, self.lr, self.warmup, decay_steps=self.decay_steps,
            end_value=self.lr * 0.1)


def default_optimizer_hparams(**overrides) -> OptHParams:
    """The default :class:`OptHParams` (overridable per field)."""
    return OptHParams(**overrides)


def default_optimizer_pieces(lr: float = 3e-4, weight_decay: float = 0.1,
                             warmup: int = 100, decay_steps: int = 100_000,
                             clip: float = 1.0):
    """The default recipe split at its one cross-leaf coupling: the
    global-norm clip. Returns ``(clip, make_inner)`` where
    ``make_inner(mask)`` builds the AdamW-with-schedule transform for
    any (sub)tree — per-leaf independent, so the overlap trainer can
    run it per gradient BUCKET as each bucket's collective lands,
    coordinating only the clip scale across buckets
    (train/store_dp.py). :func:`default_optimizer` is assembled from
    the same pieces, so the two paths cannot drift."""
    hp = OptHParams(lr=lr, weight_decay=weight_decay, warmup=warmup,
                    decay_steps=decay_steps, clip=clip)
    sched = hp.schedule()

    def make_inner(mask):
        return optax.adamw(sched, b1=hp.b1, b2=hp.b2,
                           weight_decay=hp.weight_decay, mask=mask)

    return hp.clip, make_inner


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, decay_steps: int = 100_000,
                      clip: float = 1.0):
    """AdamW + cosine schedule + global-norm clip — the standard recipe.
    Weight decay applies to matmul weights only (mask exempts norm
    scales), matching common practice."""
    clip, make_inner = default_optimizer_pieces(
        lr, weight_decay, warmup, decay_steps, clip)
    return optax.chain(
        optax.clip_by_global_norm(clip),
        make_inner(_decay_mask),
    )


def make_apply_fn(optimizer):
    """Jitted ``(params, grads, opt_state) -> (params, opt_state)`` —
    the one optimizer-step helper every eager trainer shares (store_dp,
    param_server, actor_pipeline)."""

    def apply(params, grads, opt_state):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    return jax.jit(apply)


def _path_key(path) -> tuple[str, ...]:
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(str(p.key))
        elif hasattr(p, "idx"):
            out.append(str(p.idx))
        elif hasattr(p, "name"):
            out.append(str(p.name))
        else:
            out.append(str(p))
    return tuple(out)


def _shard_update_spec(spec: P, shape: tuple, axis: str,
                       size: int) -> P:
    """Add ``axis`` onto the first unsharded, divisible dim of an
    optimizer-moment spec — cross-replica weight-update sharding
    (ZeRO-1; "Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training", PAPERS.md). Annotation is the whole
    implementation: GSPMD lowers the moment update to reduce-scatter +
    sharded update + all-gather on its own."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (e, d) in enumerate(zip(entries, shape)):
        if e is None and d >= size and d % size == 0:
            entries[i] = axis
            return P(*entries)
    return spec


def opt_state_shardings(opt_shape, params_shape, param_sh_tree, repl,
                        shard_update_axis: str | None = None):
    """Sharding for every optimizer-state leaf.

    Optax moment trees (adam mu/nu, …) mirror the params tree inside a
    larger state structure, so each opt leaf is matched to a param by
    PATH SUFFIX (('mu','blocks','wq') ends with ('blocks','wq')) with a
    shape check — never by shape alone, where two unrelated leaves that
    happen to share a shape would silently swap shardings. Unmatched
    leaves (step counts, schedule scalars) replicate.

    ``shard_update_axis``: additionally shard each matched moment over
    that (data-parallel) axis — 1/N optimizer memory per device while
    the PARAMS stay replicated (the plain-DP memory win; the fsdp axis
    already shards moments by construction).
    """
    param_map: dict[tuple[str, ...], tuple[tuple, Any]] = {}
    flat_p = jax.tree_util.tree_flatten_with_path(params_shape)[0]
    flat_sh = jax.tree_util.tree_leaves(
        param_sh_tree, is_leaf=lambda x: isinstance(x, NamedSharding))
    for (path, leaf), sh in zip(flat_p, flat_sh):
        param_map[_path_key(path)] = (tuple(leaf.shape), sh)

    def match(path, leaf):
        key = _path_key(path)
        for i in range(len(key)):
            hit = param_map.get(key[i:])
            if hit is not None and hit[0] == tuple(leaf.shape):
                sh = hit[1]
                if shard_update_axis:
                    mesh = sh.mesh
                    size = int(mesh.shape[shard_update_axis])
                    spec = _shard_update_spec(
                        sh.spec, hit[0], shard_update_axis, size)
                    if spec != sh.spec:
                        return NamedSharding(mesh, spec)
                return sh
        return repl

    return jax.tree_util.tree_map_with_path(match, opt_shape)


def _state_shardings(mesh: Mesh, cfg: tfm.TransformerConfig,
                     optimizer, shard_update: bool = False) -> TrainState:
    """Sharding pytree for TrainState: optax mirrors param specs."""
    axis_sizes = {n: int(mesh.shape[n]) for n in mesh.axis_names}
    pspecs = tfm.param_specs(cfg, axis_sizes)
    to_ns = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    param_sh = jax.tree.map(to_ns, pspecs,
                            is_leaf=lambda x: isinstance(x, P))

    params_shape = jax.eval_shape(lambda: tfm.init_params(
        jax.random.PRNGKey(0), cfg))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    upd_axis = (DATA_AXIS
                if (shard_update and DATA_AXIS in axis_sizes
                    and axis_sizes[DATA_AXIS] > 1) else None)
    if shard_update and upd_axis is None:
        from ptype_tpu import logs

        logs.get_logger("train").warning(
            "shard_update requested but the mesh has no data axis of "
            "size > 1 — optimizer moments stay unsharded",
            kv={"axes": axis_sizes})
    opt_sh = opt_state_shardings(opt_shape, params_shape, param_sh,
                                 to_ns(P()),
                                 shard_update_axis=upd_axis)
    return TrainState(param_sh, opt_sh, to_ns(P()))


def init_state(rng: jax.Array, cfg: tfm.TransformerConfig, mesh: Mesh,
               optimizer=None,
               shard_update: bool = False) -> tuple[TrainState, TrainState]:
    """Initialize a sharded TrainState ON DEVICE: init is jit'd with
    out_shardings so even 8B params never materialize unsharded.
    Returns (state, state_shardings)."""
    optimizer = optimizer or default_optimizer()
    shardings = _state_shardings(mesh, cfg, optimizer, shard_update)
    state = jax.jit(
        lambda r: _init_impl(r, cfg, optimizer),
        out_shardings=shardings,
    )(rng)
    return state, shardings


def _init_impl(rng, cfg, optimizer):
    params = tfm.init_params(rng, cfg)
    return TrainState(params, optimizer.init(params),
                      jnp.zeros((), jnp.int32))


def make_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                    optimizer=None, attn_fn: Callable | None = None,
                    seq_axis: bool = False,
                    batch_keys: tuple[str, ...] = ("tokens", "targets"),
                    grad_accum: int = 1,
                    shard_update: bool = False):
    """Compile the train step: (state, batch) → (state, metrics).

    State buffers are donated (in-place update, no HBM copy). Batch comes
    in sharded over the data-like axes; grads reduce over them via the
    sharding-implied allreduce. ``batch_keys`` fixes the batch signature
    (add "loss_mask" for masked training — every key shards the same way).
    ``grad_accum > 1`` splits the batch into that many microbatches and
    averages their grads in a ``lax.scan`` before one optimizer step —
    big effective batches on bounded activation memory.
    """
    optimizer = optimizer or default_optimizer()
    axis_sizes = {n: int(mesh.shape[n]) for n in mesh.axis_names}
    state_sh = _state_shardings(mesh, cfg, optimizer, shard_update)
    batch_sh = NamedSharding(mesh, tfm.batch_spec(axis_sizes, seq_axis))
    batch_shardings = {k: batch_sh for k in batch_keys}
    repl = NamedSharding(mesh, P())

    def grads_of(params, batch):
        if grad_accum == 1:
            return jax.value_and_grad(tfm.loss_fn)(
                params, batch, cfg, attn_fn)
        split = jax.tree.map(
            lambda x: x.reshape(grad_accum, x.shape[0] // grad_accum,
                                *x.shape[1:]),
            batch,
        )

        # Global normalizer computed over the WHOLE batch up front (the
        # mask is data, no model eval needed): each microbatch then
        # contributes nll_sum/denom, so loss and grads match grad_accum=1
        # exactly even when valid-token counts differ per microbatch.
        mask = batch.get("loss_mask")
        denom = (jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                 if mask is not None
                 else jnp.float32(batch["targets"].size))

        def micro_loss(params, mb):
            nll_sum, _, aux = tfm.loss_terms(params, mb, cfg, attn_fn)
            loss = nll_sum / denom
            if cfg.n_experts:
                loss = loss + cfg.moe_aux_coef * aux / grad_accum
            return loss

        def micro(carry, mb):
            loss_sum, grads_sum = carry
            loss, grads = jax.value_and_grad(micro_loss)(params, mb)
            return (loss_sum + loss,
                    jax.tree.map(jnp.add, grads_sum, grads)), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss, grads), _ = jax.lax.scan(
            micro, (jnp.float32(0.0), zeros), split)
        return loss, grads

    def step(state: TrainState, batch: dict):
        loss, grads = grads_of(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new = TrainState(params, opt_state, state.step + 1)
        return new, {"loss": loss, "grad_norm": gnorm, "step": new.step}

    return jax.jit(
        step,
        in_shardings=(state_sh, batch_shardings),
        out_shardings=(state_sh, {"loss": repl, "grad_norm": repl,
                                  "step": repl}),
        donate_argnums=(0,),
    )


#: Batch keys the loss reads; extra stream keys (ids, metadata) are
#: dropped before sharding/tracing. One constant for the train path's
#: filter and the eval path's — two copies would silently drift.
BATCH_KEYS = ("tokens", "targets", "loss_mask")


def make_eval_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                   attn_fn: Callable | None = None,
                   seq_axis: bool = False,
                   batch_keys: tuple[str, ...] = ("tokens", "targets")):
    """Compile the evaluation step: (params, batch) → (nll_sum, denom)
    as replicated device scalars.

    Same shardings and loss lowering as the train step (the fused
    head+loss, so (B,S,V) never materializes) with no optimizer and no
    state mutation. Returning the unnormalized pieces lets callers
    accumulate lazily (no per-batch host sync) and token-weight across
    ragged masks exactly.
    """
    axis_sizes = {n: int(mesh.shape[n]) for n in mesh.axis_names}
    batch_sh = NamedSharding(mesh, tfm.batch_spec(axis_sizes, seq_axis))
    batch_shardings = {k: batch_sh for k in batch_keys}
    repl = NamedSharding(mesh, P())

    def step(params, batch):
        nll_sum, denom, _aux = tfm.loss_terms(params, batch, cfg,
                                              attn_fn)
        return nll_sum, denom

    return jax.jit(step, in_shardings=(None, batch_shardings),
                   out_shardings=(repl, repl))


def evaluate(params, cfg: tfm.TransformerConfig, mesh: Mesh,
             batches, steps: int, attn_fn: Callable | None = None,
             seq_axis: bool = False, _step_cache: dict | None = None)\
        -> dict:
    """Mean loss + perplexity over ``steps`` batches from ``batches``.

    Token-weighted across batches (sums NLL and token counts, divides
    once) so ragged masks can't skew the mean; the per-batch scalars
    stay on device until the end, so dispatch overlaps compute.
    ``_step_cache`` (any dict the caller keeps alive, e.g. the
    Trainer's) reuses compiled eval steps across calls instead of
    retracing per evaluation.
    """
    cache = _step_cache if _step_cache is not None else {}
    nlls, denoms = [], []
    for _ in range(steps):
        batch = next(batches)
        batch = {k: v for k, v in batch.items() if k in BATCH_KEYS}
        keys = tuple(sorted(batch))
        if keys not in cache:
            cache[keys] = make_eval_step(cfg, mesh, attn_fn, seq_axis,
                                         keys)
        nll_sum, denom = cache[keys](params, batch)
        nlls.append(nll_sum)
        denoms.append(denom)
    nll_total = float(sum(nlls))
    tok_total = float(sum(denoms))
    loss = nll_total / max(tok_total, 1.0)
    import math as _math

    return {"loss": loss, "perplexity": _math.exp(min(loss, 700.0)),
            "tokens": int(tok_total)}


class Trainer:
    """Convenience loop: init + compiled step + throughput stats.

    The user-facing shape mirrors the reference's optimus coordinator
    (make work → fan out → gather → repeat, coordinator.go:46-99), but
    the fan-out/gather is one compiled SPMD program per step.
    """

    def __init__(self, cfg: tfm.TransformerConfig, mesh: Mesh,
                 optimizer=None, rng: jax.Array | None = None,
                 attn_fn=None, seq_axis: bool = False,
                 sync_every: int = 16,
                 shard_update: bool = False):
        from ptype_tpu.metrics import StepStats, device_peak_tflops

        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        # Resolve attn_impl here (not in forward) so mesh-needing
        # implementations (ring/ulysses) work and tests can introspect.
        self._attn_fn = attn_fn or tfm.resolve_attn_fn(cfg, mesh)
        if cfg.attn_impl in ("ring", "ulysses") and attn_fn is None:
            seq_axis = True
        self._seq_axis = seq_axis
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        #: Cross-replica weight-update sharding (ZeRO-1): optimizer
        #: moments shard over the data axis while params stay
        #: replicated — 1/N optimizer HBM on plain-DP meshes.
        self._shard_update = shard_update
        self.state, self.state_shardings = init_state(
            rng, cfg, mesh, self.optimizer, shard_update=shard_update
        )
        # Compiled steps keyed by the batch's key set (tokens/targets
        # always; loss_mask when the data provides one).
        self._steps: dict[tuple[str, ...], Callable] = {}
        self._eval_steps: dict[tuple[str, ...], Callable] = {}
        self.n_params = tfm.count_params(self.state.params)
        self._stats: StepStats | None = None
        self._peak = device_peak_tflops(mesh.devices.flat[0])
        #: Drain the device queue every N steps (0 = never): keeps the
        #: throughput stats honest without paying a per-step sync —
        #: host input prep overlaps device compute in between.
        self.sync_every = sync_every
        self._host_step = 0

    _BATCH_KEYS = BATCH_KEYS

    def _step_for(self, batch: dict) -> Callable:
        keys = tuple(k for k in self._BATCH_KEYS if k in batch)
        if "tokens" not in keys or "targets" not in keys:
            raise ValueError("batch must contain 'tokens' and 'targets'")
        fn = self._steps.get(keys)
        if fn is None:
            fn = make_train_step(self.cfg, self.mesh, self.optimizer,
                                 self._attn_fn, self._seq_axis,
                                 batch_keys=keys,
                                 shard_update=self._shard_update)
            self._steps[keys] = fn
        return fn

    @property
    def train_step(self) -> Callable:
        """The compiled (tokens, targets) step — compile on first access."""
        return self._step_for({"tokens": None, "targets": None})

    def shard_batch(self, batch: dict) -> dict:
        axis_sizes = {n: int(self.mesh.shape[n])
                      for n in self.mesh.axis_names}
        sh = NamedSharding(
            self.mesh, tfm.batch_spec(axis_sizes, self._seq_axis)
        )
        return {k: jax.device_put(v, sh) for k, v in batch.items()
                if k in self._BATCH_KEYS}

    def step(self, batch: dict) -> dict:
        """Dispatch one step WITHOUT waiting for it: loss/grad_norm come
        back as device scalars (reading them blocks; not reading is
        free), so the next batch's host prep overlaps device compute.
        Throughput stats advance ONLY at drain boundaries (every
        ``sync_every`` steps, or :meth:`sync`): between drains the
        previous drained rates are reported, so ``mfu``/``tokens_per_sec``
        never credit dispatched-but-unexecuted work."""
        from ptype_tpu.metrics import (StepStats, annotate, metrics,
                                       step_annotation)

        batch = self.shard_batch(batch)
        train_step = self._step_for(batch)
        if self._stats is None:
            self._stats = StepStats(
                flops_per_token=tfm.flops_per_token(
                    self.cfg, batch["tokens"].shape[1]),
                n_chips=self.mesh.devices.size,
                peak_tflops=self._peak,
            )
            self._host_step = int(self.state.step)
            self._pending_tokens = 0
            self._pending_steps = 0
            self._stats.start()
        # train.step is the health-plane seam too (goodput ledger /
        # trace span). NOTE: this trainer dispatches asynchronously —
        # the region measures dispatch between drains and the whole
        # queue at a drain boundary; the store-DP trainer is the
        # per-step-accurate goodput source.
        with annotate("train.step"), step_annotation(self._host_step):
            self.state, out = train_step(self.state, batch)
        self._host_step += 1
        metrics.counter("train.steps").add(1)
        self._pending_tokens += batch["tokens"].size
        self._pending_steps += 1
        if self.sync_every and self._host_step % self.sync_every == 0:
            jax.block_until_ready(out["loss"])
            # loss is materialized at the drain anyway — stamp the
            # health gauge without adding a sync.
            metrics.gauge("train.loss").set(float(out["loss"]))
            self._fold_pending()
        return {
            "loss": out["loss"],
            "grad_norm": out["grad_norm"],
            "step": self._host_step,
            "tokens_per_sec": self._stats.tokens_per_sec,
            "tokens_per_sec_per_chip": self._stats.tokens_per_sec_per_chip,
            "mfu": self._stats.mfu,
        }

    def _fold_pending(self) -> None:
        if self._stats is not None and self._pending_steps:
            self._stats.step(self._pending_tokens, self._pending_steps)
            self._pending_tokens = 0
            self._pending_steps = 0

    def sync(self) -> None:
        """Drain the device queue (call before reading final stats)."""
        jax.block_until_ready(self.state.params)
        self._fold_pending()

    def evaluate(self, batches, steps: int) -> dict:
        """Held-out mean loss + perplexity with this trainer's mesh,
        attention lowering, and sharding — no state mutation. Compiled
        eval steps are cached on the trainer across calls."""
        self.sync()  # evaluate the CURRENT params, not a queued update
        return evaluate(self.state.params, self.cfg, self.mesh, batches,
                        steps, attn_fn=self._attn_fn,
                        seq_axis=self._seq_axis,
                        _step_cache=self._eval_steps)

    def throughput(self) -> dict:
        """Drained throughput rates. Call after :meth:`sync` (or at any
        drain boundary) for numbers that reflect completed compute."""
        if self._stats is None:
            return {"tokens_per_sec": 0.0,
                    "tokens_per_sec_per_chip": 0.0, "mfu": 0.0}
        return {
            "tokens_per_sec": self._stats.tokens_per_sec,
            "tokens_per_sec_per_chip":
                self._stats.tokens_per_sec_per_chip,
            "mfu": self._stats.mfu,
        }
