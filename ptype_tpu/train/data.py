"""Synthetic token streams for benches and tests.

The reference's workload generator was the prime-candidate range splitter
(example/optimus/coordinator.go:67-73); the training equivalent is an
infinite stream of (tokens, targets) batches. Synthetic data is generated
ON DEVICE (jit'd PRNG) so the input pipeline never bottlenecks a bench —
host→device transfer is part of what BASELINE.md's tokens/sec measures,
and a real loader would hide it with prefetch; here there is nothing to
hide.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def synthetic_batches(vocab_size: int, batch: int, seq: int,
                      seed: int = 0):
    """Infinite iterator of {"tokens", "targets"} int32 device arrays.

    targets = tokens shifted by one (next-token LM), generated from a
    counter-derived PRNG key so the stream is reproducible and stateless.
    """

    @jax.jit
    def make(step):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        toks = jax.random.randint(
            key, (batch, seq + 1), 0, vocab_size, jnp.int32
        )
        return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    step = 0
    while True:
        yield make(step)
        step += 1
