"""Synthetic token streams for benches and tests.

The reference's workload generator was the prime-candidate range splitter
(example/optimus/coordinator.go:67-73); the training equivalent is an
infinite stream of (tokens, targets) batches. Synthetic data is generated
ON DEVICE (jit'd PRNG) so the input pipeline never bottlenecks a bench —
host→device transfer is part of what BASELINE.md's tokens/sec measures,
and a real loader would hide it with prefetch; here there is nothing to
hide.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def synthetic_batches(vocab_size: int, batch: int, seq: int,
                      seed: int = 0):
    """Infinite iterator of {"tokens", "targets"} int32 device arrays.

    targets = tokens shifted by one (next-token LM), generated from a
    counter-derived PRNG key so the stream is reproducible and stateless.
    """

    @jax.jit
    def make(step):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        toks = jax.random.randint(
            key, (batch, seq + 1), 0, vocab_size, jnp.int32
        )
        return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    step = 0
    while True:
        yield make(step)
        step += 1


# --------------------------------------------------------------- corpora


def write_token_file(path: str, tokens, dtype=None) -> None:
    """Write a flat token array as a raw binary corpus file."""
    import numpy as np

    arr = np.asarray(tokens)
    arr.astype(dtype or arr.dtype).tofile(path)


def local_row_range(sharding, batch: int, seq: int) -> tuple[int, int]:
    """[lo, hi) batch rows this process's addressable devices cover
    under ``sharding`` for a (batch, seq) array — contiguous for the
    standard data-axis batch specs, so a multi-controller loader can
    materialize only its slice of the global batch."""
    idx = sharding.addressable_devices_indices_map((batch, seq))
    row_slices = [s[0] for s in idx.values()]
    lo = min(s.start or 0 for s in row_slices)
    hi = max(batch if s.stop is None else s.stop for s in row_slices)
    covered = {r for s in row_slices
               for r in range((s.start or 0),
                              batch if s.stop is None else s.stop)}
    if covered != set(range(lo, hi)):
        # Interleaved/gapped device placement (a mesh NOT built via the
        # registry's process-id ordering): min/max would claim rows
        # this process doesn't own and the loader would feed
        # make_array_from_process_local_data the wrong rows — fail
        # loudly instead.
        raise ValueError(
            "local_row_range: this process's batch rows are not "
            "contiguous under the sharding; use a process-contiguous "
            "mesh (mesh_from_registry) or load the full batch")
    return lo, hi


class TokenFileDataset:
    """Memory-mapped flat token corpus → prefetched device batches.

    The real-data path the reference never had (its "dataset" was a
    prime-candidate range, coordinator.go:67-73). TPU-first behaviors:
    the corpus is ``np.memmap``-ed (no RAM copy, any size), batches are
    gathered on host and ``device_put`` by a background thread one step
    ahead, so host→device transfer overlaps with the current step's
    compute — the double-buffering a synchronous loader can't do.
    """

    def __init__(self, path: str, dtype="uint16", sharding=None):
        import numpy as np

        self._data = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        self.n_tokens = int(self._data.shape[0])
        self._sharding = sharding

    def batches(self, batch: int, seq: int, seed: int = 0,
                prefetch: int = 2):
        """Infinite iterator of {"tokens", "targets"} int32 device
        arrays; random windows, reproducible per seed."""
        import queue
        import threading

        import numpy as np

        if self.n_tokens < seq + 2:
            raise ValueError(
                f"corpus has {self.n_tokens} tokens; need > {seq + 1}")
        rng = np.random.default_rng(seed)
        q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        stop = threading.Event()
        ERR = "__prefetch_error__"

        import jax

        # Multi-controller: every process draws the SAME window starts
        # (shared seed → identical rng stream), but each MATERIALIZES
        # only the batch rows its addressable shards cover — per-host
        # IO scales down with the process count. Computed in the
        # CALLING thread: local_row_range's non-contiguous-placement
        # ValueError must surface here, not kill the producer thread
        # before its error-routing try block (the consumer would hang
        # in q.get() forever).
        sh = self._sharding
        local_rows = (local_row_range(sh, batch, seq)
                      if sh is not None and jax.process_count() > 1
                      else None)

        def producer():
            def make_batch(rows_for, to_device):
                starts = rng.integers(
                    0, self.n_tokens - seq - 1, size=batch)
                rows = np.stack([
                    np.asarray(self._data[s: s + seq + 1])
                    for s in rows_for(starts)
                ]).astype(np.int32)
                out = {"tokens": rows[:, :-1], "targets": rows[:, 1:]}
                return {k: to_device(v) for k, v in out.items()}

            try:
                while not stop.is_set():
                    if local_rows is not None:
                        lo, hi = local_rows
                        out = make_batch(
                            lambda st: st[lo:hi],
                            lambda v: jax.make_array_from_process_local_data(
                                sh, v, (batch,) + v.shape[1:]))
                    else:
                        out = make_batch(
                            lambda st: st,
                            lambda v: jax.device_put(v, sh))
                    # Bounded put so the thread exits promptly once the
                    # consumer abandons the iterator (no immortal thread
                    # pinning device buffers).
                    while not stop.is_set():
                        try:
                            q.put(out, timeout=0.2)
                            break
                        except queue.Full:
                            continue
            except Exception as e:  # noqa: BLE001 — surface to consumer
                # Same stop-aware bounded put as the happy path: if the
                # consumer abandoned the iterator while the queue is
                # full, the thread must still exit (not block forever
                # with the error never read).
                while not stop.is_set():
                    try:
                        q.put((ERR, e), timeout=0.2)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, name="token-prefetch",
                             daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and item[0] is ERR:
                    raise RuntimeError(
                        "token prefetch failed") from item[1]
                yield item
        finally:
            stop.set()  # generator closed/GC'd → producer exits
