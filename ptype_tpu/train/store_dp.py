"""Store-backed data-parallel training — the north-star lowering, literal.

BASELINE.json: "`cluster/store.go`'s replicated KV becomes an
XLA-collective parameter store whose push/pull lowers to allreduce/
allgather over ICI". This trainer exercises that contract exactly:

- each data-parallel worker computes grads on its shard,
- ``TensorStore.push_tree("grads", stacked)`` reduces them (psum/pmean
  over the mesh's data axis — the Put that raft used to replicate,
  store.go:56-62),
- the optimizer applies the reduced grads and ``put``s params back, and
  workers ``pull`` them (the linearizable Get, store.go:38-53).

It is deliberately eager between the compiled pieces so the Store
semantics stay observable (epochs bump per push, manifests publish to the
KV tier). The fully-fused GSPMD path in trainer.py is the throughput
choice; this mode exists for Store-semantics parity + the async
param-server family built on it (train/param_server.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu.models import transformer as tfm
from ptype_tpu.parallel.tensorstore import TensorStore, _path_part
from ptype_tpu.train.trainer import default_optimizer, make_apply_fn


class StoreDPTrainer:
    """Data-parallel trainer whose gradient exchange IS the Store."""

    def __init__(self, cfg: tfm.TransformerConfig, store: TensorStore,
                 optimizer=None, rng: jax.Array | None = None):
        self.cfg = cfg
        self.store = store
        self.mesh: Mesh = store.mesh
        self.axis = store.axis
        self.n_workers = int(self.mesh.shape[self.axis])
        self.optimizer = optimizer or default_optimizer()
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        params = jax.jit(lambda r: tfm.init_params(r, cfg))(rng)
        self.opt_state = self.optimizer.init(params)
        self.store.put_tree("params", params)
        self._treedef = jax.tree_util.tree_structure(params)
        # Keys in TREEDEF leaf order (tree_flatten_with_path order), NOT
        # the Store's string-sorted order — string sort permutes numeric
        # path components ('10' < '2'), which would silently cross-wire
        # leaves on unflatten.
        self._keys = [
            "params/" + "/".join(_path_part(p) for p in path)
            for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        ]
        self.step_count = 0

        # Per-worker grad fn, vmapped over the stacked worker batch dim —
        # one compiled program computing every worker's local grads, laid
        # out sharded over the data axis (SPMD over the mesh).
        def local_grads(params, batch):
            loss, grads = jax.value_and_grad(tfm.loss_fn)(
                params, batch, cfg
            )
            return loss, grads

        self._grads_fn = jax.jit(jax.vmap(local_grads, in_axes=(None, 0)))
        self._apply_fn = make_apply_fn(self.optimizer)

    def params(self) -> dict:
        flat = self.store.get_tree("params")
        return jax.tree_util.tree_unflatten(
            self._treedef, [flat[k] for k in self._keys]
        )

    def step(self, batch: dict) -> dict:
        """One DP step. ``batch`` leaves are (B, S); B splits evenly into
        n_workers stacked shards (the scatter, coordinator.go:67-73).

        The whole step runs inside a ``train.step`` region (the
        metrics.annotate seam): one profiler annotation AND — when the
        trace plane is armed — one span whose children are the Store
        push (``store.push_tree/...``) and any coord manifest traffic,
        so a soak failure shows which step a fault landed in. The same
        seam feeds the health plane's goodput ledger (per-step
        data/compute/collective breakdown) when one is installed."""
        from ptype_tpu.metrics import annotate, metrics

        with annotate("train.step"):
            out = self._step(batch)
        # The scalar families the health alert rules watch: loss
        # (NaN/spike) as a gauge, step progress (stall detection) as a
        # counter — sampled into series by the health Sampler.
        metrics.gauge("train.loss").set(out["loss"])
        metrics.counter("train.steps").add(1)
        return out

    def _step(self, batch: dict) -> dict:
        from ptype_tpu.metrics import annotate

        B = batch["tokens"].shape[0]
        if B % self.n_workers:
            raise ValueError(
                f"batch size {B} not divisible by {self.n_workers} workers"
            )
        # The data leg of the goodput breakdown: host→device batch
        # staging, attributed separately from compute/collective.
        with annotate("train.data"):
            sh = NamedSharding(self.mesh, P(self.axis, None, None))
            stacked = {
                k: jax.device_put(
                    jnp.reshape(v,
                                (self.n_workers, B // self.n_workers, -1)),
                    sh,
                )
                for k, v in batch.items()
            }
        params = self.params()
        losses, grads = self._grads_fn(params, stacked)

        # The gather: Store push == pmean allreduce over the data axis,
        # bucketed — the whole grad tree reduces in ceil(bytes/bucket)
        # fused launches per dtype group, all in flight before the
        # optimizer consumes the first leaf. push_tree returns the
        # committed views, so no second get_tree round trip.
        reduced_flat = self.store.push_tree("grads", grads, op="mean")
        reduced = jax.tree_util.tree_unflatten(
            self._treedef,
            [reduced_flat[k.replace("params/", "grads/", 1)]
             for k in self._keys],
        )

        new_params, self.opt_state = self._apply_fn(
            params, reduced, self.opt_state
        )
        self.store.put_tree("params", new_params)
        self.step_count += 1
        return {
            "loss": float(jnp.mean(losses)),
            "step": self.step_count,
            "grad_epoch": self.store.epoch(self._grad_key0()),
        }

    def _grad_key0(self) -> str:
        return self._keys[0].replace("params/", "grads/", 1)
