"""Store-backed data-parallel training — the north-star lowering, literal.

BASELINE.json: "`cluster/store.go`'s replicated KV becomes an
XLA-collective parameter store whose push/pull lowers to allreduce/
allgather over ICI". This trainer exercises that contract exactly:

- each data-parallel worker computes grads on its shard,
- ``TensorStore.push_tree("grads", stacked)`` reduces them (psum/pmean
  over the mesh's data axis — the Put that raft used to replicate,
  store.go:56-62),
- the optimizer applies the reduced grads and ``put``s params back, and
  workers ``pull`` them (the linearizable Get, store.go:38-53).

It is deliberately eager between the compiled pieces so the Store
semantics stay observable (epochs bump per push, manifests publish to the
KV tier). The fully-fused GSPMD path in trainer.py is the throughput
choice; this mode exists for Store-semantics parity + the async
param-server family built on it (train/param_server.py).

Gradient-exchange modes (``overlap``):

- ``False`` (default): the legacy fully-async barrier step — push_tree
  dispatches every bucket, the whole-tree optimizer apply consumes the
  results, and nothing on the host blocks until the loss readback.
- ``"drain"``: the synchronous-DDP accounting baseline — same step,
  but the host waits out the collectives (``store.push_wait`` region)
  before the apply, so the goodput ledger's collective leg carries the
  reduce wall time. This is the honest "before" for the overlap
  comparison.
- ``True``: T3-style fine-grained overlap (PAPERS.md arXiv
  2401.16677): buckets dispatch lazily through
  ``TensorStore.push_tree_iter``, each bucket's wait interleaves with
  the next bucket's dispatch + commit + the per-bucket optimizer
  bookkeeping, and the optimizer applies per BUCKET (the default AdamW
  recipe decomposed via ``trainer.default_optimizer_pieces``; the
  global-norm clip — the recipe's one cross-bucket coupling — is
  coordinated through per-bucket partial norms as a device value, so
  the host never syncs for it). A custom ``optimizer`` falls back to
  the whole-tree apply with streamed waits (an arbitrary optax chain
  can't be split per bucket safely).

``zero`` selects a rung of the cross-replica sharding LADDER
(parallel/zero.py, PAPERS.md arXiv 2004.13336); every rung shards the
optimizer state 1/N and runs the identical shard-local AdamW:

- ``zero=1``: grads ride the bucketed ALLREDUCE stream
  (``push_tree_iter``) and stay replicated; the fused apply slices
  each replica's shard of params and grads, then allgathers the
  updated params back.
- ``zero=2`` (also the back-compat ``zero=True``): gradients
  reduce-SCATTER bucket-by-bucket
  (``TensorStore.push_tree_scatter_iter`` — half the wire bytes, same
  int8+EF wire, residuals owned per shard), each replica's grad shard
  feeds the update directly, and the updated params allgather back —
  fused into the per-bucket apply program — before committing to the
  Store. The allgathers dispatch asynchronously, so they overlap the
  next step's data staging the same way the push_tree_iter stream
  overlaps the reduce.
- ``zero=3``: params are RESIDENT sharded too (``ZeroState.pflat`` —
  ``ScatteredTree``-style flats are the only layout); each bucket
  allgathers just-in-time for the forward (one fused launch per
  bucket, the gathered buffers donated to the grads program so they
  die after the forward), the update is purely elementwise on the
  flats, and the new param flats commit straight back to the Store.

All rungs survive churn in-place: :meth:`StoreDPTrainer.reshard`
re-pads and re-places the whole resident state onto a survivor mesh
(``ZeroState.reshard`` — atomic, moments bit-preserved) without a
checkpoint round trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu import jitwatch
from ptype_tpu.models import transformer as tfm
from ptype_tpu.parallel.mesh import axis_n
from ptype_tpu.parallel.tensorstore import TensorStore, _path_part
from ptype_tpu.parallel.topology import DATA_AXIS
from ptype_tpu.parallel.zero import ShardPlan, ZeroState
from ptype_tpu.train.trainer import (_decay_mask, default_optimizer,
                                     default_optimizer_hparams,
                                     default_optimizer_pieces,
                                     make_apply_fn)

_OVERLAP_MODES = (False, "drain", True)

#: Per-bucket partial square-norm over FULL reduced leaves (the zero=1
#: allreduce stream) — same global-norm coordination as the sharded
#: flats' _sqnorm, summed across buckets by clip_scale.
_leaves_sqnorm = jax.jit(
    lambda vs: sum(jnp.sum(jnp.square(v.astype(jnp.float32)))
                   for v in vs))


def _resident_nbytes(arr) -> int:
    """Bytes THIS replica holds of ``arr`` (one addressable shard for
    sharded arrays, the whole buffer for replicated ones)."""
    shards = getattr(arr, "addressable_shards", None)
    return shards[0].data.nbytes if shards else arr.nbytes


class StoreDPTrainer:
    """Data-parallel trainer whose gradient exchange IS the Store."""

    def __init__(self, cfg: tfm.TransformerConfig, store: TensorStore,
                 optimizer=None, rng: jax.Array | None = None,
                 overlap=False, zero: bool = False,
                 zero_hparams=None):
        if overlap not in _OVERLAP_MODES:
            raise ValueError(
                f"StoreDPTrainer: overlap must be one of "
                f"{_OVERLAP_MODES}, got {overlap!r}")
        # Normalize the ladder knob: bool True predates the ladder and
        # IS the reduce-scatter rung (kept as the back-compat
        # spelling); integers name the rung explicitly. The identity
        # check matters — ``True == 1`` but the bool spelling must map
        # to stage 2, not 1.
        if zero is True:
            zero_stage = 2
        elif zero in (False, 0, None):
            zero_stage = 0
        elif zero in (1, 2, 3):
            zero_stage = int(zero)
        else:
            raise ValueError(
                f"StoreDPTrainer: zero must be False, True (= stage "
                f"2), or a ZeRO ladder stage 1/2/3, got {zero!r}")
        if zero and optimizer is not None:
            raise ValueError(
                "StoreDPTrainer: zero=True shards the DEFAULT AdamW "
                "recipe (parallel/zero.py); an arbitrary optimizer "
                "cannot be decomposed into shard-local flat applies — "
                "tune it via zero_hparams (trainer.OptHParams) or "
                "pass zero=False")
        if zero_hparams is not None and not zero:
            raise ValueError(
                "StoreDPTrainer: zero_hparams only applies with "
                "zero=True")
        if zero and overlap is not False:
            raise ValueError(
                "StoreDPTrainer: zero=True has its own streamed "
                "reduce-scatter pipeline; combine it with "
                "overlap=False")
        self.cfg = cfg
        self.store = store
        self.mesh: Mesh = store.mesh
        self.axis = store.axis
        self.n_workers = axis_n(self.mesh, self.axis)
        self.overlap = overlap
        self.zero = zero_stage > 0
        self.zero_stage = zero_stage
        self._custom_opt = optimizer is not None
        self.optimizer = optimizer or default_optimizer()
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        params = jax.jit(lambda r: tfm.init_params(r, cfg))(rng)
        # overlap=True with the default recipe trains through
        # _bucket_states — and zero=True through the 1/N-resident
        # ZeroState — NOT this whole-tree state: leave it None so a
        # consumer (checkpoint, mode switch) fails loudly instead of
        # silently reading never-updated init moments. PT007 enforces
        # the converse: nothing in train/ may build full-tree state
        # outside these init helpers.
        self.opt_state = (None if zero
                          or (overlap is True and not self._custom_opt)
                          else self.optimizer.init(params))
        seed_seq = self.store.put_tree("params", params)
        self._treedef = jax.tree_util.tree_structure(params)
        # Keys in TREEDEF leaf order (tree_flatten_with_path order), NOT
        # the Store's string-sorted order — string sort permutes numeric
        # path components ('10' < '2'), which would silently cross-wire
        # leaves on unflatten.
        self._keys = [
            "params/" + "/".join(_path_part(p) for p in path)
            for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        ]
        self._key_index = {k: i for i, k in enumerate(self._keys)}
        # The committed device views, kept locally: the trainer itself
        # wrote them, so re-pulling the whole tree from the store every
        # step is a pure round trip. tree_seq guards external mutation.
        self._param_leaves = list(jax.tree_util.tree_leaves(params))
        self._params_seq = seed_seq
        self.step_count = 0

        # Per-bucket apply machinery (overlap=True, default recipe) —
        # built lazily on the first step, when the bucket plan is known.
        self._buckets: list[list[int]] | None = None
        self._bucket_states: list | None = None
        self._apply_fns: list | None = None
        self._sqnorm_fns: list | None = None
        self._scale_fn = None

        # ZeRO-1 sharded update state (zero=True): the shard plan is
        # known AT INIT (it is a pure function of the param shapes and
        # the wire's bucket_bytes), so the moments materialize sharded
        # from step 0 — no replica ever holds the full optimizer state.
        self._zero: ZeroState | None = None
        self._zero_order: list[int] | None = None
        if self.zero:
            # Slot order is the gradient stream's: store-sorted keys
            # ("grads/..." sorts like "params/..." — same suffixes).
            order = sorted(range(len(self._keys)),
                           key=lambda i: self._keys[i])
            self._zero_order = order
            mask_leaves = jax.tree_util.tree_leaves(_decay_mask(params))
            plan = ShardPlan.for_leaves(
                [self._param_leaves[i] for i in order],
                self.n_workers, self.store.wire.bucket_bytes)
            self._zero = ZeroState.create(
                plan, self.mesh, self.axis,
                zero_hparams or default_optimizer_hparams(),
                [mask_leaves[i] for i in order])
            if self.zero_stage == 3:
                # Params leave the replicated world entirely: resident
                # as P(axis) bucket flats. The seed put_tree's
                # replicated leaf entries are dropped from the store
                # and replaced with per-bucket flat commits (epoch
                # semantics like the grad scatter path) — no replica
                # holds the full tree after this point.
                self._zero.scatter_params(
                    [self._param_leaves[i] for i in order])
                for k in self._keys:
                    self.store.delete(k)
                for bi, flat in enumerate(self._zero.pflat):
                    self.store.commit_sharded(
                        f"params/bucket{bi:05d}", flat)
                self._param_leaves = None
                self._params_seq = self.store.tree_seq("params")
        #: Per-replica resident gradient bytes of the last step's
        #: exchange (full leaves under zero=1, one shard per replica
        #: under zero=2/3) — the bench ladder's grad column.
        self.last_grad_bytes: int | None = None

        # Per-worker grad fn, vmapped over the stacked worker batch dim —
        # one compiled program computing every worker's local grads, laid
        # out sharded over the data axis (SPMD over the mesh).
        def local_grads(params, batch):
            loss, grads = jax.value_and_grad(tfm.loss_fn)(
                params, batch, cfg
            )
            return loss, grads

        # Under zero=3 the gathered param leaves are TRANSIENT: they
        # live only for the forward (locals of _step) and die when it
        # returns — the resident footprint stays the sharded flats,
        # and the apply program's donation (parallel/zero.py
        # _shard_apply3_fn, pinned by progaudit) keeps the update
        # in-place on those flats.
        self._grads_fn = jax.jit(jax.vmap(local_grads, in_axes=(None, 0)))
        self._apply_fn = make_apply_fn(self.optimizer)
        #: (params avals, stacked-batch avals) stashed on the first
        #: step — what compiled_cost() lowers the cost programs
        #: against without holding batch data.
        self._cost_avals: tuple | None = None

    def params(self) -> dict:
        """The current parameter tree. Served from the locally-kept
        committed views — the store is only re-pulled when its write
        stamp says some OTHER writer touched the namespace since this
        trainer's own last put (external mutation / epoch mismatch).

        Under ``zero=3`` there IS no replicated residency: the tree is
        materialized just-in-time from the resident shards via the ONE
        sanctioned full-tree gather (``ZeroState.gather_params``)."""
        if self.zero_stage == 3:
            gathered = self._zero.gather_params()
            leaves = [None] * len(self._keys)
            for slot, i in enumerate(self._zero_order):
                leaves[i] = gathered[slot]
            return jax.tree_util.tree_unflatten(self._treedef, leaves)
        seq = self.store.tree_seq("params")
        if seq == self._params_seq and self._param_leaves is not None:
            return jax.tree_util.tree_unflatten(
                self._treedef, self._param_leaves)
        flat = self.store.get_tree("params")
        self._param_leaves = [flat[k] for k in self._keys]
        self._params_seq = seq
        return jax.tree_util.tree_unflatten(
            self._treedef, self._param_leaves)

    def step(self, batch: dict) -> dict:
        """One DP step. ``batch`` leaves are (B, S); B splits evenly into
        n_workers stacked shards (the scatter, coordinator.go:67-73).

        The whole step runs inside a ``train.step`` region (the
        metrics.annotate seam): one profiler annotation AND — when the
        trace plane is armed — one span whose children are the Store
        push (``store.push_tree/...``) and any coord manifest traffic,
        so a soak failure shows which step a fault landed in. The same
        seam feeds the health plane's goodput ledger (per-step
        data/compute/collective breakdown) when one is installed."""
        from ptype_tpu.metrics import annotate, metrics

        with annotate("train.step"):
            out = self._step(batch)
        # The scalar families the health alert rules watch: loss
        # (NaN/spike) as a gauge, step progress (stall detection) as a
        # counter — sampled into series by the health Sampler.
        metrics.gauge("train.loss").set(out["loss"])
        metrics.counter("train.steps").add(1)
        return out

    def _stage(self, batch: dict):
        from ptype_tpu.metrics import annotate

        B = batch["tokens"].shape[0]
        if B % self.n_workers:
            raise ValueError(
                f"batch size {B} not divisible by {self.n_workers} workers"
            )
        # The data leg of the goodput breakdown: host→device batch
        # staging, attributed separately from compute/collective.
        with annotate("train.data"), \
                jitwatch.sanctioned_transfer("train.data"):
            # The sanctioned host→device seam: the batch upload IS the
            # data leg's contract — typed and counted, so an armed
            # hot region elsewhere can disallow every other transfer.
            sh = NamedSharding(self.mesh, P(self.axis, None, None))
            return {
                k: jax.device_put(
                    jnp.reshape(v,
                                (self.n_workers, B // self.n_workers, -1)),
                    sh,
                )
                for k, v in batch.items()
            }

    def _step(self, batch: dict) -> dict:
        from ptype_tpu.metrics import annotate

        stacked = self._stage(batch)
        params = self.params()
        if self._cost_avals is None:
            aval = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
            self._cost_avals = (
                jax.tree_util.tree_map(aval, params),
                jax.tree_util.tree_map(aval, stacked))
        with jitwatch.hot_region("train.step"):
            # Armed, the guard disallows implicit transfers across the
            # whole dispatch chain (grads → reduce → apply): the batch
            # already staged through the sanctioned seam, so anything
            # else crossing the host boundary here is a leak.
            losses, grads = self._grads_fn(params, stacked)

            if self.zero_stage == 1:
                self._reduce_apply_zero1(grads)
            elif self.zero_stage == 3:
                self._reduce_apply_zero3(grads)
            elif self.zero:
                self._reduce_apply_zero(grads)
            elif self.overlap is True:
                self._reduce_apply_overlapped(params, grads)
            elif self.overlap == "drain":
                # Synchronous-DDP accounting: every bucket dispatched,
                # then waited out through BucketPush.wait (the one
                # collective-attribution contract), so the goodput
                # ledger's collective leg is the reduce wall time — the
                # honest baseline the overlap mode shrinks.
                handles = self.store.push_tree_stream("grads", grads,
                                                      op="mean")
                for h in handles:
                    h.wait()
                reduced = self._tree_from_handles(handles)
                with annotate("train.opt"):
                    new_params, self.opt_state = self._apply_fn(
                        params, reduced, self.opt_state)
                self._param_leaves = list(
                    jax.tree_util.tree_leaves(new_params))
                self._params_seq = self.store.put_tree("params",
                                                       new_params)
            else:
                # The gather: Store push == pmean allreduce over the
                # data axis, bucketed — the whole grad tree reduces in
                # ceil(bytes/bucket) fused launches per dtype group,
                # all in flight before the optimizer consumes the
                # first leaf. push_tree returns the committed views
                # directly.
                reduced_flat = self.store.push_tree("grads", grads,
                                                    op="mean")
                reduced = jax.tree_util.tree_unflatten(
                    self._treedef,
                    [reduced_flat[k.replace("params/", "grads/", 1)]
                     for k in self._keys])
                with annotate("train.opt"):
                    new_params, self.opt_state = self._apply_fn(
                        params, reduced, self.opt_state
                    )
                self._param_leaves = list(
                    jax.tree_util.tree_leaves(new_params))
                # Stamp from the seqs OUR put assigned (not a re-read
                # of the global max, which would absorb a concurrent
                # external write into the cache stamp and hide it).
                self._params_seq = self.store.put_tree("params",
                                                       new_params)

        self.step_count += 1
        return {
            "loss": float(jnp.mean(losses)),
            "step": self.step_count,
            "grad_epoch": self.store.epoch(self._grad_key0()),
        }

    # ------------------------------------------- ZeRO sharded updates

    def _reduce_apply_zero(self, grads) -> None:
        """The sharded weight update: stream the per-bucket gradient
        reduce-SCATTER (bucket i's wait interleaves bucket i+1's
        dispatch, like the overlap mode's allreduce stream), coordinate
        the global-norm clip through per-bucket partial sqnorms, then
        run the fused shard-local-AdamW + param-allgather program per
        bucket. Everything dispatches async — the final put_tree's
        arrays are still in flight while the next step stages data."""
        from ptype_tpu.metrics import annotate

        handles = []
        sqs = []
        prev = None
        for h in self.store.push_tree_scatter_iter("grads", grads,
                                                   op="mean"):
            handles.append(h)
            sqs.append(self._zero.partial_sqnorm(h.flat))
            if prev is not None:
                prev.wait()
            prev = h
        if prev is not None:
            prev.wait()
        # The shard-local optimizer leg — its own component in the
        # goodput breakdown (health/goodput.py), so ZeRO's update-FLOP
        # savings are visible in `obs top` and the bench tail.
        with annotate("train.opt/zero"):
            scale = self._zero.clip_scale(sqs)
            for bi, h in enumerate(handles):
                idxs = [self._zero_order[s.index]
                        for s in h.bucket.slots]
                newp = self._zero.apply_bucket(
                    bi, [self._param_leaves[i] for i in idxs],
                    h.flat, scale)
                for i, leaf in zip(idxs, newp):
                    self._param_leaves[i] = leaf
            self._zero.finish_step()
        self.last_grad_bytes = sum(_resident_nbytes(h.flat)
                                   for h in handles)
        new_params = jax.tree_util.tree_unflatten(
            self._treedef, self._param_leaves)
        self._params_seq = self.store.put_tree("params", new_params)

    def _reduce_apply_zero1(self, grads) -> None:
        """ZeRO-1 rung: grads ride the bucketed ALLREDUCE stream
        (``push_tree_iter`` — full reduced leaves, replicated) and the
        fused apply slices each replica's shard of params AND grads
        before the shard-local AdamW + param allgather. Optimizer
        memory is 1/N like the other rungs; grad memory stays full —
        the ladder's measured middle step."""
        from ptype_tpu.metrics import annotate

        handles = []
        sqs = []
        prev = None
        for h in self.store.push_tree_iter("grads", grads, op="mean"):
            handles.append(h)
            sqs.append(_leaves_sqnorm([v for _, v in h.items()]))
            if prev is not None:
                prev.wait()
            prev = h
        if prev is not None:
            prev.wait()
        if len(handles) != len(self._zero.plan.buckets):
            raise ValueError(
                f"zero=1: grad stream produced {len(handles)} "
                f"buckets, the shard plan has "
                f"{len(self._zero.plan.buckets)} — plans diverged")
        with annotate("train.opt/zero"):
            scale = self._zero.clip_scale(sqs)
            grad_bytes = 0
            for bi, h in enumerate(handles):
                idxs = [self._grad_index(k) for k in h.keys]
                gleaves = [v for _, v in h.items()]
                grad_bytes += sum(v.nbytes for v in gleaves)
                newp = self._zero.apply_bucket_full(
                    bi, [self._param_leaves[i] for i in idxs],
                    gleaves, scale)
                for i, leaf in zip(idxs, newp):
                    self._param_leaves[i] = leaf
            self._zero.finish_step()
        self.last_grad_bytes = grad_bytes
        new_params = jax.tree_util.tree_unflatten(
            self._treedef, self._param_leaves)
        self._params_seq = self.store.put_tree("params", new_params)

    def _reduce_apply_zero3(self, grads) -> None:
        """ZeRO-3 rung: grads reduce-scatter exactly like ZeRO-2, but
        params are resident sharded too — the apply is purely
        elementwise on the flats (NO collective; progaudit pins it at
        zero launches) and each bucket's new param flat commits
        straight back to the store with an epoch bump. The full tree
        is never materialized on the update path."""
        from ptype_tpu.metrics import annotate

        handles = []
        sqs = []
        prev = None
        for h in self.store.push_tree_scatter_iter("grads", grads,
                                                   op="mean"):
            handles.append(h)
            sqs.append(self._zero.partial_sqnorm(h.flat))
            if prev is not None:
                prev.wait()
            prev = h
        if prev is not None:
            prev.wait()
        with annotate("train.opt/zero"):
            scale = self._zero.clip_scale(sqs)
            grad_bytes = 0
            for bi, h in enumerate(handles):
                grad_bytes += _resident_nbytes(h.flat)
                newflat = self._zero.apply_bucket3(bi, h.flat, scale)
                self.store.commit_sharded(
                    f"params/bucket{bi:05d}", newflat)
            self._zero.finish_step()
        self.last_grad_bytes = grad_bytes
        self._params_seq = self.store.tree_seq("params")

    # ---------------------------------------------- live resharding

    def reshard(self, mesh: Mesh, axis: str | None = None) -> dict:
        """LIVE reshard onto a survivor mesh — no checkpoint round
        trip. Re-pads and re-places the resident ZeRO state
        (``ZeroState.reshard`` — atomic, moments bit-preserved),
        re-homes the store, re-places the params, and training
        continues on the next ``step()`` call (the jitted programs
        retrace for the new mesh on first use).

        The move runs as a ``train.reshard`` span with an inflight
        gauge and a completion counter — the ``reshard-stall`` health
        rule's series. On a raise (the per-bucket ``train.reshard``
        chaos seam's drop, a placement failure) EVERYTHING is left
        intact — old plan, old mesh, old arrays — and the inflight
        gauge stays up (that IS the stall signal); the caller
        (``ElasticZeroTrainer.recover``) just retries."""
        import time as _t

        from ptype_tpu.metrics import annotate, metrics

        if not self.zero:
            raise ValueError(
                "StoreDPTrainer.reshard: live resharding needs the "
                "sharded ZeRO state — construct with zero=True/1/2/3 "
                "(replicated modes restart from a checkpoint instead)")
        axis = axis or self.axis
        old_n = self.n_workers
        new_n = axis_n(mesh, axis)
        t0 = _t.perf_counter()
        metrics.gauge("train.reshard_inflight").set(1.0)
        with annotate("train.reshard"):
            self._zero.reshard(mesh, axis)
            self.store.reshard(mesh, axis)
            self.mesh = mesh
            self.axis = axis
            self.n_workers = new_n
            if self.zero_stage == 3:
                for bi, flat in enumerate(self._zero.pflat):
                    self.store.commit_sharded(
                        f"params/bucket{bi:05d}", flat)
                self._params_seq = self.store.tree_seq("params")
            else:
                new_params = jax.tree_util.tree_unflatten(
                    self._treedef,
                    [jax.device_put(np.asarray(x),
                                    NamedSharding(mesh, P()))
                     for x in self._param_leaves])
                self._param_leaves = list(
                    jax.tree_util.tree_leaves(new_params))
                self._params_seq = self.store.put_tree("params",
                                                       new_params)
            self._cost_avals = None
        metrics.gauge("train.reshard_inflight").set(0.0)
        metrics.counter("train.reshards").add(1)
        return {"old_n": old_n, "new_n": new_n,
                "reshard_ms": round((_t.perf_counter() - t0) * 1e3, 2)}

    # --------------------------------------- compiled-cost accounting

    def compiled_cost(self) -> dict:
        """FLOPs/bytes per step as XLA compiled them (ISSUE 8) — the
        ``mfu_compiled`` numerator, fed to a goodput ledger via
        ``ledger.set_compiled_flops(trainer.compiled_cost()["flops"])``.

        Sums the gradient program (lowered with the layer scan fully
        unrolled so ``cost_analysis`` counts every layer — see
        :func:`ptype_tpu.health.profiling.compiled_cost`) and the
        optimizer-apply program(s) of whichever exchange mode this
        trainer runs: the whole-tree apply, the per-bucket overlap
        applies, or the ZeRO-1 shard-local applies. Requires one
        completed step (the batch avals and bucket plans come from
        it)."""
        import dataclasses

        from ptype_tpu.health import profiling

        if self._cost_avals is None:
            raise ValueError(
                "StoreDPTrainer.compiled_cost: run at least one step "
                "first (the cost programs lower against the real "
                "batch shapes)")
        params_avals, stacked_avals = self._cost_avals
        cost_cfg = dataclasses.replace(
            self.cfg, scan_unroll=max(1, self.cfg.n_layers))

        def local_grads(p, b):
            return jax.value_and_grad(tfm.loss_fn)(p, b, cost_cfg)

        programs = {"grads": profiling.compiled_cost(
            jax.jit(jax.vmap(local_grads, in_axes=(None, 0))),
            params_avals, stacked_avals)}
        if self.zero:
            programs["optimizer"] = self._zero.compiled_cost()
        elif self._apply_fns is not None:
            flops = nbytes = 0.0
            scale = jax.ShapeDtypeStruct((), jnp.float32)
            for bi, idxs in enumerate(self._buckets):
                leaves = jax.tree_util.tree_leaves(params_avals)
                subp = {str(i): leaves[i] for i in idxs}
                c = profiling.compiled_cost(
                    self._apply_fns[bi], subp, subp,
                    profiling.tree_avals(self._bucket_states[bi]),
                    scale)
                flops += c["flops"]
                nbytes += c["bytes_accessed"]
            programs["optimizer"] = {"flops": flops,
                                     "bytes_accessed": nbytes}
        elif self.opt_state is not None:
            programs["optimizer"] = profiling.compiled_cost(
                self._apply_fn, params_avals, params_avals,
                profiling.tree_avals(self.opt_state))
        w, b, s = stacked_avals["tokens"].shape
        tokens = w * b * s
        flops = sum(p["flops"] for p in programs.values())
        return {
            "flops": flops,
            "bytes_accessed": sum(p["bytes_accessed"]
                                  for p in programs.values()),
            "tokens_per_step": tokens,
            "flops_per_token": flops / tokens,
            "programs": programs,
        }

    def zero_state(self) -> ZeroState:
        """The 1/N-resident sharded optimizer state (zero=True only) —
        what checkpoint.ZeroCheckpoint saves and restores."""
        if self._zero is None:
            raise ValueError(
                "StoreDPTrainer: no ZeRO state — construct with "
                "zero=True")
        return self._zero

    # ---------------------------------------------- fine-grained overlap

    def _reduce_apply_overlapped(self, params, grads) -> None:
        """Consume the lazy bucket stream: bucket i's wait interleaves
        with bucket i+1's dispatch/commit, then the optimizer applies
        per bucket. The global-norm clip scale is a device value built
        from per-bucket partial norms — no host sync on the clip."""
        handles = []
        sub_grads = []
        sqs = []
        prev = None
        for h in self.store.push_tree_iter("grads", grads, op="mean"):
            handles.append(h)
            if self._sqnorm_fns is not None:
                bi = len(handles) - 1
                g = self._sub_grads(bi, h)
                sub_grads.append(g)
                sqs.append(self._sqnorm_fns[bi](g))
            if prev is not None:
                # Wait out the PREVIOUS bucket while this one (and its
                # partial-norm compute) is in flight — the measured
                # collective wait shrinks by exactly the overlapped
                # host+device work.
                prev.wait()
            prev = h
        if self._buckets is None:
            # First step: the bucket plan is now known — build the
            # per-bucket sub-optimizers, then redo the cheap bookkeeping.
            self._init_bucket_apply(handles)
            if self._sqnorm_fns is not None:
                sub_grads = [self._sub_grads(bi, h)
                             for bi, h in enumerate(handles)]
                sqs = [fn(g) for fn, g in
                       zip(self._sqnorm_fns, sub_grads)]
        if prev is not None:
            prev.wait()
        from ptype_tpu.metrics import annotate

        if self._custom_opt:
            # Arbitrary optimizer: whole-tree apply (streamed waits
            # above still gave the ledger its collective attribution).
            reduced = self._tree_from_handles(handles)
            with annotate("train.opt"):
                new_params, self.opt_state = self._apply_fn(
                    params, reduced, self.opt_state)
            self._param_leaves = list(
                jax.tree_util.tree_leaves(new_params))
        else:
            with annotate("train.opt"):
                scale = self._scale_fn(jnp.stack(sqs))
                for bi in range(len(handles)):
                    subp = {str(i): self._param_leaves[i]
                            for i in self._buckets[bi]}
                    newp, self._bucket_states[bi] = self._apply_fns[bi](
                        subp, sub_grads[bi], self._bucket_states[bi],
                        scale)
                    for i in self._buckets[bi]:
                        self._param_leaves[i] = newp[str(i)]
        new_params = jax.tree_util.tree_unflatten(
            self._treedef, self._param_leaves)
        self._params_seq = self.store.put_tree("params", new_params)

    def _grad_index(self, grad_key: str) -> int:
        return self._key_index[grad_key.replace("grads/", "params/", 1)]

    def _sub_grads(self, bi: int, h) -> dict:
        return {str(self._grad_index(k)): v for k, v in h.items()}

    def _tree_from_handles(self, handles):
        leaves = [None] * len(self._keys)
        for h in handles:
            for k, v in h.items():
                leaves[self._grad_index(k)] = v
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def _init_bucket_apply(self, handles) -> None:
        """Build the per-bucket optimizer machinery from the first
        step's bucket plan: each bucket gets the default AdamW recipe
        over its own param sub-tree (same schedule/decay-mask
        semantics as ``default_optimizer`` — assembled from the same
        pieces), plus a jitted partial-sqnorm fn; one jitted scale fn
        coordinates the global-norm clip across buckets."""
        self._buckets = [[self._grad_index(k) for k in h.keys]
                         for h in handles]
        if self._custom_opt:
            return
        import optax

        clip, make_inner = default_optimizer_pieces()
        mask_leaves = jax.tree_util.tree_leaves(
            _decay_mask(jax.tree_util.tree_unflatten(
                self._treedef, self._param_leaves)))
        self._bucket_states, self._apply_fns, self._sqnorm_fns = [], [], []
        for idxs in self._buckets:
            subp = {str(i): self._param_leaves[i] for i in idxs}
            inner = make_inner({str(i): mask_leaves[i] for i in idxs})
            self._bucket_states.append(inner.init(subp))

            def apply(p, g, s, scale, _inner=inner):
                g = jax.tree_util.tree_map(
                    lambda t: (t.astype(jnp.float32) * scale).astype(
                        t.dtype), g)
                updates, s = _inner.update(g, s, p)
                return optax.apply_updates(p, updates), s

            self._apply_fns.append(jax.jit(apply))
            self._sqnorm_fns.append(jax.jit(
                lambda g: sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                              for x in jax.tree_util.tree_leaves(g))))

        clip_f = float(clip)

        def scale_of(sq_stack):
            gnorm = jnp.sqrt(jnp.sum(sq_stack))
            return jnp.where(gnorm < clip_f, 1.0, clip_f / gnorm)

        self._scale_fn = jax.jit(scale_of)

    def _grad_key0(self) -> str:
        if self.zero_stage >= 2:
            # The scatter path commits per BUCKET, not per leaf (the
            # zero=1 allreduce stream commits per leaf like overlap).
            return "grads/bucket00000"
        return self._keys[0].replace("params/", "grads/", 1)


# ----------------------------------------------------------- benching


def measure_overlap(mesh: Mesh, preset: str = "tiny", steps: int = 6,
                    batch: int = 16, bucket_bytes: int = 64 * 1024,
                    compress: str | None = "int8") -> dict:
    """Collective share of store-DP step time, synchronous baseline vs
    fine-grained overlap — the bench.py ``collective_overlap_pct``
    probe and the ISSUE 6 acceptance metric. Runs the same training
    loop twice (``overlap="drain"`` then ``overlap=True``) with a
    private goodput ledger each, and reports how much of the measured
    collective leg the overlap hides."""
    from ptype_tpu.health.goodput import GoodputLedger
    from ptype_tpu.metrics import MetricsRegistry
    from ptype_tpu.parallel.collectives import WireConfig
    from ptype_tpu.train.data import synthetic_batches

    cfg = tfm.preset(preset)
    seq = min(cfg.max_seq, 128)

    def run(overlap):
        wire = WireConfig(compress=compress, bucket_bytes=bucket_bytes,
                          int8_min_bytes=0)
        store = TensorStore(mesh, wire=wire)
        trainer = StoreDPTrainer(cfg, store, overlap=overlap)
        stream = synthetic_batches(cfg.vocab_size, batch, seq)
        trainer.step(next(stream))  # compile + warm outside the ledger
        ledger = GoodputLedger(registry=MetricsRegistry()).install()
        try:
            for _ in range(steps):
                out = trainer.step(next(stream))
        finally:
            ledger.uninstall()
        assert jnp.isfinite(out["loss"])
        return ledger.summary()

    base = run("drain")
    over = run(True)
    share_base = base["collective_share_pct"]
    share_over = over["collective_share_pct"]
    return {
        "collective_share_drain_pct": round(share_base, 2),
        "collective_share_overlap_pct": round(share_over, 2),
        "collective_overlap_pct": round(
            100.0 * (1.0 - share_over / share_base), 2)
        if share_base else 0.0,
        "drain_step_ms": base["step_breakdown"]["step_ms"],
        "overlap_step_ms": over["step_breakdown"]["step_ms"],
        "steps": steps,
        "bucket_bytes": bucket_bytes,
        "compress": compress,
    }


def measure_zero(mesh: Mesh, preset: str = "tiny", steps: int = 6,
                 batch: int = 16, compress: str | None = None) -> dict:
    """Per-replica optimizer-state bytes and step time, ZeRO-1 sharded
    update vs the replicated store-DP baseline — the bench.py
    ``zero_opt_mem_mb`` / ``zero_step_ms`` probe and the ISSUE 7
    acceptance numbers. Runs the same loop twice with the same seed and
    reports measured resident bytes (``addressable_shards``, not a
    formula) plus the loss gap."""
    from ptype_tpu.parallel.collectives import WireConfig
    from ptype_tpu.train.data import synthetic_batches
    import time as _t

    cfg = tfm.preset(preset)
    seq = min(cfg.max_seq, 128)

    def opt_bytes(tree) -> int:
        total = 0
        for x in jax.tree_util.tree_leaves(tree):
            shards = getattr(x, "addressable_shards", None)
            total += (shards[0].data.nbytes if shards
                      else getattr(x, "nbytes", 0))
        return total

    def run(zero: bool):
        wire = WireConfig(compress=compress, int8_min_bytes=0)
        trainer = StoreDPTrainer(cfg, TensorStore(mesh, wire=wire),
                                 rng=jax.random.PRNGKey(0), zero=zero)
        stream = synthetic_batches(cfg.vocab_size, batch, seq, seed=5)
        trainer.step(next(stream))  # compile + warm
        t0 = _t.perf_counter()
        for _ in range(steps):
            out = trainer.step(next(stream))
        dt = (_t.perf_counter() - t0) / steps
        if zero:
            nbytes = trainer.zero_state().moment_bytes_per_replica()
        else:
            nbytes = opt_bytes(trainer.opt_state)
        return dt, nbytes, out["loss"]

    repl_dt, repl_bytes, repl_loss = run(False)
    zero_dt, zero_bytes, zero_loss = run(True)
    return {
        "zero_opt_mem_mb": round(zero_bytes / 2**20, 3),
        "repl_opt_mem_mb": round(repl_bytes / 2**20, 3),
        "opt_mem_ratio": round(repl_bytes / zero_bytes, 2)
        if zero_bytes else None,
        "zero_step_ms": round(zero_dt * 1e3, 2),
        "repl_step_ms": round(repl_dt * 1e3, 2),
        "final_loss_zero": round(float(zero_loss), 5),
        "final_loss_repl": round(float(repl_loss), 5),
        "n_replicas": axis_n(mesh, DATA_AXIS),
        "steps": steps,
        "compress": compress,
    }


def measure_zero_ladder(mesh: Mesh, preset: str = "tiny",
                        steps: int = 4, batch: int = 16) -> dict:
    """The full ladder measured (ISSUE 17): replicated baseline vs
    ZeRO-1/2/3, same seed and stream — per-replica resident bytes for
    optimizer moments, the grad reduction, and params, plus step time
    and final loss (which must match across rungs; the ladder changes
    residency, never math). Feeds ``zero2_grad_mem_mb`` /
    ``zero3_param_mem_mb`` in the bench tail and the ``make
    zero-bench`` ladder table."""
    import time as _t

    from ptype_tpu.train.data import synthetic_batches

    cfg = tfm.preset(preset)
    seq = min(cfg.max_seq, 128)
    n = axis_n(mesh, DATA_AXIS)
    rows = {}
    for stage in (0, 1, 2, 3):
        trainer = StoreDPTrainer(cfg, TensorStore(mesh),
                                 rng=jax.random.PRNGKey(0),
                                 zero=stage if stage else False)
        stream = synthetic_batches(cfg.vocab_size, batch, seq, seed=5)
        trainer.step(next(stream))  # compile + warm
        t0 = _t.perf_counter()
        for _ in range(steps):
            out = trainer.step(next(stream))
        dt = (_t.perf_counter() - t0) / steps
        if stage:
            opt_b = trainer.zero_state().moment_bytes_per_replica()
            param_b = trainer.zero_state().param_bytes_per_replica()
        else:
            opt_b = sum(
                _resident_nbytes(x) for x in
                jax.tree_util.tree_leaves(trainer.opt_state))
            param_b = 0
        if not param_b:  # replicated leaves resident (stages 0-2)
            param_b = sum(x.nbytes for x in
                          jax.tree_util.tree_leaves(trainer.params()))
        rows[f"zero{stage}" if stage else "repl"] = {
            "step_ms": round(dt * 1e3, 2),
            "opt_mem_mb": round(opt_b / 2**20, 3),
            "grad_mem_mb": round((trainer.last_grad_bytes or 0)
                                 / 2**20, 3),
            "param_mem_mb": round(param_b / 2**20, 3),
            "final_loss": round(float(out["loss"]), 5),
        }
    return {
        "ladder": rows,
        "zero2_grad_mem_mb": rows["zero2"]["grad_mem_mb"],
        "zero3_param_mem_mb": rows["zero3"]["param_mem_mb"],
        "repl_grad_mem_mb": rows["zero1"]["grad_mem_mb"],
        "repl_param_mem_mb": rows["repl"]["param_mem_mb"],
        "n_replicas": n,
        "steps": steps,
    }


def measure_reshard(preset: str = "tiny", steps: int = 3,
                    batch: int = 16, zero: int = 2) -> dict:
    """Live reshard vs the checkpoint-restore round trip it replaces
    (ISSUE 17): train on the full 8-device host mesh, shrink to 4
    survivors both ways, and report each recovery in STEP units
    (``reshard_resume_steps`` — wall time to be training again on the
    survivor set, divided by the steady step time). The live path is
    ``StoreDPTrainer.reshard`` (in memory, atomic); the baseline is
    ZeroCheckpoint + StoreCheckpoint save → fresh trainer → restore."""
    import tempfile
    import time as _t

    from ptype_tpu.checkpoint import StoreCheckpoint, ZeroCheckpoint
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.train.data import synthetic_batches

    cfg = tfm.preset(preset)
    seq = min(cfg.max_seq, 128)
    mesh8 = build_mesh({DATA_AXIS: 8})
    mesh4 = build_mesh({DATA_AXIS: 4}, devices=jax.devices()[:4])

    def trained():
        tr = StoreDPTrainer(cfg, TensorStore(mesh8),
                            rng=jax.random.PRNGKey(0), zero=zero)
        stream = synthetic_batches(cfg.vocab_size, batch, seq, seed=5)
        tr.step(next(stream))
        t0 = _t.perf_counter()
        for _ in range(steps):
            tr.step(next(stream))
        return tr, (_t.perf_counter() - t0) / steps, stream

    # Live path: reshard + the first survivor step (pays the retrace).
    tr, step_s, stream = trained()
    t0 = _t.perf_counter()
    info = tr.reshard(mesh4)
    tr.step(next(stream))
    live_s = _t.perf_counter() - t0

    # Checkpoint path on an identical twin: save, fresh trainer on
    # the survivor mesh, restore, first step.
    twin, _, stream2 = trained()
    with tempfile.TemporaryDirectory() as td:
        t0 = _t.perf_counter()
        ZeroCheckpoint(td + "/zero").save(steps, twin.zero_state())
        StoreCheckpoint(twin.store, td + "/store",
                        keys_prefix="params/").save(steps)
        fresh = StoreDPTrainer(cfg, TensorStore(mesh4),
                               rng=jax.random.PRNGKey(0), zero=zero)
        StoreCheckpoint(fresh.store, td + "/store",
                        keys_prefix="params/").resume()
        ZeroCheckpoint(td + "/zero").restore_into(fresh.zero_state())
        if zero == 3:
            for bi, flat in enumerate(fresh.zero_state().pflat):
                fresh.store.commit_sharded(
                    f"params/bucket{bi:05d}", flat)
        fresh.step(next(stream2))
        ckpt_s = _t.perf_counter() - t0

    return {
        "zero_stage": zero,
        "step_ms": round(step_s * 1e3, 2),
        "reshard_ms": info["reshard_ms"],
        "live_resume_ms": round(live_s * 1e3, 2),
        "ckpt_resume_ms": round(ckpt_s * 1e3, 2),
        "reshard_resume_steps": round(live_s / step_s, 2),
        "ckpt_resume_steps": round(ckpt_s / step_s, 2),
        "resume_speedup": round(ckpt_s / live_s, 2),
    }
