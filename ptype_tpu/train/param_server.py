"""Async param-server training — Store put/get WITHOUT a barrier.

The BASELINE.json config "BERT-base async param-server mode (Store
push/pull, no allreduce)" is the reference's Store used in its raw form:
``Put``/``Get`` with no ordering between writers beyond raft
linearizability (cluster/store.go:38-62). Here:

- The **server** owns the canonical parameters in a :class:`TensorStore`
  namespace and applies gradient pushes as they arrive — no barrier, no
  allreduce; each push is an optimizer step (Hogwild/Downpour-style).
- **Workers** ``pull`` a (possibly stale) parameter snapshot, compute
  grads on their own batch, and ``push`` them back. A staleness bound
  rejects pushes computed against parameters more than
  ``max_staleness`` versions old — the knob the reference never had
  (its writers could never be stale: raft serialized them).

The server's methods are plain callables, so it drops straight into an
:class:`ptype_tpu.actor.ActorServer` (``register(ParamServer(...),
"ParamServer")``) — the multi-host deployment is workers calling
``ParamServer.Push``/``ParamServer.Pull`` over the balanced RPC client,
payloads riding the tensor codec.
"""

from __future__ import annotations

import threading
from typing import Any

import jax

from ptype_tpu.models import transformer as tfm
from ptype_tpu.parallel import collectives
from ptype_tpu.parallel.tensorstore import TensorStore
from ptype_tpu.train.trainer import default_optimizer, make_apply_fn


class StalePushError(Exception):
    """Grad push rejected: computed against too-old parameters."""


def _is_stale(e: Exception) -> bool:
    """True for a staleness rejection, local or remote. Over the actor
    wire the server's StalePushError arrives as a RemoteError carrying
    the exception name (actor.py error serialization) — the worker must
    treat both forms as the same recoverable signal."""
    return isinstance(e, StalePushError) or "StalePushError" in str(e)


class ParamServer:
    """Canonical parameter owner; applies async gradient pushes.

    Thread-safe: concurrent worker pushes serialize on a lock (the
    in-process analog of the reference Store serializing writes through
    the raft leader).
    """

    def __init__(self, cfg: tfm.TransformerConfig, store: TensorStore,
                 optimizer=None, rng: jax.Array | None = None,
                 max_staleness: int = 8,
                 wire: collectives.WireConfig | None = None):
        self.cfg = cfg
        self.store = store
        self.optimizer = optimizer or default_optimizer()
        self.max_staleness = max_staleness
        #: Wire policy for grad pushes over the RPC tier: when int8,
        #: Push accepts block-scaled quantized trees
        #: (collectives.quantize_tree — ≈4× fewer TCP bytes) and
        #: dequantizes before the optimizer. Defaults to the store's
        #: wire, so one config covers collective AND RPC gradients.
        self.wire = wire if wire is not None else store.wire
        self._quantized = 0
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        params = jax.jit(lambda r: tfm.init_params(r, cfg))(rng)
        self._params = params
        self._opt_state = self.optimizer.init(params)
        self._version = 0
        self._applied = 0
        self._rejected = 0
        self._lock = threading.Lock()
        self._treedef = jax.tree_util.tree_structure(params)
        self.store.put_tree("params", params)

        self._apply_fn = make_apply_fn(self.optimizer)

    # Methods are Capitalized where they form the actor RPC surface
    # (net/rpc naming, ref calculator.go:9-12).

    def Pull(self) -> dict:
        """Parameter snapshot + its version (the un-barriered Get)."""
        with self._lock:
            return {"params": self._params, "version": self._version}

    def Push(self, grads: Any, version: int) -> dict:
        """Apply one worker's grads (the un-barriered Put). ``version``
        is the parameter version the grads were computed against.
        ``grads`` may be a plain pytree or a quantized wire tree
        (:func:`collectives.quantize_tree`) — the worker opted into
        the int8 RPC wire; the server reassembles against its own
        parameter structure."""
        quantized = collectives.is_quantized_tree(grads)
        if quantized:
            # Staleness needs only the version integer — reject BEFORE
            # paying the full-tree dequant (rejections cluster exactly
            # when the server is hot). The authoritative check re-runs
            # under the lock below; the version only grows, so this
            # early verdict can never un-reject.
            with self._lock:
                self._check_staleness_locked(version)
            grads = collectives.dequantize_tree(grads, self._treedef)
        with self._lock:
            staleness = self._check_staleness_locked(version)
            self._params, self._opt_state = self._apply_fn(
                self._params, grads, self._opt_state
            )
            self._version += 1
            self._applied += 1
            if quantized:  # count APPLIED quantized pushes only —
                self._quantized += 1  # rejected ones never trained
            return {"version": self._version, "staleness": staleness}

    def _check_staleness_locked(self, version: int) -> int:
        """Raise (and count) when ``version`` is too far behind;
        callers hold the lock. Returns the staleness."""
        staleness = self._version - int(version)
        if staleness > self.max_staleness:
            self._rejected += 1
            raise StalePushError(
                f"push at version {version} is {staleness} behind "
                f"(max_staleness={self.max_staleness})"
            )
        return staleness

    def Sync(self) -> dict:
        """Publish current params into the TensorStore namespace (for
        checkpointers / late joiners reading the manifest). Rides the
        bucketed tree path: put_tree dispatches every leaf's placement
        through one batched device_put, so a Sync under the push lock
        stalls concurrent workers for one dispatch, not one per leaf."""
        with self._lock:
            self.store.put_tree("params", self._params)
            return {"version": self._version}

    def Stats(self) -> dict:
        with self._lock:
            return {
                "version": self._version,
                "applied": self._applied,
                "rejected": self._rejected,
                "quantized": self._quantized,
                "wire": self.wire.compress,
            }


class AsyncWorker:
    """Pull → local grads → push, against a ParamServer-shaped peer.

    ``server`` is anything exposing Pull/Push — the in-process object or
    a balanced RPC client proxy (``client.call("ParamServer.Pull")``).
    """

    def __init__(self, cfg: tfm.TransformerConfig, server, worker_id: int = 0,
                 wire: collectives.WireConfig | None = None):
        self.cfg = cfg
        self.server = server
        self.worker_id = worker_id
        self.steps = 0
        self.stale_rejections = 0
        #: Int8 wire for the grad push over RPC: block-scaled
        #: quantization with a local error-feedback residual per leaf
        #: (same EF contract as the collective wire — the quantization
        #: error rides into the NEXT push instead of accumulating).
        #: Only int8 is implemented on this tier — reject other
        #: compressions loudly rather than silently pushing raw fp32.
        if wire is not None and wire.compress not in (None, "int8"):
            raise ValueError(
                f"AsyncWorker: wire compress {wire.compress!r} is not "
                f"implemented on the RPC tier (use 'int8' or None)")
        self.wire = wire
        self._residuals: list | None = None
        self._grads_fn = jax.jit(
            lambda params, batch: jax.value_and_grad(tfm.loss_fn)(
                params, batch, cfg
            )
        )

    def step(self, batch: dict) -> dict:
        snap = self.server.Pull()
        loss, grads = self._grads_fn(snap["params"], batch)
        prev_residuals = self._residuals
        if self.wire is not None and self.wire.compress == "int8":
            grads, res = collectives.quantize_tree(
                grads, self.wire.q_block,
                self._residuals if self.wire.error_feedback else None,
                want_residuals=self.wire.error_feedback)
            if self.wire.error_feedback:
                self._residuals = res
        try:
            out = self.server.Push(grads, snap["version"])
        except Exception as e:  # noqa: BLE001 — see _is_stale
            # ANY failed push dropped the wire that carried the
            # accumulated EF error — restore the pre-push residual
            # (stale rejections AND transport faults alike) or the
            # carryover degrades to naive per-step quantization under
            # exactly the churn that produces failures.
            self._residuals = prev_residuals
            if not _is_stale(e):
                raise
            self.stale_rejections += 1
            return {"loss": float(loss), "applied": False,
                    "worker": self.worker_id}
        self.steps += 1
        return {"loss": float(loss), "applied": True,
                "version": out["version"], "staleness": out["staleness"],
                "worker": self.worker_id}

    def run(self, batches, n_steps: int) -> list[dict]:
        return [self.step(next(batches)) for _ in range(n_steps)]
