"""Deterministic fault injection — the chaos layer.

The recovery machinery (rpc retries, coordinator failover, elastic
reshard, checkpoint fallback) used to be tested with one hand-rolled
fault per test. This module makes fault injection a first-class
subsystem: a seeded :class:`FaultPlan` (a schedule of
:class:`FaultSpec`: what to inject, where, when, how many times) is
armed process-wide, and narrow hooks compiled into the real seams fire
it. Every firing and every observed recovery lands in a trace the test
asserts against.

Injection sites (the seams that call :func:`hit`):

======================  =====================================================
site                    actions
======================  =====================================================
``rpc.dial``            ``drop`` / ``timeout`` / ``delay`` (rpc.py `_dial`)
``rpc.send``            ``drop`` / ``truncate`` / ``delay`` (socket send)
``rpc.recv``            ``delay`` — slow reply (rpc.py read loop)
``coord.wire_send``     ``drop`` / ``truncate`` / ``delay`` (coord/wire.py)
``coord.wire_recv``     ``drop`` / ``delay`` (coord/wire.py)
``coord.keepalive``     ``revoke`` — lease-revoke a member (coord/core.py)
``coord.wal_append``    ``delay`` — wedge the primary so a standby promotes
``coord.put``           ``kill_primary`` — die mid-write (coord/service.py)
``store.push``          ``delay`` (straggler) / ``timeout``
``store.pull``          ``delay`` (straggler)
``checkpoint.commit``   ``crash`` — between shard write and manifest commit
``checkpoint.shard``    ``corrupt`` — flip bytes in one shard on disk
``gateway.admit``       ``shed`` (force-refuse) / ``delay`` (gateway/admission)
``gateway.route``       ``drop`` (veto the picked replica) / ``delay``
``gateway.probe``       ``drop`` / ``timeout`` / ``delay`` (gateway/pool)
``serve.admit``         ``shed`` (typed ShedError + retry_after, the
                        pool-exhausted path) / ``delay`` (serve_engine)
``serve.spec``          ``reject`` (poison a speculation window — that
                        iteration falls back to the plain decode step:
                        correct tokens, just slower) / ``delay`` (stall
                        the draft forward) (serve_engine)
``serve.migrate``       ``drop`` (abort the KV-block transfer outright) /
                        ``delay`` (stall it mid-flight) / ``truncate``
                        (ship a wire missing blocks — the decode side
                        detects the short manifest and refuses it). All
                        three land on the same recovery: the request
                        falls back to local prefill on the decode
                        replica — correct tokens, never lost
                        (gateway/frontdoor `_dispatch_disagg`)
``scale.spawn``         ``fail`` (the replica process/host dies before it
                        comes up — the reconciler retries next tick) /
                        ``delay`` (slow spawn) (reconciler/replica.py)
``scale.drain``         ``wedge`` (hold a drain open past ``delay_s`` —
                        drive it past its deadline so the reconciler's
                        escalation path fires) / ``delay``
                        (reconciler/replica.py)
``train.reshard``       ``drop`` (abort the live reshard mid-move — the
                        atomic swap means the OLD plan/mesh/arrays are
                        fully intact and the caller retries:
                        ``ElasticZeroTrainer.recover``) / ``delay`` /
                        ``wedge`` (stall one bucket's re-place — drives
                        the ``reshard-stall`` health rule)
                        (parallel/zero.py ``ZeroState.reshard``; keyed
                        by ``bucketNNNNN``)
``loadgen.issue``       ``drop`` (swallow one scheduled arrival — the
                        trace records a ``dropped`` outcome and
                        goodput accounts it) / ``delay`` (stall the
                        issue — a wedged driver host; surfaces as
                        ``loadgen.overrun`` + issue lag, never as a
                        silent closed-loop wait). Keyed by arrival
                        ``seq``; answered requests pair the recovery,
                        so traffic replay composes with the chaos
                        soak (loadgen/driver.py)
======================  =====================================================

Zero-cost contract: every seam calls ``chaos.hit(site, key)``, which is
a single attribute load + ``None`` check when no plan is armed — no
locks, no allocation. Arm per-test with :func:`arm` / the
:class:`armed` context manager, or set ``PTYPE_CHAOS_PLAN`` (inline
JSON or a path to a JSON file) so multiprocess workers arm themselves
at import.

Recovery pairing: seams report health on their success paths via
:func:`note_ok` ("an rpc call completed", "a coord op was served", "a
checkpoint committed"). A note is recorded in the trace only while a
fault of the same class (the site prefix before the first dot) is
outstanding, so :func:`unrecovered` returning ``{}`` means every
injected fault was followed by a successful operation of its class —
the soak harness's no-wedge invariant.

This module imports only the stdlib (the seams it hooks include the
lowest layers of the package; it must never create an import cycle).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import asdict, dataclass

__all__ = [
    "FaultSpec", "FaultPlan", "FaultEvent", "Fault",
    "arm", "disarm", "current", "armed", "pause", "resume",
    "hit", "note_ok", "trace", "fired", "unrecovered",
    "set_observer",
]

#: Env var carrying a plan for workers spawned as separate processes:
#: inline JSON, or a path to a JSON file (handy for shells).
PLAN_ENV = "PTYPE_CHAOS_PLAN"


@dataclass
class FaultSpec:
    """One scheduled fault: fire ``action`` at ``site`` on the
    ``after+1``-th matching pass, ``times`` times in a row."""

    site: str
    action: str
    #: Substring filter on the seam-provided key (node address, wire
    #: op, store key, shard filename ...). Empty matches everything.
    match: str = ""
    #: Matching passes to skip before the first firing.
    after: int = 0
    #: Consecutive matching passes that fire (then the spec is spent).
    times: int = 1
    #: Sleep length for ``delay`` actions.
    delay_s: float = 0.05


@dataclass
class FaultEvent:
    """One trace entry — an injected fault or an observed recovery."""

    seq: int
    kind: str  # "fault" | "recovery"
    site: str
    action: str
    key: str
    t: float


class Fault:
    """What a seam gets back from :func:`hit` when a spec fires."""

    __slots__ = ("spec",)

    def __init__(self, spec: FaultSpec):
        self.spec = spec

    @property
    def action(self) -> str:
        return self.spec.action

    @property
    def delay_s(self) -> float:
        return self.spec.delay_s

    def sleep(self) -> None:
        time.sleep(self.spec.delay_s)

    def __repr__(self) -> str:  # shows up in seam error messages
        return f"Fault({self.spec.site}:{self.spec.action})"


def _cls(site: str) -> str:
    """Fault class = site prefix: ``rpc`` / ``coord`` / ``store`` /
    ``checkpoint`` — the granularity recovery pairing runs at."""
    return site.split(".", 1)[0]


class FaultPlan:
    """A seeded, replayable schedule of faults plus its firing trace.

    The plan object owns all mutable chaos state (counters, trace,
    outstanding-fault ledger) under one lock, so arming a fresh plan
    fully resets the world and a test can hold the plan after
    :func:`disarm` to inspect what happened.
    """

    def __init__(self, specs: list[FaultSpec], seed: int | None = None,
                 name: str = "plan"):
        self.specs = list(specs)
        self.seed = seed
        self.name = name
        self._lock = threading.Lock()
        self._seen = [0] * len(self.specs)
        self._fired = [0] * len(self.specs)
        self._trace: list[FaultEvent] = []
        self._pending: dict[str, int] = {}
        self._t0 = time.monotonic()

    # ------------------------------------------------------ construction

    @classmethod
    def random(cls, seed: int, menu: list[dict],
               n_faults: int = 8, name: str | None = None) -> "FaultPlan":
        """Deterministic random schedule: ``n_faults`` draws from
        ``menu``. Each menu entry is a dict with ``site``/``action``
        and optional ``match``, plus ``(lo, hi)`` ranges for ``after``,
        ``times`` and ``delay_s``. Same seed + same menu = identical
        specs, which is what makes a failing soak replayable."""
        rng = random.Random(seed)
        specs = []
        for _ in range(n_faults):
            m = rng.choice(menu)
            lo, hi = m.get("after", (0, 10))
            tl, th = m.get("times", (1, 1))
            dl, dh = m.get("delay_s", (0.01, 0.05))
            specs.append(FaultSpec(
                site=m["site"], action=m["action"],
                match=m.get("match", ""),
                after=rng.randint(lo, hi),
                times=rng.randint(tl, th),
                delay_s=round(rng.uniform(dl, dh), 4),
            ))
        return cls(specs, seed=seed, name=name or f"random-{seed}")

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed,
            "specs": [asdict(s) for s in self.specs],
        }, separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        d = json.loads(raw)
        return cls([FaultSpec(**s) for s in d["specs"]],
                   seed=d.get("seed"), name=d.get("name", "plan"))

    # ----------------------------------------------------------- firing

    def _hit(self, site: str, key: str) -> Fault | None:
        with self._lock:
            winner: FaultSpec | None = None
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if spec.match and spec.match not in key:
                    continue
                self._seen[i] += 1
                if (winner is None
                        and self._seen[i] > spec.after
                        and self._fired[i] < spec.times):
                    # At most one spec fires per pass, but every
                    # matching spec still counts the pass — schedules
                    # stay deterministic whichever spec wins.
                    self._fired[i] += 1
                    winner = spec
            if winner is None:
                return None
            self._record("fault", site, winner.action, key)
            self._pending[_cls(site)] = self._pending.get(_cls(site), 0) + 1
            return Fault(winner)

    def _note_ok(self, site: str, key: str) -> bool:
        """Returns True when a recovery was recorded (a fault of this
        class was outstanding) — the module-level beacon forwards those
        to the trace observer."""
        with self._lock:
            c = _cls(site)
            if self._pending.get(c, 0) <= 0:
                return False
            self._pending[c] -= 1
            self._record("recovery", site, "ok", key)
            return True

    def _record(self, kind: str, site: str, action: str, key: str) -> None:
        self._trace.append(FaultEvent(
            seq=len(self._trace), kind=kind, site=site, action=action,
            key=key, t=round(time.monotonic() - self._t0, 4)))

    # ------------------------------------------------------- inspection

    def trace(self) -> list[FaultEvent]:
        with self._lock:
            return list(self._trace)

    def fired(self) -> list[FaultEvent]:
        """Injected faults only, in firing order."""
        return [e for e in self.trace() if e.kind == "fault"]

    def unrecovered(self) -> dict[str, int]:
        """Fault classes with more injections than subsequent
        successes — ``{}`` is the soak harness's paired invariant."""
        with self._lock:
            return {c: n for c, n in self._pending.items() if n > 0}

    def exhausted(self) -> bool:
        """True once every spec has fired all its times."""
        with self._lock:
            return all(f >= s.times for s, f in zip(self.specs, self._fired))


# -------------------------------------------------------------- module API

_plan: FaultPlan | None = None
_paused: bool = False
#: Optional ``cb(kind, site, action, key)`` notified on every recorded
#: firing/recovery OUTSIDE the plan lock — how the trace plane
#: (ptype_tpu.trace) attaches chaos events to the afflicted request's
#: span without this module importing anything above the stdlib.
_observer = None


def set_observer(cb) -> None:
    """Install (or clear, with None) the firing/recovery observer."""
    global _observer
    _observer = cb


def _notify(kind: str, site: str, action: str, key: str) -> None:
    obs = _observer
    if obs is None:
        return
    try:
        obs(kind, site, action, key)
    except Exception:  # noqa: BLE001 — observers must never break a seam
        pass


def arm(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide (replaces any armed plan)."""
    global _plan, _paused
    _paused = False
    _plan = plan
    return plan


def disarm() -> None:
    global _plan, _paused
    _plan = None
    _paused = False


def current() -> FaultPlan | None:
    return _plan


def pause() -> None:
    """Stop injecting but keep recording recoveries — the drain phase
    of a soak (outstanding faults can still be paired)."""
    global _paused
    _paused = True


def resume() -> None:
    global _paused
    _paused = False


class armed:
    """``with chaos.armed(plan):`` — arm for a scope, always disarm."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return arm(self.plan)

    def __exit__(self, *exc) -> None:
        disarm()


def hit(site: str, key: str = "") -> Fault | None:
    """The seam hook: returns the Fault to inject, or None (the
    overwhelmingly common case — one load + compare when disarmed)."""
    plan = _plan
    if plan is None or _paused:
        return None
    f = plan._hit(site, key)
    if f is not None:
        _notify("fault", site, f.action, key)
    return f


def note_ok(site: str, key: str = "") -> None:
    """Success-path beacon: records a recovery if a fault of this
    site's class is outstanding; free no-op otherwise."""
    plan = _plan
    if plan is not None and plan._note_ok(site, key):
        _notify("recovery", site, "ok", key)


def trace() -> list[FaultEvent]:
    plan = _plan
    return plan.trace() if plan is not None else []


def fired() -> list[FaultEvent]:
    plan = _plan
    return plan.fired() if plan is not None else []


def unrecovered() -> dict[str, int]:
    plan = _plan
    return plan.unrecovered() if plan is not None else {}


def _maybe_arm_from_env() -> None:
    """Arm from ``PTYPE_CHAOS_PLAN`` (inline JSON or a file path) —
    how subprocess workers join a drill without code changes."""
    raw = os.environ.get(PLAN_ENV)
    if not raw or _plan is not None:
        return
    if os.path.exists(raw):
        with open(raw, encoding="utf-8") as f:
            raw = f.read()
    arm(FaultPlan.from_json(raw))


_maybe_arm_from_env()
